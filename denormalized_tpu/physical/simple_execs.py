"""Source / projection / filter / sink operators (host-side, vectorized).

These mirror the reference's ``DenormalizedStreamingTableExec``
(stream_table.rs:71-275) and the DataFusion projection/filter/sink nodes its
plans contain.  They are deliberately thin: all heavy compute lives in the
windowed operator's device step, and these nodes just move batch references
and run vectorized numpy expression kernels.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Iterator

import numpy as np

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical.expr import Expr
from denormalized_tpu.physical.base import (
    EOS,
    WM_ANNOUNCE,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)
from denormalized_tpu.sources.base import Source


#: per-process ordinal per source NAME: two sources sharing a name (the
#: bench join runs two default-named MemorySources) must not share metric
#: series — the registry dedups by (name, labels), and a shared gauge /
#: gauge_fn would oscillate between (or drop) the two owners.  The first
#: claimant of a name keeps it bare; later ones get ``name#2``, ``#3``...
#: (deterministic in plan-build order, so a restarted identical query
#: maps to the same series within one process run).
_SOURCE_SERIES_ORDINALS: dict[str, int] = {}


def _source_series_label(name: str) -> str:
    n = _SOURCE_SERIES_ORDINALS.get(name, 0) + 1
    _SOURCE_SERIES_ORDINALS[name] = n
    return name if n == 1 else f"{name}#{n}"


class _IdleTracker:
    """Idle-source detection shared by both SourceExec drive loops: rows
    re-arm it; after ``timeout_ms`` without rows it yields ONE
    WatermarkHint at the max canonical timestamp seen.

    ``quiet`` (optional) is a reader-side gate: the hint carries the
    GLOBAL max timestamp, so on the multi-partition prefetch path it
    must never fire while any partition still has rows enqueued or
    known backlog at the broker — the consumer-side clock alone reads
    "idle" after any long consumer stall (first-batch compile, GC) even
    though the stalled period's batches are sitting in the queue, and
    the resulting hint would close windows the slower partition still
    owes rows to (the same soak-found failure family as the
    partition-watermark activity guard, see ``_PartitionWatermarks``)."""

    def __init__(self, timeout_ms: int, quiet: Callable[[], bool] | None = None) -> None:
        self.timeout_ms = timeout_ms
        self._last_rows_wall = time.monotonic()
        self._max_ts: int | None = None
        self._sent = False
        self._quiet = quiet

    def observe_rows(self, batch: RecordBatch) -> None:
        from denormalized_tpu.common.constants import (
            CANONICAL_TIMESTAMP_COLUMN,
        )

        self._last_rows_wall = time.monotonic()
        self._sent = False
        bmax = int(
            np.max(
                np.asarray(
                    batch.column(CANONICAL_TIMESTAMP_COLUMN),
                    dtype=np.int64,
                )
            )
        )
        if self._max_ts is None or bmax > self._max_ts:
            self._max_ts = bmax

    def maybe_hint(self) -> WatermarkHint | None:
        if (
            self._sent
            or self._max_ts is None
            or (time.monotonic() - self._last_rows_wall) * 1000
            < self.timeout_ms
        ):
            return None
        if self._quiet is not None and not self._quiet():
            return None
        self._sent = True
        return WatermarkHint(self._max_ts)


class _PartitionWatermarks:
    """Per-partition watermark aggregation: the source-level watermark is
    the MIN over each partition's own max-of-batch-min-ts.  The merged
    stream's legacy rule (operator watermark = global max of batch
    min-ts) races ahead on whichever partition drains fastest — during
    replay/catch-up that drops the slower partitions' entire backlog as
    late.  Exclusions from the min:

    - finished partitions (bounded EOS or a dead unbounded reader): their
      constraint lifts permanently;
    - partitions idle past ``timeout_ms`` (Flink-style idleness) — they
      re-enter on new rows, and the monotonic emission guard means a
      resumed partition's OLD rows may drop late, exactly as if idleness
      had been declared by the idle-hint machinery.

    ``observe``/``advance`` return a kind="partition" WatermarkHint only
    when the min strictly advances."""

    #: first-read hold bound, as a multiple of the idle timeout: a reader
    #: that still hasn't RETURNED from its first read after this long
    #: stops holding the watermark and falls back to idle exclusion —
    #: a reader wedged in connect/seek must not stall the stream forever.
    #: The residual hazard (a reader legitimately IN its first read that
    #: long whose eventual rows then drop late) is documented in
    #: docs/watermarks.md.
    FIRST_READ_GRACE_MULT = 4

    def __init__(self, n: int, timeout_ms: int | None, activity=None) -> None:
        self._wm: list[int | None] = [None] * n
        self._last_rows = [time.monotonic()] * n
        self._finished = [False] * n
        self._timeout_s = (
            timeout_ms / 1000.0 if timeout_ms is not None else None
        )
        self._emitted: int | None = None
        self._born = time.monotonic()
        # activity(idx) -> (has_pending, last_rowful_produce_wall,
        # first_read_done[, may_judge_idle]): on the threaded path
        # idleness must be judged by what the READER produced, not by
        # when the consumer got around to processing it — a burst of one
        # partition's catch-up batches ahead in the SHARED queue
        # otherwise makes the other partition look idle while its
        # backlog is already enqueued, excludes it from the min, and
        # late-drops that backlog (soak-found: a contiguous slice of the
        # first window after a kill/restore vanished whenever the
        # consumer spent >idle_timeout on one partition's run of queued
        # batches).  first_read_done separates "quiet topic" from "still
        # starting": a reader that has not yet RETURNED from its first
        # read (connect/seek/fetch in flight, possibly starved by a
        # compiling consumer on a shared core) holds the min — its
        # initial backlog is unknown, not absent (soak-found at stream
        # start: window 0 short by the slower-connecting partition's
        # share under first-batch compile).  may_judge_idle extends the
        # same reasoning to a reader that KNOWS it has broker-side
        # backlog (PartitionReader.caught_up() is False): a partition
        # mid-way through a large catch-up fetch/decode has nothing
        # enqueued and a stale produce stamp, yet idle-excluding it
        # late-drops the very rows that fetch is carrying.
        self._activity = activity

    def observe(self, idx: int, batch: RecordBatch) -> WatermarkHint | None:
        from denormalized_tpu.common.constants import (
            CANONICAL_TIMESTAMP_COLUMN,
        )

        bmin = int(
            np.min(
                np.asarray(
                    batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
                )
            )
        )
        if self._wm[idx] is None or bmin > self._wm[idx]:
            self._wm[idx] = bmin
        self._last_rows[idx] = time.monotonic()
        return self.advance()

    def finish(self, idx: int) -> WatermarkHint | None:
        self._finished[idx] = True
        return self.advance()

    def advance(self) -> WatermarkHint | None:
        now = time.monotonic()
        vals = []
        for i, (w, lr, fin) in enumerate(
            zip(self._wm, self._last_rows, self._finished)
        ):
            if fin:
                continue
            if self._activity is not None:
                act = self._activity(i)
                pending, produced, first_read_done = act[0], act[1], act[2]
                may_judge_idle = act[3] if len(act) > 3 else True
                if not first_read_done:
                    # still starting: backlog unknown, hold — but only up
                    # to a bounded multiple of the idle timeout; past it
                    # the stuck reader is excluded like an idle one
                    if self._timeout_s is None or (
                        now - self._born
                        < self.FIRST_READ_GRACE_MULT * self._timeout_s
                    ):
                        return None
                    continue
                lr = max(lr, produced)
                if pending or not may_judge_idle:
                    # enqueued-but-unprocessed rows, or reader-reported
                    # broker backlog (catch-up fetch in flight): never idle
                    lr = now
            idle = (
                self._timeout_s is not None
                and now - lr >= self._timeout_s
            )
            if w is None:
                if idle:
                    continue  # never-produced idle partition: excluded
                return None  # a live partition hasn't spoken yet
            if idle:
                continue
            vals.append(w)
        if not vals:
            return None
        m = min(vals)
        if self._emitted is None or m > self._emitted:
            self._emitted = m
            return WatermarkHint(m, kind="partition")
        return None


class SourceExec(ExecOperator):
    """Leaf operator: drives every partition of a source and merges their
    batches into one ordered stream.

    The reference spawns one tokio task per Kafka partition feeding an mpsc
    channel (kafka_stream_read.rs:87-298); bounded sources here just
    round-robin in-thread, while unbounded sources get one reader thread per
    partition feeding a queue (the same shape, sized like the reference's
    RecordBatchReceiverStreamBuilder).  Checkpoint barriers are injected
    in-band between batches when an orchestrator is attached.
    """

    def __init__(
        self,
        source: Source,
        *,
        queue_size: int = 64,
        idle_timeout_ms: int | None = None,
        partition_watermarks: bool | str = "auto",
    ) -> None:
        self.source = source
        self.schema = source.schema
        self._queue_size = queue_size
        self._idle_timeout_ms = idle_timeout_ms
        self._partition_watermarks = partition_watermarks
        self._barrier_poll: Callable[[], int | None] | None = None
        self._metrics = {"rows_out": 0, "batches_out": 0}
        self._readers: list | None = None
        self._yielded_offsets: list | None = None
        self._ckpt = None  # (CheckpointCoordinator, node_id)
        self._pump = None  # live prefetch pump (supervisor metrics)
        import weakref

        from denormalized_tpu import obs

        # the registry this operator was BUILT under: run-time binds
        # (pump workers, reconstructed kafka readers) must land in the
        # same query-scoped registry regardless of which thread drives
        # the generator or when a supervised rebuild happens
        self._obs_reg = obs.current_registry()
        # collision-free series label (see _source_series_label): two
        # same-named sources in one plan get distinct series
        self._obs_source_label = _source_series_label(str(source.name))
        self._obs_rows_out = obs.counter(
            "dnz_op_rows_out_total", op="source",
            source=self._obs_source_label,
        )
        # registry view of the ad-hoc decode-fallback counter: the
        # authoritative count stays on the readers/pump (see metrics()),
        # the gauge reads it at export time.  Weakref, not self — the
        # process-global registry must not pin a finished query's
        # operator graph (pump, readers, buffers) in memory forever.
        ref = weakref.ref(self)
        obs.gauge_fn(
            "dnz_decode_fallback_rows",
            lambda: (
                op.metrics().get("decode_fallback_rows", 0)
                if (op := ref()) is not None else 0
            ),
            source=self._obs_source_label,
        )

    def set_barrier_source(self, poll: Callable[[], int | None]) -> None:
        self._barrier_poll = poll

    # -- checkpointing (offset persistence mirrors BatchReadMetadata,
    # kafka_stream_read.rs:49-65,275-289; restore :110-140) -------------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        from denormalized_tpu.state.checkpoint import make_barrier_poll

        self._ckpt = (coord, node_id)
        channel = orch.register(f"src_{node_id}")
        base_poll = make_barrier_poll(channel)

        def poll():
            epoch = base_poll()
            if epoch is not None:
                self._persist_offsets(epoch)
            return epoch

        self._barrier_poll = poll

    def enable_cluster_checkpointing(
        self, node_id: str, coord, poll_epoch: Callable[[], int | None]
    ) -> None:
        """Cluster-mode wiring (cluster/worker.py): barriers come from
        the coordinator's control channel instead of a local
        Orchestrator — same in-band injection, same offset persistence,
        but the epoch NUMBER is cluster-global so every worker's cut
        shares one key suffix."""
        self._ckpt = (coord, node_id)

        def poll():
            epoch = poll_epoch()
            if epoch is not None:
                self._persist_offsets(epoch)
            return epoch

        self._barrier_poll = poll

    def persist_final_offsets(self, epoch: int) -> None:
        """Persist the (final) yielded offsets for ``epoch`` OUTSIDE the
        stream — cluster workers call this when a barrier lands after
        this source already reached EOS, so the cluster cut still
        records every partition at its end position instead of omitting
        the finished worker (which would replay its whole subset on
        restore)."""
        self._persist_offsets(epoch)

    def _persist_offsets(self, epoch: int) -> None:
        from denormalized_tpu.state.checkpoint import put_json

        if self._ckpt is None or self._yielded_offsets is None:
            return
        coord, node_id = self._ckpt
        # offsets of batches actually YIELDED downstream — in the threaded
        # path reader positions race ahead (prefetched batches still sit in
        # the queue), so the barrier must not persist live reader state
        put_json(
            coord,
            f"offsets_{node_id}",
            epoch,
            {"epoch": epoch, "partitions": list(self._yielded_offsets)},
        )

    def _restore_offsets(self, readers) -> None:
        from denormalized_tpu.common.errors import StateError
        from denormalized_tpu.state.checkpoint import get_json

        if self._ckpt is None:
            return
        coord, node_id = self._ckpt
        snap = get_json(coord, f"offsets_{node_id}")
        if snap is None:
            return
        parts = snap.get("partitions", [])
        if len(parts) != len(readers):
            raise StateError(
                f"checkpoint has {len(parts)} partitions but source "
                f"{self.source.name!r} now has {len(readers)} — partition "
                "layout must match across restarts"
            )
        for r, s in zip(readers, parts):
            r.offset_restore(s)

    def metrics(self):
        m = dict(self._metrics)
        # per-partition Python-decode fallback counts, aggregated: a
        # schema shape that silently routes to the ~30x-slower Python
        # decoder must be observable, not a quiet perf cliff.  Reading an
        # int attribute across the prefetch worker threads is safe.
        # read the pump's CURRENT readers when it exists: a supervised
        # restart swaps the worker's reader, and the pre-crash list would
        # silently freeze this count at the crash point.  Retired
        # readers' counts are carried on the worker so a restart never
        # RESETS the perf-cliff metric either.
        if self._pump is not None:
            m["decode_fallback_rows"] = sum(
                w.decode_fallback_total() for w in self._pump.workers
            )
            # poison records skipped by salvage decode (silent data
            # loss, now operator-visible) — soak reports read this
            m["salvaged_rows"] = sum(
                w.salvaged_total() for w in self._pump.workers
            )
            # supervisor restart state: how many worker crashes this
            # source absorbed (and where), so a flapping partition is
            # visible even when every restart succeeded
            rs = self._pump.restart_stats()
            m["prefetch_restarts"] = rs["restarts"]
            m["prefetch_restarted_partitions"] = rs["restarted_partitions"]
            if rs["last_errors"]:
                m["prefetch_last_errors"] = dict(rs["last_errors"])
        else:
            m["decode_fallback_rows"] = sum(
                r.decode_fallback_rows() for r in (self._readers or [])
            )
            m["salvaged_rows"] = sum(
                int(getattr(r, "salvaged_rows", 0) or 0)
                for r in (self._readers or [])
            )
        return m

    def _label(self):
        return f"SourceExec({self.source.name})"

    def _maybe_barrier(self) -> Iterator[StreamItem]:
        if self._barrier_poll is not None:
            epoch = self._barrier_poll()
            if epoch is not None:
                yield Marker(epoch)

    def _partition_wm_tracker(self, n_readers: int, activity=None):
        """Resolve partition-watermark mode: 'auto' enables it for any
        multi-partition source whose liveness is guaranteed — bounded
        (finished partitions leave the min) or unbounded WITH an idle
        timeout (quiet partitions leave the min).  An unbounded source
        with no idleness policy keeps legacy max-of-min semantics: a
        silent partition would otherwise stall the watermark forever."""
        on = self._partition_watermarks is True or (
            self._partition_watermarks == "auto"
            and n_readers > 1
            and (
                not self.source.unbounded
                or self._idle_timeout_ms is not None
            )
        )
        if not on:
            return None
        return _PartitionWatermarks(
            n_readers, self._idle_timeout_ms, activity=activity
        )

    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu import obs

        # reader construction binds instruments (kafka consumer-lag
        # gauges): scope the binds to this operator's captured registry
        with obs.bound_registry(self._obs_reg):
            readers = self.source.partitions()
        self._readers = readers
        self._restore_offsets(readers)
        self._yielded_offsets = [r.offset_snapshot() for r in readers]
        if not self.source.unbounded or len(readers) == 1:
            # deterministic round-robin over bounded partitions (also the
            # single-reader unbounded path, which needs idle hints like
            # the threaded path below — bounded sources get the EOS flush
            # instead)
            idle = (
                _IdleTracker(
                    self._idle_timeout_ms,
                    # same reader-side gate as the prefetch path: a
                    # reader that KNOWS it has backlog (caught_up False)
                    # blocks the idle hint; None (no backlog knowledge)
                    # keeps the wall-clock judgment
                    quiet=lambda: all(
                        r.caught_up() is not False for r in readers
                    ),
                )
                if self.source.unbounded and self._idle_timeout_ms is not None
                else None
            )
            pwm = self._partition_wm_tracker(len(readers))
            if pwm is not None:
                yield WatermarkHint(WM_ANNOUNCE, kind="partition")
            live = list(enumerate(readers))
            while live:
                nxt = []
                for i, r in live:
                    b = r.read()
                    if b is None:
                        if pwm is not None and (h := pwm.finish(i)):
                            yield h
                        continue
                    nxt.append((i, r))
                    if b.num_rows:
                        self._metrics["rows_out"] += b.num_rows
                        self._metrics["batches_out"] += 1
                        self._obs_rows_out.add(b.num_rows)
                        if idle is not None:
                            idle.observe_rows(b)
                        if self._dr_lineage is not None:
                            # sampled record lineage: tag rows with the
                            # reader's own post-batch offset snapshot
                            self._dr_lineage.ingest(
                                self._obs_source_label, i,
                                r.offset_snapshot(), b,
                            )
                        yield b
                        self._yielded_offsets[i] = r.offset_snapshot()
                        if pwm is not None and (h := pwm.observe(i, b)):
                            yield h
                    else:
                        if idle is not None and (h := idle.maybe_hint()):
                            yield h
                        if pwm is not None and (h := pwm.advance()):
                            yield h
                    yield from self._maybe_barrier()
                live = nxt
            yield EOS
            return

        # live multi-partition: one prefetch worker per partition runs the
        # full fetch → decode → assembly loop off this thread (the ctypes
        # foreign calls release the GIL for their native portion, so
        # workers overlap across cores).  Each ready item carries the
        # reader's offset snapshot taken right after the read, so barrier
        # persistence reflects only yielded batches; backpressure is the
        # per-partition bounded buffer inside the pump, released only
        # after downstream fully processed the batch.
        from denormalized_tpu.runtime.prefetch import PrefetchPump

        with obs.bound_registry(self._obs_reg):
            pump = PrefetchPump(
                readers,
                queue_budget=self._queue_size,
                # per-partition rebuild hooks: with these the pump
                # SUPERVISES worker crashes (restart + seek to the last
                # enqueued offset) instead of failing the query on the
                # first transient error
                reader_factories=self.source.partition_factories(),
                source_name=self._obs_source_label,
            )
        self._pump = pump
        finished = 0
        # idle-source watermark hints: live readers deliver EMPTY batches
        # on read timeouts even when the topic is quiet, so idleness is
        # measured from the last ROWFUL batch (wall clock), not from queue
        # starvation — gated on reader-side quiescence so a consumer
        # stall can never declare idleness over data already in flight.
        # One hint per idle period; rows re-arm it.
        idle = (
            _IdleTracker(self._idle_timeout_ms, quiet=pump.quiet)
            if self._idle_timeout_ms is not None
            else None
        )
        pwm = self._partition_wm_tracker(len(readers), activity=pump.activity)
        if pwm is not None:
            yield WatermarkHint(WM_ANNOUNCE, kind="partition")
        pump.start()
        try:
            while finished < len(readers):
                # liveness-checked get: a worker that died without its
                # sentinel surfaces as a structured error instead of
                # wedging the stream in an untimed queue wait
                item = pump.get_live()
                if isinstance(item, BaseException):
                    raise item
                idx, snap, batch = item
                if batch is None:
                    # per-reader EOS (dead unbounded reader)
                    finished += 1
                    if pwm is not None and (h := pwm.finish(idx)):
                        yield h
                    continue
                self._metrics["rows_out"] += batch.num_rows
                self._metrics["batches_out"] += 1
                self._obs_rows_out.add(batch.num_rows)
                if idle is not None:
                    if batch.num_rows:
                        idle.observe_rows(batch)
                    elif h := idle.maybe_hint():
                        yield h
                if self._dr_lineage is not None and batch.num_rows:
                    self._dr_lineage.ingest(
                        self._obs_source_label, idx, snap, batch
                    )
                yield batch
                self._yielded_offsets[idx] = snap
                pump.consumed(idx, bool(batch.num_rows))
                if pwm is not None:
                    h = (
                        pwm.observe(idx, batch)
                        if batch.num_rows
                        else pwm.advance()
                    )
                    if h:
                        yield h
                yield from self._maybe_barrier()
        finally:
            pump.stop()
        yield EOS


class ProjectExec(ExecOperator):
    def __init__(self, input_op: ExecOperator, exprs: list[Expr], schema: Schema):
        self.input_op = input_op
        self.exprs = exprs
        self.schema = schema
        self.bind_obs("project")

    @property
    def children(self):
        return [self.input_op]

    def _label(self):
        return f"ProjectExec({', '.join(e.name for e in self.exprs)})"

    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu.logical.expr import AliasExpr, Column

        def passthrough_name(e: Expr) -> str | None:
            # validity masks survive projections that are pure column
            # references (possibly aliased); computed exprs get no mask
            while isinstance(e, AliasExpr):
                e = e.inner
            return e.name if isinstance(e, Column) else None

        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                t0 = time.perf_counter()
                self._obs_rows_in.add(item.num_rows)
                cols = [e.eval(item) for e in self.exprs]
                masks = [
                    item.mask(src) if (src := passthrough_name(e)) is not None else None
                    for e in self.exprs
                ]
                out = RecordBatch(self.schema, cols, masks)
                self._note_batch(t0, item.num_rows)
                yield out
            else:
                yield item


class FilterExec(ExecOperator):
    def __init__(self, input_op: ExecOperator, predicate: Expr):
        self.input_op = input_op
        self.predicate = predicate
        self.schema = input_op.schema
        self.bind_obs("filter")

    @property
    def children(self):
        return [self.input_op]

    def _label(self):
        return f"FilterExec({self.predicate!r})"

    def run(self) -> Iterator[StreamItem]:
        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                t0 = time.perf_counter()
                self._obs_rows_in.add(item.num_rows)
                keep = np.asarray(self.predicate.eval(item), dtype=bool)
                out = (
                    item if keep.all()
                    else item.filter(keep) if keep.any()
                    else None
                )
                self._note_batch(t0, item.num_rows)
                if out is not None:
                    yield out
            else:
                yield item


class SinkExec(ExecOperator):
    """Terminal operator driving a sink callable over the finished stream
    (print_stream at datastream.rs:311-339 / sink_python at
    py datastream.rs:229-270)."""

    def __init__(self, input_op: ExecOperator, sink: "Sink") -> None:
        self.input_op = input_op
        self.sink = sink
        self.schema = input_op.schema
        self.bind_obs("sink")

    @property
    def children(self):
        return [self.input_op]

    def _label(self):
        return f"SinkExec({type(self.sink).__name__})"

    def run(self) -> Iterator[StreamItem]:
        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # sink.write is this operator's busy time: a slow sink
                # (blocking Kafka produce, fsync-heavy file sink) must
                # show up as the bottleneck it is, not as upstream wait
                t0 = time.perf_counter()
                self._obs_rows_in.add(item.num_rows)
                self.sink.write(item)
                self._note_batch(t0, item.num_rows)
            elif isinstance(item, EndOfStream):
                self.sink.close()
            yield item


class Sink:
    def write(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class PrintSink(Sink):
    """stdout sink; strips internal columns like the reference's
    print_stream (datastream.rs:317-339 prints JSON rows minus metadata)."""

    def __init__(self, file=None) -> None:
        self._file = file or sys.stdout

    def write(self, batch: RecordBatch) -> None:
        # sink = user-facing boundary: columnar columns materialize here
        user = batch.select(
            batch.schema.without_internal().names
        ).materialized()
        import json

        names = user.schema.names
        for i in range(user.num_rows):
            row = {n: _py(user.columns[j][i]) for j, n in enumerate(names)}
            print(json.dumps(row), file=self._file)


def _py(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


class CallbackSink(Sink):
    """Python-callback sink (the PyO3 ``sink_python`` equivalent): calls
    ``fn(batch)`` with internal columns stripped."""

    def __init__(self, fn: Callable[[RecordBatch], None]) -> None:
        self._fn = fn

    def write(self, batch: RecordBatch) -> None:
        # user callback = user-facing boundary: rows may materialize
        self._fn(
            batch.select(
                batch.schema.without_internal().names
            ).materialized()
        )


class CollectSink(Sink):
    """Test sink: collects emitted batches."""

    def __init__(self) -> None:
        self.batches: list[RecordBatch] = []

    def write(self, batch: RecordBatch) -> None:
        self.batches.append(batch)

    def result(self) -> RecordBatch:
        return RecordBatch.concat(self.batches)
