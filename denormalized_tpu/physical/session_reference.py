"""Reference (pre-vectorization) session operator — the differential oracle.

This is the row/segment-at-a-time implementation the vectorized
``SessionWindowExec`` replaced: per-row ``hash(tuple)`` composite keys, one
Python iteration + ``_Agg`` of Python lists per (key, segment), and open
sessions as a dict of Python objects.  It is kept VERBATIM (class renamed)
for two jobs:

- the differential oracle for ``tests/test_session_vectorized.py`` and the
  ``session_scale`` bench phase's before/after comparison;
- an escape hatch: ``DENORMALIZED_SESSION_REFERENCE=1`` makes the planner
  build this operator instead of the vectorized one.

Known defect (by design left in place — it is what the rewrite fixes): the
salted 64-bit ``hash(tuple)`` composite can collide and silently merge
segments of two distinct keys; the interner's dense ids cannot.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import AggregateExpr, Expr
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)


@dataclass
class _Agg:
    """Mergeable running aggregate for one session.  Variance uses
    Welford/Chan moments (means/m2s) — numerically stable at any value
    magnitude, merged exactly by ``segment_agg.chan_merge``."""

    count: int = 0
    counts: list[int] = field(default_factory=list)  # per value col
    sums: list[float] = field(default_factory=list)
    mins: list[float] = field(default_factory=list)
    maxs: list[float] = field(default_factory=list)
    means: list[float] = field(default_factory=list)
    m2s: list[float] = field(default_factory=list)


@dataclass
class _Session:
    start: int
    last: int
    agg: _Agg
    # one Accumulator per UDAF/collection aggregate (None when none exist)
    accs: list | None = None


class ReferenceSessionWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        aggr_exprs: list[AggregateExpr],
        gap_ms: int,
        *,
        emit_on_close: bool = True,
        name: str = "session_window",
    ) -> None:
        if not group_exprs:
            raise PlanError("session windows require at least one group key")
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self.aggr_exprs = list(aggr_exprs)
        self.gap_ms = int(gap_ms)
        self.emit_on_close = emit_on_close
        self.name = name

        in_schema = input_op.schema
        self._value_exprs: list[Expr] = []
        keys: dict[str, int] = {}

        def value_idx(e: Expr) -> int:
            k = repr(e)
            if k not in keys:
                keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
            return keys[k]

        # accumulator (UDAF/collection) aggregates ride their own per-
        # session Accumulator instances; their args never enter the float
        # value matrix (they may be strings)
        self._udafs = []  # list of AggregateExpr with kind == "udaf"
        self._agg_specs: list[tuple] = []
        for a in self.aggr_exprs:
            if a.kind == "udaf":
                self._agg_specs.append(("udaf", len(self._udafs)))
                self._udafs.append(a)
                continue
            if a.arg is None:
                self._agg_specs.append((a.kind, None))
                continue
            self._agg_specs.append((a.kind, value_idx(a.arg)))

        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in self.aggr_exprs]
        fields += [
            Field(WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
        ]
        self.schema = Schema(fields)

        # per key: open sessions sorted by start (usually exactly one)
        self._sessions: dict[tuple, list[_Session]] = {}
        self._watermark: int | None = None
        # True once a kind="partition" hint arrived: batch min-ts no
        # longer advances the watermark (replay-skew safety)
        self._src_watermarks = False
        self._ckpt: tuple | None = None
        self._metrics = {"rows_in": 0, "sessions_emitted": 0, "late_rows": 0}
        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("session_ref")
        # state observatory: the oracle operator has no interner, so it
        # assigns its own sequential key ids for the sketches (per-row
        # Python is this operator's nature — it is the slow reference)
        self._sw = statewatch.make_watch("session_ref")
        self._sw_ids: dict = {}
        self._sw_keys: list = []
        self._obs_late = obs.counter("dnz_late_rows_total", op="session_ref")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="session_ref"
        )

    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        return (
            f"SessionWindowExec(gap={self.gap_ms}ms, "
            f"groups=[{', '.join(g.name for g in self.group_exprs)}])"
        )

    # -- state observatory (obs/statewatch.py) --------------------------
    def _sw_intern_rows(self, key_cols, n: int) -> np.ndarray:
        """Sequential key ids for the sketches (the oracle has no dense
        interner; ids never recycle, so attribution is alias-free).
        When keys-ever-seen dwarfs the live key population the map is
        dropped and the sketches re-warm — the same bounded-memory
        policy the join/udaf re-intern applies; without it a churning
        differential soak would grow this display-only map forever."""
        if len(self._sw_ids) > 4 * max(len(self._sessions), 1024):
            self._sw.reset_sketches()
            self._sw_ids = {}
            self._sw_keys = []
        ids = np.empty(n, dtype=np.int64)
        d = self._sw_ids
        keys_list = self._sw_keys
        for i in range(n):
            k = tuple(kc[i] for kc in key_cols)
            j = d.get(k)
            if j is None:
                j = len(keys_list)
                d[k] = j
                keys_list.append(k)
            ids[i] = j
        return ids

    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm

        sessions = self._sessions
        n_sessions = 0
        acc_objs = 0
        oldest = None
        for lst in list(sessions.values()):
            n_sessions += len(lst)
            for s in lst:
                if s.accs:
                    acc_objs += len(s.accs)
                if oldest is None or s.start < oldest:
                    oldest = s.start
        live_keys = len(sessions)
        V = len(self._value_exprs)
        # one _Session: interval + 6 per-column aggregate lists (the
        # dict-era layout this operator preserves verbatim)
        per_session = 96 + V * 6 * 8
        wm = self._watermark
        info = {
            "op": "session_ref",
            "state_bytes": (
                n_sessions * per_session
                + live_keys * swm.KEY_EST_BYTES
                + acc_objs * swm.ACC_EST_BYTES
            ),
            "live_keys": live_keys,
            "key_capacity": live_keys,
            "free_gids": 0,
            "slot_capacity": n_sessions,
            "slot_live": n_sessions,
            "acc_objects": acc_objs,
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
            "retention_unit_ms": self.gap_ms,
        }
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - int(oldest))
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []

        def resolve(gids):
            from denormalized_tpu.ops.interner import format_key_tuple

            keys_list = self._sw_keys
            return [
                format_key_tuple(keys_list[g])
                if 0 <= g < len(keys_list) else None
                for g in np.asarray(gids).tolist()
            ]

        return [(None, self._sw, resolve)]

    # ------------------------------------------------------------------
    def _make_accs(self) -> list | None:
        if not self._udafs:
            return None
        return [a.udaf.make() for a in self._udafs]

    # ------------------------------------------------------------------
    @staticmethod
    def _merge_agg(a: _Agg, p: _Agg) -> None:
        from denormalized_tpu.ops.segment_agg import chan_merge

        a.count += p.count
        for i in range(len(a.sums)):
            _, a.means[i], a.m2s[i] = chan_merge(
                a.counts[i], a.means[i], a.m2s[i],
                p.counts[i], p.means[i], p.m2s[i],
            )
            a.counts[i] += p.counts[i]
            a.sums[i] += p.sums[i]
            a.mins[i] = min(a.mins[i], p.mins[i])
            a.maxs[i] = max(a.maxs[i], p.maxs[i])

    def _merge_rows(
        self,
        key: tuple,
        ts_sorted: np.ndarray,
        partial: _Agg,
        partial_accs: list | None = None,
    ):
        """Merge one batch segment [first, last] into the per-key OPEN
        session set.  Sessions stay open until the watermark passes
        ``last + gap`` — closing on gap-at-arrival would mis-split
        out-of-order data, so a segment may bridge (merge) several open
        sessions (standard event-time session-merge)."""
        first, last = int(ts_sorted[0]), int(ts_sorted[-1])
        open_list = self._sessions.setdefault(key, [])
        keep: list[_Session] = []
        hits: list[_Session] = []
        for s in open_list:
            # within-gap overlap in either direction → merge
            if first - s.last <= self.gap_ms and s.start - last <= self.gap_ms:
                hits.append(s)
            else:
                keep.append(s)
        if not hits:
            keep.append(_Session(first, last, partial, partial_accs))
        else:
            # the OLDEST session is the merge base and the new partial folds
            # in LAST: order-sensitive accumulators (first/last_value,
            # array_agg) keep arrival order, and the per-batch merge copies
            # only the new partial's state — not the session's accumulated
            # state — so long sessions stay O(rows), not quadratic
            hits.sort(key=lambda s: s.start)
            base = hits[0]
            for s in hits[1:]:
                self._merge_agg(base.agg, s.agg)
                if base.accs is not None:
                    for acc, other in zip(base.accs, s.accs):
                        acc.merge(other.state())
            self._merge_agg(base.agg, partial)
            if base.accs is not None and partial_accs is not None:
                for acc, p in zip(base.accs, partial_accs):
                    acc.merge(p.state())
            base.start = min(base.start, first)
            base.last = max(base.last, last, *(s.last for s in hits[1:]))
            keep.append(base)
        keep.sort(key=lambda s: s.start)
        self._sessions[key] = keep

    def _process_batch(self, batch: RecordBatch) -> Iterator[RecordBatch]:
        n = batch.num_rows
        if n == 0:
            return
        self._metrics["rows_in"] += n
        self._obs_rows_in.add(n)
        ts = np.asarray(batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64)
        key_cols = [np.asarray(g.eval(batch), dtype=object) for g in self.group_exprs]
        if self._sw:
            self._sw.update(self._sw_intern_rows(key_cols, n))
        vals = (
            np.stack(
                [np.asarray(e.eval(batch), dtype=np.float64) for e in self._value_exprs],
                axis=1,
            )
            if self._value_exprs
            else np.zeros((n, 0))
        )
        from denormalized_tpu.logical.expr import column_validity

        valid = np.ones_like(vals, dtype=bool)
        for ci, e in enumerate(self._value_exprs):
            m = column_validity(e, batch)
            if m is not None:
                valid[:, ci] = m

        # accumulator-aggregate argument columns (raw dtypes) + masks
        udaf_cols: list[list[np.ndarray]] = []
        udaf_masks: list[np.ndarray | None] = []
        for a in self._udafs:
            udaf_cols.append([np.asarray(e.eval(batch)) for e in a.udaf.args])
            udaf_masks.append(
                column_validity(a.udaf.args[0], batch) if a.udaf.args else None
            )
        # watermark advances from the RAW batch min (late rows included —
        # they only keep the min lower, and the reference's
        # RecordBatchWatermark is computed over the whole batch); computing
        # it after the late-filter would let a dropped row inflate the
        # watermark and mis-drop later on-time rows
        raw_min = int(ts.min())

        # late rows: a row with ts+gap <= watermark would close as a
        # singleton — but if it lies within gap of a STILL-OPEN session for
        # its key it belongs to that session (Flink event-time session
        # semantics: the merged session closes later).  So salvage
        # open-session-mergeable rows and drop only true closed singletons.
        if self._watermark is not None:
            late = ts + self.gap_ms <= self._watermark
            if late.any():
                # decide per-row in ARRIVAL order against a live interval
                # view that also tracks this batch's on-time rows for the
                # affected keys: an earlier row (late or on-time) can extend
                # a session into range of a later late row, exactly as
                # row-at-a-time processing would.  Kept rows then flow
                # through the normal segment/merge machinery, which
                # reproduces the same merged aggregates.
                gap_ms = self.gap_ms
                late_keys = {
                    tuple(kc[i] for kc in key_cols)
                    for i in np.nonzero(late)[0]
                }
                views = {
                    k: [[s.start, s.last] for s in self._sessions.get(k, ())]
                    for k in late_keys
                }
                for i in range(n):
                    key = tuple(kc[i] for kc in key_cols)
                    iv_list = views.get(key)
                    if iv_list is None:
                        continue
                    t = int(ts[i])
                    hit = [
                        iv
                        for iv in iv_list
                        if t - iv[1] <= gap_ms and iv[0] - t <= gap_ms
                    ]
                    if late[i]:
                        if not hit:
                            continue  # true closed singleton: stays dropped
                        late[i] = False
                    merged = [
                        min([t] + [iv[0] for iv in hit]),
                        max([t] + [iv[1] for iv in hit]),
                    ]
                    views[key] = [
                        iv for iv in iv_list if iv not in hit
                    ] + [merged]
            n_late = int(late.sum())
            if n_late:
                self._metrics["late_rows"] += n_late
                self._obs_late.add(n_late)
                keep = ~late
                ts = ts[keep]
                key_cols = [kc[keep] for kc in key_cols]
                vals = vals[keep]
                valid = valid[keep]
                udaf_cols = [[c[keep] for c in cols] for cols in udaf_cols]
                udaf_masks = [
                    m[keep] if m is not None else None for m in udaf_masks
                ]
                n = len(ts)
                if n == 0:
                    return

        # vectorized per-key segmenting: sort by (key, ts), then reduceat over
        # key-run + intra-batch gap boundaries
        composite = np.fromiter(
            (hash(tuple(kc[i] for kc in key_cols)) for i in range(n)),
            dtype=np.int64,
            count=n,
        )
        order = np.lexsort((ts, composite))
        ts_s = ts[order]
        comp_s = composite[order]
        vals_s = vals[order]
        valid_s = valid[order]
        key_rows = [kc[order] for kc in key_cols]
        # boundaries: new key run or gap within same key
        newkey = np.empty(n, dtype=bool)
        newkey[0] = True
        newkey[1:] = comp_s[1:] != comp_s[:-1]
        gap = np.empty(n, dtype=bool)
        gap[0] = True
        gap[1:] = (ts_s[1:] - ts_s[:-1]) > self.gap_ms
        bounds = np.nonzero(newkey | gap)[0]
        ends = np.append(bounds[1:], n)
        for b0, b1 in zip(bounds, ends):
            key = tuple(kr[b0] for kr in key_rows)
            seg_vals = vals_s[b0:b1]
            seg_valid = valid_s[b0:b1]
            # null-neutralize per aggregate kind (same semantics as the
            # device kernel: nulls excluded from count/sum/min/max)
            seg_counts = seg_valid.sum(axis=0)
            seg_sums = np.where(seg_valid, seg_vals, 0.0).sum(axis=0)
            with np.errstate(invalid="ignore", divide="ignore"):
                seg_means = np.where(
                    seg_counts > 0, seg_sums / np.maximum(seg_counts, 1), 0.0
                )
                seg_m2s = np.where(
                    seg_valid, (seg_vals - seg_means) ** 2, 0.0
                ).sum(axis=0)
            partial = _Agg(
                count=int(b1 - b0),
                counts=[int(c) for c in seg_counts],
                sums=[float(s) for s in seg_sums],
                mins=[
                    float(s)
                    for s in np.where(seg_valid, seg_vals, np.inf).min(axis=0)
                ],
                maxs=[
                    float(s)
                    for s in np.where(seg_valid, seg_vals, -np.inf).max(axis=0)
                ],
                means=[float(m) for m in seg_means],
                m2s=[float(m) for m in seg_m2s],
            )
            partial_accs = self._make_accs()
            if partial_accs is not None:
                seg_rows = order[b0:b1]
                for acc, cols, am in zip(partial_accs, udaf_cols, udaf_masks):
                    chunk = [c[seg_rows] for c in cols]
                    if am is not None:
                        ok = am[seg_rows]
                        chunk = [c[ok] for c in chunk]
                    acc.update(*chunk)
            self._merge_rows(key, ts_s[b0:b1], partial, partial_accs)

        # watermark advance + close expired sessions — skipped under
        # per-partition watermarks: the authoritative advance arrives as
        # a kind="partition" hint right after this batch
        if not self._src_watermarks:
            yield from self._advance_and_close(raw_min)

    def _advance_and_close(self, candidate_wm: int) -> Iterator[RecordBatch]:
        """Monotonic watermark advance, then emit every session whose gap
        has expired — shared by the per-batch path and idle-source
        WatermarkHint handling."""
        if self._watermark is None or candidate_wm > self._watermark:
            self._watermark = candidate_wm
        closed: list[tuple[tuple, _Session]] = []
        for k in list(self._sessions):
            still: list[_Session] = []
            for s in self._sessions[k]:
                if s.last + self.gap_ms <= self._watermark:
                    closed.append((k, s))
                else:
                    still.append(s)
            if still:
                self._sessions[k] = still
            else:
                del self._sessions[k]
        if closed:
            yield self._emit(closed)

    def _emit(self, closed: list[tuple[tuple, _Session]]) -> RecordBatch:
        self._metrics["sessions_emitted"] += len(closed)
        self._obs_windows.add(len(closed))
        m = len(closed)
        cols: list[np.ndarray] = []
        in_schema = self.input_op.schema
        for ci, g in enumerate(self.group_exprs):
            f = g.out_field(in_schema)
            vals = np.array([k[ci] for k, _ in closed], dtype=object)
            if f.dtype.is_numeric:
                vals = vals.astype(f.dtype.to_numpy())
            cols.append(vals)
        from denormalized_tpu.ops.segment_agg import VAR_KINDS, variance_from_m2

        for ai, spec in enumerate(self._agg_specs):
            kind, col_i = spec[0], spec[1]
            if kind == "udaf":
                vals_out = [s.accs[col_i].evaluate() for _, s in closed]
                arr = np.empty(len(vals_out), dtype=object)
                for vi, v in enumerate(vals_out):
                    arr[vi] = v
                f = self.aggr_exprs[ai].out_field(self.input_op.schema)
                if f.dtype.is_numeric:
                    arr = arr.astype(f.dtype.to_numpy())
                cols.append(arr)
            elif kind in VAR_KINDS:
                cols.append(
                    variance_from_m2(
                        kind,
                        np.array([s.agg.counts[col_i] for _, s in closed]),
                        np.array([s.agg.m2s[col_i] for _, s in closed]),
                    )
                )
            elif kind == "count":
                cols.append(
                    np.array(
                        [
                            s.agg.count if col_i is None else s.agg.counts[col_i]
                            for _, s in closed
                        ],
                        dtype=np.int64,
                    )
                )
            elif kind == "sum":
                cols.append(np.array([s.agg.sums[col_i] for _, s in closed]))
            elif kind == "avg":
                cols.append(
                    np.array(
                        [
                            s.agg.sums[col_i] / s.agg.counts[col_i]
                            if s.agg.counts[col_i]
                            else np.nan
                            for _, s in closed
                        ]
                    )
                )
            elif kind == "min":
                v = np.array([s.agg.mins[col_i] for _, s in closed])
                cols.append(np.where(np.isposinf(v), np.nan, v))
            elif kind == "max":
                v = np.array([s.agg.maxs[col_i] for _, s in closed])
                cols.append(np.where(np.isneginf(v), np.nan, v))
            else:
                raise PlanError(f"session window does not support {kind}")
        starts = np.array([s.start for _, s in closed], dtype=np.int64)
        ends = np.array([s.last + self.gap_ms for _, s in closed], dtype=np.int64)
        # cast agg outputs to declared dtypes
        out_cols = []
        for f, c in zip(self.schema.fields[: len(cols)], cols):
            out_cols.append(
                c if c.dtype == object else c.astype(f.dtype.to_numpy())
            )
        out_cols += [starts, ends, starts.copy()]
        return RecordBatch(self.schema, out_cols)

    # -- checkpointing (host dict state → JSON blob) ----------------------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        from denormalized_tpu.state.checkpoint import get_json

        # node ids embed the CLASS name (checkpoint.assign_node_ids); map
        # this class's back to the production operator's so snapshots
        # interoperate in both directions (same plan position, same key)
        node_id = node_id.replace(
            "ReferenceSessionWindowExec", "SessionWindowExec"
        )
        self._ckpt = (coord, f"session_{node_id}")
        snap = get_json(coord, self._ckpt[1])
        if snap is None:
            return
        self._watermark = snap["watermark"]
        self._sessions = {}
        for entry in snap["sessions"]:
            key_list, start, last, agg = entry[:4]
            acc_states = entry[4] if len(entry) > 4 else None
            accs = self._make_accs()
            if accs is not None and acc_states is not None:
                for acc, st in zip(accs, acc_states):
                    acc.merge(st)
            s = _Session(
                start,
                last,
                _Agg(
                    count=agg["count"],
                    counts=list(agg["counts"]),
                    sums=list(agg["sums"]),
                    mins=list(agg["mins"]),
                    maxs=list(agg["maxs"]),
                    means=list(agg.get("means", [0.0] * len(agg["sums"]))),
                    m2s=list(agg.get("m2s", [0.0] * len(agg["sums"]))),
                ),
                accs,
            )
            self._sessions.setdefault(tuple(key_list), []).append(s)

    def _snapshot(self, epoch: int) -> None:
        from denormalized_tpu.state.checkpoint import put_json

        coord, key = self._ckpt
        sessions = [
            [list(k), s.start, s.last,
             {
                 "count": s.agg.count,
                 "counts": s.agg.counts,
                 "sums": s.agg.sums,
                 "mins": [float(m) for m in s.agg.mins],
                 "maxs": [float(m) for m in s.agg.maxs],
                 "means": [float(m) for m in s.agg.means],
                 "m2s": [float(m) for m in s.agg.m2s],
             },
             [acc.state() for acc in s.accs] if s.accs is not None else None]
            for k, lst in self._sessions.items()
            for s in lst
        ]
        put_json(
            coord, key, epoch,
            {"epoch": epoch, "watermark": self._watermark, "sessions": sessions},
        )

    def run(self) -> Iterator[StreamItem]:
        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # materialized inside the timing bracket (the doctor's
                # busy/handoff contract, same as the vectorized operator)
                t0 = time.perf_counter()
                out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item  # pure mode announcement
                        continue
                yield from self._advance_and_close(item.ts_ms)
                # emissions stamp canonical ts with the session START:
                # forward clamped below every still-open session's start
                # AND below watermark - gap — the lateness rule accepts
                # out-of-order rows down to watermark - gap + 1, and such
                # a row can START (or merge a session down to) exactly
                # there, so that is the true output low bound
                open_starts = [
                    s.start
                    for lst in self._sessions.values()
                    for s in lst
                ]
                floor = (
                    self._watermark - self.gap_ms
                    if self._watermark is not None
                    else item.ts_ms
                )
                yield WatermarkHint(
                    min(
                        [item.ts_ms, floor]
                        + [st - 1 for st in open_starts]
                    ),
                    kind=item.kind,
                )
            elif isinstance(item, Marker):
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                yield item
            elif isinstance(item, EndOfStream):
                if self.emit_on_close and self._sessions:
                    closed = [
                        (k, s)
                        for k, lst in self._sessions.items()
                        for s in lst
                    ]
                    closed.sort(key=lambda e: e[1].start)
                    self._sessions.clear()
                    yield self._emit(closed)
                yield EOS
                return
