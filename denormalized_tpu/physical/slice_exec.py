"""Slice-folding window operator — one ingest, N concurrent window specs.

``SliceWindowExec`` is the execution half of the multi-query engine
(docs/multi_query.md): it accumulates per-(group, slide-unit) partials
ONCE per input batch into a shared :class:`SliceStore` and lets every
subscribed window spec — tumbling, sliding, and any number of
concurrently registered queries over the same source+filter+keys — fold
its windows from those partials.  A sliding window composes ``L/g``
slice partials by exact addition (the constant-pivot Chan combine; see
ops/slice_store.py) instead of re-aggregating raw rows per overlap, and
``N`` shareable queries pay ONE ingest+decode+aggregate pass instead of
``N``.

Two modes:

- **single-subscriber** (the planner's ``EngineConfig(slice_windows=
  True)`` fast path): a drop-in for :class:`StreamingWindowExec` on
  foldable aggregates — emissions flow as plain RecordBatches;
- **tagged** (the multi-query runtime): emissions are wrapped in
  :class:`SubscriberBatch` carrying the subscriber index, and the
  shared drive loop (runtime/multi_query.py) routes each to its query's
  sink.

Checkpointing takes ONE snapshot per epoch: the slice store's partials,
the shared interner, the watermark, and every subscriber's emission
cursor — restore resumes each query exactly where its own emissions
stopped (per-query cursors, one store).  Semantics (late drop against
the per-subscriber open floor, per-partition watermark rebase, idle
hints, EOS flush) mirror StreamingWindowExec so a query moved between
the operators sees the same windows.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import (
    SKETCH_AGG_KINDS,
    VAR_KINDS,
    AggregateExpr,
    Column as _ColExpr,
    Expr,
)
from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.ops.interner import GroupInterner
from denormalized_tpu.ops.slice_store import SliceStore
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)
from denormalized_tpu.physical.window_exec import (
    watermark_floor,
    window_output_low_watermark,
)

#: aggregate kinds whose windows fold exactly from slice partials —
#: the sketch kinds fold within their documented error bounds via
#: mergeable sketch planes (ops/sketches.py), sharing like any other
#: foldable aggregate (subsumption groups, shared joins, live attach)
FOLDABLE_KINDS = frozenset(
    ("count", "sum", "min", "max", "avg")
    + tuple(VAR_KINDS)
    + tuple(SKETCH_AGG_KINDS)
)


@dataclass
class SliceSubscriber:
    """One window spec folding from the shared slice store."""

    aggr_exprs: list
    length_ms: int
    slide_ms: int
    tag: int = 0
    label: str | None = None
    #: residual predicate re-applied per row before this subscriber's
    #: slice partials accumulate (subsumption sharing: the group
    #: ingests under the WEAKEST member predicate; members with a
    #: strictly stronger predicate re-filter here).  None = the
    #: subscriber's predicate IS the base predicate — no re-filter.
    filter_expr: Expr | None = None
    #: full-predicate signature (checkpoint identity of this
    #: subscriber's filter, planner/predicates.predicate_signature)
    filter_sig: str = ""
    # filled by the operator: per-subscriber agg specs over the SHARED
    # value-column space, and the output schema
    agg_specs: list = field(default_factory=list)
    schema: Schema | None = None
    #: any agg spec is a ("sketch", …) entry — the emit path splits
    #: finalization between scalar components and sketch planes
    has_sketch: bool = False


class SubscriberBatch:
    """A tagged emission in multi-subscriber (shared) mode: ``tag`` is
    the subscriber index, ``batch`` the per-query emission."""

    __slots__ = ("tag", "batch")

    def __init__(self, tag: int, batch: RecordBatch) -> None:
        self.tag = tag
        self.batch = batch


def refilter_gid_mask(gid: np.ndarray, gid_pass: np.ndarray) -> np.ndarray:
    """Per-row residual mask from per-gid pass bits: one gather over
    dense interned gids.  The re-filter hot path for residual
    predicates over the group-key columns — the predicate itself is
    evaluated once per NEW gid (``_extend_gid_pass``), never per row."""
    return gid_pass[gid]


def shared_sort_order(units: np.ndarray, gid: np.ndarray) -> np.ndarray:
    """ONE stable ``(unit, gid)`` sort permutation for a whole batch,
    shared by every sort-lane filter class.  The key multiplier only
    has to separate gids (any value > max gid yields the same ordering
    relation), so the permutation is identical to the one each class's
    store would compute with its own capacity — classes reuse it
    instead of re-sorting."""
    mult = np.int64(max(int(gid.max()) + 1, 1)) if len(gid) else np.int64(1)
    key = units.astype(np.int64) * mult + gid.astype(np.int64)
    return np.argsort(key, kind="stable")


def masked_sorted_order(order: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Subset a stable sort permutation by a residual mask, preserving
    sort order — the per-class re-filter between the shared sort and
    that class's slice-store accumulate.  A stable subset of a stable
    sort IS the subset's stable sort, so the re-filtered member's folds
    stay byte-comparable to an independent oracle that sorts its
    filtered rows directly."""
    return order[mask[order]]


class _FilterClass:
    """One residual-predicate class inside a shared pipeline:
    subscribers whose full predicate equals the group's base predicate
    form class ``""`` (no re-filter, the shared ingest already applied
    it); each strictly stronger predicate gets its own class that
    re-filters the shared pass into its own slice partials.  Residual
    classes force the store's lexsort lane so an independent oracle
    (whose interner capacity differs) can match the fold lane by
    pinning ``EngineConfig(slice_sort_lane=True)``."""

    __slots__ = (
        "sig", "pred", "gid_lane", "gid_pass", "store", "exact_from_unit",
        "rows_kept",
    )

    def __init__(self, sig, pred, gid_lane, store) -> None:
        self.sig = sig
        self.pred = pred
        self.gid_lane = gid_lane
        self.gid_pass = np.zeros(0, dtype=bool)
        self.store = store
        # rows this class accumulated (post re-filter): the demand side
        # of upstream-cost attribution — a member whose residual keeps
        # 90% of a shared join's output is charged 90% of the join's
        # probe/build/gather time, not 1/N (shared_fractions)
        self.rows_kept = 0
        # first slice unit this class's partials are complete from: None
        # for classes present since the start of the stream, else the
        # unit after the max event time ingested when a mid-stream
        # attach opened the class.  EVERY member's first exact window
        # clamps past it — the floor is a property of the class's
        # partials, not of whichever joiner happened to create it
        self.exact_from_unit: int | None = None


class SliceWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        subscribers: list[SliceSubscriber],
        *,
        emit_on_close: bool = True,
        tagged: bool = False,
        unit_ms: int | None = None,
        sort_lane: bool = False,
        name: str = "slice_window",
    ) -> None:
        if not subscribers:
            raise PlanError("SliceWindowExec needs at least one subscriber")
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self._subs = list(subscribers)
        self.emit_on_close = emit_on_close
        self._tagged = tagged
        self.name = name

        in_schema = input_op.schema
        # shared deduped value-column space across ALL subscribers (the
        # StreamingWindowExec dedup, widened to N aggregate lists).
        # ``_value_keys`` persists so live-attached subscribers can
        # resolve their aggregates against the SAME column space.
        self._value_exprs: list[Expr] = []
        self._value_transforms: list[str | None] = []
        self._var_shift: dict[str, float] = {}
        self._value_keys: dict = {}
        # sketch specs deduped across subscribers by (kind, value col,
        # params): two queries asking approx_distinct(v) share ONE HLL
        # plane, like any other deduped component.  Insertion order
        # assigns sids, so shared and restored runs label planes alike.
        self._sketch_specs: dict[tuple, object] = {}
        # dense value-id interner for approx_top_k lanes (lazy — only
        # pipelines carrying a top-k sketch pay for it)
        self._vid_interner: GroupInterner | None = None

        unit = 0
        for sub in self._subs:
            self._prepare_subscriber(sub, grow=True)
            unit = math.gcd(
                unit, math.gcd(sub.length_ms, sub.slide_ms)
            )
        if unit_ms is not None:
            # explicit slice-width pin: the fold grouping is part of a
            # query's numeric contract (f64 sums round per fold tree),
            # so an independent oracle comparing against a shared run
            # pins the shared group's unit here.  Any divisor of the
            # natural gcd is valid — slices still tile every window.
            if unit_ms <= 0 or unit % int(unit_ms):
                raise PlanError(
                    f"slice_unit_ms={unit_ms} must divide every "
                    f"subscriber's window length and slide (gcd {unit}ms)"
                )
            unit = int(unit_ms)
        self.unit_ms = unit
        all_specs = [s for sub in self._subs for s in sub.agg_specs]
        self._components = tuple(sa.components_for(all_specs))
        self._force_sort_lane = bool(sort_lane)

        self._grouped = len(self.group_exprs) > 0
        self._interner = (
            GroupInterner(len(self.group_exprs)) if self._grouped else None
        )
        # per-filter-class slice stores: one store per residual
        # predicate class; subscribers map to their class object
        self._classes: list[_FilterClass] = []
        self._sub_class: list[_FilterClass] = [
            self._class_for(sub) for sub in self._subs
        ]
        # live-registration state: pending attach/detach ops applied at
        # batch boundaries on the operator thread, per-sub cost ledger
        # for actual-fraction attribution, backfill-exactness tracking
        import threading

        self._ops_lock = threading.Lock()
        self._pending_ops: list = []
        self._sub_cost_ms: list[float] = [0.0] * len(self._subs)
        self._first_exact: list[int | None] = [None] * len(self._subs)
        self._first_ts: int | None = None
        self._exact_floor_unit: int | None = None
        self._orphans: dict[int, dict] = {}
        self._orphan_class_arrays: dict[str, tuple] = {}
        self._departed: set[int] = set()
        # base re-derivation (weakest-member departure): a predicate
        # every survivor's own filter implies, applied to arriving rows
        # BEFORE intern/value-eval/sort — the upstream plan still runs
        # the original (wider) base filter, but rows no survivor can
        # reach stop paying the ingest path (set_ingest_pred)
        self._ingest_pred: Expr | None = None
        # measured upstream shared cost (ms) — a shared join's
        # probe/build/gather ledger, apportioned across subscribers by
        # their classes' kept-rows demand in shared_fractions()
        self._upstream_cost_fn = None
        # fired after a detach completes (tag already removed, unowned
        # classes dropped, slices pruned) — the multi-query runtime
        # re-derives the ingest base from survivors here
        self.on_detach = None
        # single-subscriber mode exposes that subscriber's schema (the
        # planner drop-in contract); tagged mode has no single schema —
        # downstream is the multi-query drive loop, not an operator
        self.schema = self._subs[0].schema

        # streaming state
        self._ckpt: tuple | None = None
        self._next_win: list[int | None] = [None] * len(self._subs)
        self._watermark_ms: int | None = None
        self._src_watermarks = False
        self._max_ts: int | None = None
        self._metrics = {
            "rows_in": 0,
            "rows_ingested": 0,
            "batches_in": 0,
            "late_rows": 0,
            "windows_emitted": 0,
            "slice_folds": 0,
            "slices_live": 0,
            "slices_pruned": 0,
            "subscribers": len(self._subs),
        }

        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("slice_window")
        self._sw = statewatch.make_watch("slice_window")
        self._obs_late = obs.counter("dnz_late_rows_total", op="slice_window")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="slice_window"
        )
        self._obs_emit_lag = obs.histogram(
            "dnz_emit_event_lag_ms", op="slice_window"
        )
        self._obs_wm_lag = obs.gauge("dnz_watermark_lag_ms", op="slice_window")
        self._obs_wm_lag_hist = obs.histogram(
            "dnz_watermark_lag_hist_ms", op="slice_window"
        )
        self._obs_slice_rows = obs.counter("dnz_slice_rows_total")
        self._obs_slice_units = obs.gauge("dnz_slice_units")
        self._obs_slice_subs = obs.gauge("dnz_slice_subscribers")
        self._obs_folds = obs.counter("dnz_slice_folds_total")
        self._obs_fold_ms = obs.histogram("dnz_slice_fold_ms")
        self._obs_slice_subs.set(len(self._subs))
        # per-subscriber emit lag: the aggregate histogram above sums
        # over subscribers, so a slow query hiding inside a shared
        # pipeline was unattributable — one gauge per query fixes that
        self._obs_mq_emit_lag = [
            obs.gauge(
                "dnz_mq_emit_lag_ms",
                query=sub.label if sub.label is not None else f"q{q}",
            )
            for q, sub in enumerate(self._subs)
        ]
        # query-dense serving instruments: live subscriber count (moves
        # on attach/detach), windows served from retained slices at
        # attach, and the per-batch residual re-filter cost
        self._obs_mq_live = obs.gauge("dnz_mq_subscribers_live")
        self._obs_mq_backfill = obs.counter("dnz_mq_backfill_windows_total")
        self._obs_refilter_ms = obs.histogram("dnz_mq_refilter_ms")
        self._obs_mq_live.set(len(self._subs))
        # sketch-plane instruments (rows through sketch kernels, exact
        # plane bytes, per-batch kernel time) — per-batch deltas of the
        # stores' own counters, summed over filter classes
        self._obs_sketch_rows = obs.counter("dnz_sketch_rows_total")
        self._obs_sketch_bytes = obs.gauge("dnz_sketch_state_bytes")
        self._obs_sketch_ms = obs.histogram("dnz_sketch_update_ms")
        self._sketch_rows_seen = 0
        self._sketch_upd_seen = 0.0

    # -- subscriber / filter-class plumbing ------------------------------
    @property
    def _store(self) -> SliceStore:
        """The base filter class's store (legacy single-class view —
        state accounting and tests address it directly)."""
        return self._classes[0].store

    def _prepare_subscriber(self, sub: SliceSubscriber, *, grow: bool) -> None:
        """Normalize one subscriber's window spec and resolve its
        aggregates against the shared value-column space.  With
        ``grow=False`` (live attach) the value space is frozen: an
        aggregate needing a column the group never ingested raises —
        the caller falls back to an independent pipeline."""
        in_schema = self.input_op.schema

        def col_idx(e: Expr, transform: str | None) -> int:
            k = (transform, repr(e))
            if k not in self._value_keys:
                if not grow:
                    raise PlanError(
                        f"subscriber aggregate over {e!r} needs a value "
                        "column the shared group does not ingest — "
                        "attach requires aggregates over the group's "
                        "existing column space"
                    )
                self._value_keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
                self._value_transforms.append(transform)
            return self._value_keys[k]

        sub.slide_ms = int(sub.slide_ms) if sub.slide_ms else int(
            sub.length_ms
        )
        sub.length_ms = int(sub.length_ms)
        if sub.length_ms <= 0 or sub.slide_ms <= 0:
            raise PlanError(
                "window length and slide must be positive for the "
                f"slice path (got L={sub.length_ms} S={sub.slide_ms})"
            )
        specs: list[tuple] = []
        for a in sub.aggr_exprs:
            if not isinstance(a, AggregateExpr):
                raise PlanError(f"{a!r} is not an aggregate expression")
            if a.kind not in FOLDABLE_KINDS:
                raise PlanError(
                    f"aggregate kind {a.kind!r} does not fold from "
                    "slice partials (UDAFs run in UdafWindowExec)"
                )
            if a.arg is None:
                specs.append((a.kind, None))
            elif a.kind in SKETCH_AGG_KINDS:
                specs.append(self._sketch_spec_for(a, col_idx, grow))
            elif a.kind in sa.VAR_KINDS:
                specs.append(
                    (
                        a.kind,
                        col_idx(a.arg, "shift"),
                        col_idx(a.arg, "shift_sq"),
                    )
                )
            else:
                specs.append((a.kind, col_idx(a.arg, None)))
        sub.agg_specs = specs
        sub.has_sketch = any(s[0] == "sketch" for s in specs)
        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in sub.aggr_exprs]
        fields += [
            Field(
                WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False
            ),
            Field(
                WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False
            ),
            Field(
                CANONICAL_TIMESTAMP_COLUMN,
                DataType.TIMESTAMP_MS,
                nullable=False,
            ),
        ]
        sub.schema = Schema(fields)

    def _sketch_spec_for(self, a: AggregateExpr, col_idx, grow: bool) -> tuple:
        """Resolve one sketch aggregate to its (deduped) SketchSpec and
        value lane.  Specs dedup by (family, value column, params) —
        concurrent queries asking the same sketch over the same column
        share one plane per slice cell.  With ``grow=False`` (live
        attach) a spec the group never planned raises: sketch planes
        exist per slice unit from the unit's creation, so a mid-stream
        joiner can only ride planes already maintained."""
        from denormalized_tpu.ops import sketches as skx

        if a.kind == "approx_distinct":
            vcol = col_idx(a.arg, "hash")
            key = ("hll", vcol, ())
            q = None
        elif a.kind == "approx_top_k":
            k = int(a.params[0]) if a.params else 10
            vcol = col_idx(a.arg, "vid")
            key = ("topk", vcol, (k,))
            q = None
        else:  # approx_percentile_cont / approx_median
            q = float(a.params[0]) if a.params else 0.5
            vcol = col_idx(a.arg, None)
            key = ("kll", vcol, ())
        spec = self._sketch_specs.get(key)
        if spec is None:
            if not grow:
                raise PlanError(
                    f"subscriber aggregate {a.kind}({a.arg!r}) needs a "
                    "sketch plane the shared group does not maintain — "
                    "attach requires sketches the group already plans"
                )
            sid = f"sk{len(self._sketch_specs)}"
            if key[0] == "hll":
                spec = skx.HllSpec(sid, vcol)
            elif key[0] == "topk":
                spec = skx.TopKSpec(sid, vcol, key[2][0])
            else:
                spec = skx.KllSpec(sid, vcol)
            self._sketch_specs[key] = spec
        if q is None:
            return ("sketch", vcol, spec)
        return ("sketch", vcol, spec, q)

    def _class_for(self, sub: SliceSubscriber) -> _FilterClass:
        """Find or create the filter class for one subscriber's
        residual predicate."""
        sig = "" if sub.filter_expr is None else repr(sub.filter_expr)
        for cls in self._classes:
            if cls.sig == sig:
                return cls
        gid_lane = False
        if sig and self._grouped:
            key_names = {
                g.name for g in self.group_exprs if isinstance(g, _ColExpr)
            }
            gid_lane = (
                len(key_names) == len(self.group_exprs)
                and sub.filter_expr.columns_referenced() <= key_names
            )
        store = SliceStore(
            self._components,
            self.unit_ms,
            # residual classes always sort: their independent oracles
            # run a DIFFERENT interner (own gid space/capacity), so the
            # dense-lane guard could diverge — the lexsort lane's fold
            # order is capacity-independent (oracle pins
            # EngineConfig(slice_sort_lane=True) to match)
            force_sort_lane=self._force_sort_lane or bool(sig),
            sketches=tuple(self._sketch_specs.values()),
        )
        cls = _FilterClass(sig, sub.filter_expr, gid_lane, store)
        self._classes.append(cls)
        return cls

    def _extend_gid_pass(self, cls: _FilterClass, ngroups: int) -> None:
        """Evaluate a gid-lane class's residual predicate over the
        interner keys of gids not yet classified (new groups only —
        O(new keys), never O(rows))."""
        start = len(cls.gid_pass)
        if ngroups <= start:
            return
        new = np.arange(start, ngroups, dtype=np.int64)
        key_vals = self._interner.keys_of(new)
        fields = [g.out_field(self.input_op.schema) for g in self.group_exprs]
        kb = RecordBatch(Schema(fields), list(key_vals))
        passed = np.asarray(cls.pred.eval(kb), dtype=bool)
        cls.gid_pass = np.concatenate((cls.gid_pass, passed))

    def shared_fractions(self) -> dict[int, float]:
        """Measured per-subscriber share of this pipeline's work, keyed
        by subscriber tag — the doctor's actual-fraction attribution
        for shared pipelines (re-filter + per-class accumulate + fold
        cost differs across subscribers, so 1/N would lie).

        When the shared input is itself a measured operator (a shared
        ``StreamingJoinExec`` reporting probe/build/gather time via
        ``_upstream_cost_fn``), that upstream cost is apportioned by
        each subscriber's share of kept rows: a member whose residual
        keeps 90% of the join output caused ~90% of the join's gather
        fan-out, and is attributed accordingly."""
        total = sum(self._sub_cost_ms)
        n = max(len(self._subs), 1)
        up = 0.0
        if self._upstream_cost_fn is not None:
            try:
                up = float(self._upstream_cost_fn())
            except Exception:  # dnzlint: allow(broad-except) doctor attribution is best-effort: a torn upstream metrics read mid-teardown degrades to measured-only shares, it never fails the pipeline
                up = 0.0
        if total <= 0.0 and up <= 0.0:
            return {sub.tag: 1.0 / n for sub in self._subs}
        kept = [0.0] * len(self._subs)
        if up > 0.0:
            for cls in self._classes:
                owners = [
                    q for q, c in enumerate(self._sub_class) if c is cls
                ]
                if owners and cls.rows_kept:
                    share = cls.rows_kept / len(owners)
                    for q in owners:
                        kept[q] = share
            ktot = sum(kept)
            if ktot > 0.0:
                kept = [k / ktot for k in kept]
            else:
                kept = [1.0 / n] * len(self._subs)
        denom = total + up
        return {
            sub.tag: (self._sub_cost_ms[q] + up * kept[q]) / denom
            for q, sub in enumerate(self._subs)
        }

    # -- live registration (attach/detach at slice boundaries) -----------
    def request_attach(self, sub: SliceSubscriber, when_ts: int | None = None):
        """Queue a mid-stream subscription (any thread).  The operator
        thread applies it at the next batch boundary — with ``when_ts``
        set, at the first batch whose min event time reaches it, so a
        replayed request lands at the same stream position after a
        kill/restore (event time is deterministic; arrival time isn't)."""
        with self._ops_lock:
            self._pending_ops.append(("attach", sub, when_ts))

    def request_detach(self, tag: int, when_ts: int | None = None):
        """Queue a mid-stream unsubscription (any thread)."""
        with self._ops_lock:
            self._pending_ops.append(("detach", tag, when_ts))

    def _drain_ops(self, upcoming_ts: int | None) -> Iterator:
        """Apply pending attach/detach ops whose event-time threshold
        the upcoming batch reaches (``None`` = end of stream: apply
        everything).  Yields backfilled window emissions from attaches."""
        with self._ops_lock:
            if not self._pending_ops:
                return
            ready, rest = [], []
            for op in self._pending_ops:
                when = op[2]
                if upcoming_ts is None or when is None or when <= upcoming_ts:
                    ready.append(op)
                else:
                    rest.append(op)
            self._pending_ops = rest
        for kind, payload, _when in ready:
            if kind == "attach":
                for b in self.attach(payload):
                    yield b
            else:
                self.detach(payload)

    def attach(self, sub: SliceSubscriber, *, warm: bool = True) -> list:
        """Attach a subscriber mid-stream and warm it from the slice
        store's retained partials.  Returns the backfilled window
        emissions (windows the gcd slices already cover exactly).

        Exactness contract: the first exact window j* is the max of the
        joiner's anchor at the stream's first event time and the ceiling
        of the highest prune/late-drop floor ever applied — everything
        from j* on folds from complete slices, so backfilled windows and
        all later ones are byte-identical to an independent from-start
        pipeline.  A joiner whose residual predicate opens a NEW filter
        class has no retained partials to warm from, so its j* addition-
        ally clamps past the max event time already ingested."""
        from denormalized_tpu import obs

        if sub.tag in self._departed:
            # replay idempotence: this tag joined AND left before the
            # restored checkpoint — re-applying its registration
            # schedule must not re-attach it
            return []
        if any(s.tag == sub.tag for s in self._subs):
            raise PlanError(f"subscriber tag {sub.tag} is already attached")
        self._prepare_subscriber(sub, grow=False)
        if sub.length_ms % self.unit_ms or sub.slide_ms % self.unit_ms:
            raise PlanError(
                f"window {sub.length_ms}ms/{sub.slide_ms}ms does not "
                f"tile the shared group's {self.unit_ms}ms slices — "
                "attach requires length and slide divisible by the unit"
            )
        needed = set(sa.components_for(sub.agg_specs))
        if not needed <= set(self._components):
            raise PlanError(
                "subscriber aggregates need slice components "
                f"{sorted(needed - set(self._components))} the shared "
                "store does not maintain"
            )
        sig = "" if sub.filter_expr is None else repr(sub.filter_expr)
        fresh = all(c.sig != sig for c in self._classes)
        cls = self._class_for(sub)
        if fresh:
            stash = self._orphan_class_arrays.pop(cls.sig, None)
            if stash is not None:
                # a restored checkpoint carried this class's partials
                # (its only owners were late joiners) — revive them
                # along with the class's exactness floor (the original
                # class may itself have opened mid-stream)
                st_arrays, st_ngroups, st_efu = stash
                cls.store.restore_arrays(st_arrays, st_ngroups)
                cls.exact_from_unit = st_efu
            elif self._max_ts is not None:
                # genuinely new residual class mid-stream: its partials
                # only cover data from here on — record the floor ON
                # THE CLASS so later same-class joiners inherit it
                cls.exact_from_unit = self._max_ts // self.unit_ms + 1
        self._subs.append(sub)
        q = len(self._subs) - 1
        self._sub_class.append(cls)
        self._sub_cost_ms.append(0.0)
        self._next_win.append(None)
        self._first_exact.append(None)
        self._obs_mq_emit_lag.append(
            obs.gauge(
                "dnz_mq_emit_lag_ms",
                query=sub.label if sub.label is not None else f"q{sub.tag}",
            )
        )
        self._obs_mq_live.set(len(self._subs))
        self._obs_slice_subs.set(len(self._subs))
        emitted: list = []
        rec = self._orphans.pop(sub.tag, None)
        if rec is not None:
            if (
                rec["filter_sig"] != sub.filter_sig
                or int(rec["length_ms"]) != sub.length_ms
                or int(rec["slide_ms"]) != sub.slide_ms
            ):
                from denormalized_tpu.common.errors import StateError

                raise StateError(
                    f"re-attaching subscriber tag {sub.tag} does not "
                    "match its checkpointed record (filter signature or "
                    "window spec changed)"
                )
            # replayed registration after restore: adopt the cursor the
            # checkpoint carried — no backfill, those windows emitted
            nw = rec["next_win"]
            self._next_win[q] = None if nw is None else int(nw)
            fe = rec.get("first_exact")
            self._first_exact[q] = None if fe is None else int(fe)
        elif warm and self._first_ts is not None:
            j_star = self._anchor(q, self._first_ts)
            if self._exact_floor_unit is not None:
                j_star = max(
                    j_star,
                    -(-(self._exact_floor_unit * self.unit_ms)
                      // sub.slide_ms),
                )
            if cls.exact_from_unit is not None:
                # the class opened mid-stream: no partials predate its
                # creation, so exactness starts past everything the
                # stream had ingested by then — for every member, not
                # just the joiner that opened it
                j_star = max(
                    j_star,
                    -(-(cls.exact_from_unit * self.unit_ms)
                      // sub.slide_ms),
                )
            self._first_exact[q] = j_star
            wm = self._wm_floor(q)
            if wm is not None and wm > j_star:
                for j in range(j_star, wm):
                    b = self._emit_window(q, j)
                    if b is not None:
                        emitted.append(b)
                self._obs_mq_backfill.add(wm - j_star)
            self._next_win[q] = max(j_star, wm) if wm is not None else j_star
        return emitted

    def detach(self, tag: int) -> None:
        """Detach a subscriber; drop its cursor, ledger, and any filter
        class no survivor owns, then prune slices only it retained."""
        matches = [q for q, s in enumerate(self._subs) if s.tag == tag]
        if not matches:
            if tag in self._departed:
                return  # replayed detach of an already-departed tag
            raise PlanError(f"no attached subscriber has tag {tag}")
        if len(self._subs) == 1:
            raise PlanError(
                "cannot detach the last subscriber — stop the pipeline "
                "instead"
            )
        q = matches[0]
        self._departed.add(tag)
        del self._subs[q]
        del self._next_win[q]
        del self._sub_class[q]
        del self._sub_cost_ms[q]
        del self._first_exact[q]
        del self._obs_mq_emit_lag[q]
        owned = {id(c) for c in self._sub_class}
        self._classes = [c for c in self._classes if id(c) in owned]
        floor = self._floor_unit()
        if floor is not None:
            self._metrics["slices_pruned"] += sum(
                cls.store.prune(floor) for cls in self._classes
            )
        self._obs_mq_live.set(len(self._subs))
        self._obs_slice_subs.set(len(self._subs))
        if self.on_detach is not None:
            self.on_detach(tag)

    def set_ingest_pred(self, pred: Expr | None) -> None:
        """Narrow (or clear) the ingest predicate applied to arriving
        rows before intern/value-eval/sort.  The caller (the
        multi-query runtime's base re-derivation) guarantees every
        surviving subscriber's full predicate implies ``pred``, so
        dropped rows are rows NO survivor's class would keep — partials
        stay byte-identical while rows only the departed base member
        could reach stop paying the ingest path.  Takes effect at the
        next batch; the re-derivation fires at a batch boundary (the
        detach drain), so no in-flight batch is split."""
        self._ingest_pred = pred

    # ------------------------------------------------------------------
    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        m = dict(self._metrics)
        m["slices_live"] = max(len(c.store) for c in self._classes)
        m["subscribers"] = len(self._subs)
        m["filter_classes"] = len(self._classes)
        return m

    def _label(self):
        specs = ", ".join(
            f"{s.length_ms}ms/{s.slide_ms}ms" for s in self._subs[:4]
        )
        if len(self._subs) > 4:
            specs += f", … ({len(self._subs)} total)"
        return (
            f"SliceWindowExec(unit={self.unit_ms}ms, windows=[{specs}], "
            f"groups=[{', '.join(g.name for g in self.group_exprs)}])"
        )

    # -- state observatory (obs/statewatch.py) ---------------------------
    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm

        live_keys = len(self._interner) if self._interner is not None else (
            1 if self._max_ts is not None else 0
        )
        store_bytes = sum(c.store.nbytes() for c in self._classes)
        # the approx_top_k value→vid interner is NOT a sketch plane: it
        # grows with distinct VALUES (one dict entry + boxed key each),
        # the one cardinality-linear structure on the sketch lane —
        # account it like any other interned key so budget/growth
        # verdicts see it (docs/approx_aggregates.md)
        vid_keys = (
            len(self._vid_interner) if self._vid_interner is not None else 0
        )
        units = self._store.live_units()
        oldest = units[0] * self.unit_ms if units else None
        wm = self._watermark_ms
        info = {
            "op": "slice_window",
            "state_bytes": store_bytes
            + (live_keys + vid_keys) * swm.KEY_EST_BYTES,
            "vid_interner_keys": vid_keys,
            "slice_store_bytes": store_bytes,
            # exact sketch-plane bytes (already inside state_bytes via
            # the stores' nbytes) — O(1) per gid in value cardinality,
            # the doctor's contrast to unbounded exact accumulators
            "sketch_bytes": sum(
                c.store.sketch_nbytes() for c in self._classes
            ),
            "live_keys": live_keys,
            "slot_capacity": int(self._store.capacity),
            "slot_live": live_keys,
            "slices_live": max(len(c.store) for c in self._classes),
            "subscribers": len(self._subs),
            "filter_classes": len(self._classes),
            "retention_unit_ms": max(s.length_ms for s in self._subs),
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
        }
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - int(oldest))
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        if self._interner is None:
            return [(None, self._sw, None)]
        from denormalized_tpu.ops.interner import display_keys

        return [
            (None, self._sw, lambda g: display_keys(self._interner, g))
        ]

    # -- cursor / retention arithmetic -----------------------------------
    def _anchor(self, q: int, ts_min: int) -> int:
        """First window of subscriber ``q`` overlapping ``ts_min``."""
        sub = self._subs[q]
        return (ts_min - sub.length_ms) // sub.slide_ms + 1

    def _wm_floor(self, q: int) -> int | None:
        if self._watermark_ms is None:
            return None
        sub = self._subs[q]
        return int(
            watermark_floor(self._watermark_ms, sub.length_ms, sub.slide_ms)
        )

    def _floor_unit(self) -> int | None:
        """Lowest slice unit any subscriber's open (or rebased-open)
        window may still fold — rows below it are late for EVERY
        subscriber and slices below it are prunable.  Under per-
        partition watermarks a slower partition may rebase a cursor
        back down to the watermark floor, so the floor accounts for
        that exactly like StreamingWindowExec's rebase rule."""
        lows = []
        for q, sub in enumerate(self._subs):
            nw = self._next_win[q]
            if nw is None:
                return None
            low_j = nw
            if self._src_watermarks:
                f = self._wm_floor(q)
                if f is not None:
                    low_j = min(low_j, f)
            lows.append(low_j * sub.slide_ms // self.unit_ms)
        return min(lows)

    # -- per-batch processing --------------------------------------------
    def _eval_values(
        self, batch: RecordBatch, n: int
    ) -> tuple[np.ndarray, np.ndarray, dict[int, np.ndarray]]:
        from denormalized_tpu.logical.expr import column_validity

        V = max(len(self._value_exprs), 1)
        values64 = np.zeros((n, V), dtype=np.float64)
        colvalid = np.ones((n, V), dtype=bool)
        aux: dict[int, np.ndarray] = {}
        for j, e in enumerate(self._value_exprs):
            tr = self._value_transforms[j]
            if tr in ("hash", "vid"):
                # sketch source lanes: never forced through float64 (a
                # string column would not survive the cast, and an
                # int64 beyond 2^53 would lose identity).  The f64
                # matrix column stays 0 — no scalar component reads it.
                m = column_validity(e, batch)
                if m is not None:
                    colvalid[:, j] = m
                col = e.eval(batch)
                if tr == "hash":
                    from denormalized_tpu.ops.sketches import stable_hash64

                    aux[j] = stable_hash64(col, m)
                else:
                    aux[j] = self._intern_vids(col, m, n)
                continue
            raw = np.asarray(e.eval(batch), dtype=np.float64)
            m = column_validity(e, batch)
            if m is not None:
                colvalid[:, j] = m
            if tr is not None:
                # variance pivot shift: identical rule to
                # StreamingWindowExec — the first finite valid value ever
                # seen for this expression pins K, so shared and
                # independent runs over the same feed shift identically
                key = repr(e)
                K = self._var_shift.get(key)
                if K is None:
                    valid_vals = raw[colvalid[:, j]] if m is not None else raw
                    finite = valid_vals[np.isfinite(valid_vals)]
                    if len(finite):
                        K = float(finite[0])
                        self._var_shift[key] = K
                    else:
                        K = 0.0
                raw = raw - K
                if tr == "shift_sq":
                    raw = raw * raw
            values64[:, j] = raw
        return values64, colvalid, aux

    def _intern_vids(
        self, col, valid: np.ndarray | None, n: int
    ) -> np.ndarray:
        """Dense value ids for an approx_top_k lane: the exec-owned
        single-column interner assigns ids in first-seen order over the
        SHARED (base-predicate) row stream, so every subscriber's
        summary speaks the same id space and ``keys_of`` recovers the
        original values at emission.  Invalid rows get id 0 and are
        masked out by ``colvalid`` before the sketch kernel runs."""
        if self._vid_interner is None:
            self._vid_interner = GroupInterner(1)
        out = np.zeros(n, dtype=np.int64)
        if valid is None:
            out[:] = self._vid_interner.intern([col])
        else:
            idx = np.flatnonzero(valid)
            if len(idx):
                sub = (
                    col.take(idx)
                    if hasattr(col, "take")
                    else np.asarray(col)[idx]
                )
                out[idx] = self._vid_interner.intern([sub])
        return out

    def _process_batch(self, batch: RecordBatch) -> Iterator:
        n = batch.num_rows
        if n == 0:
            return
        t_shared0 = time.perf_counter()
        self._metrics["rows_in"] += n
        self._metrics["batches_in"] += 1
        self._obs_rows_in.add(n)
        ts = np.asarray(
            batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
        )
        units = ts // self.unit_ms
        ts_min = int(ts.min())
        ts_max = int(ts.max())
        if self._first_ts is None:
            self._first_ts = ts_min
        self._max_ts = ts_max if self._max_ts is None else max(
            self._max_ts, ts_max
        )
        for q in range(len(self._subs)):
            if self._next_win[q] is None:
                self._next_win[q] = self._anchor(q, ts_min)
            elif self._src_watermarks:
                # per-partition watermarks: a slower partition's earlier
                # windows stay legitimate until the min-driven watermark
                # closes them — rebase the cursor down to the watermark
                # floor (never below it: those windows genuinely emitted),
                # and never below the subscriber's exactness floor: a
                # mid-stream joiner's windows before first_exact can
                # never fold completely (its class has no partials
                # there), and out-of-order upstream output — a shared
                # join's probe emissions carry retained rows older than
                # the frontier — would otherwise drag the cursor into
                # that inexact range and emit truncated windows
                anchor = self._anchor(q, ts_min)
                if anchor < self._next_win[q]:
                    f = self._wm_floor(q)
                    new = anchor if f is None else max(anchor, f)
                    fe = self._first_exact[q]
                    if fe is not None:
                        new = max(new, fe)
                    if new < self._next_win[q]:
                        self._next_win[q] = new
        if self._ingest_pred is not None:
            # re-derived (narrowed) base after the weakest member left:
            # rows failing every survivor's predicate skip the ingest
            # path entirely.  Watermark/cursor bookkeeping above already
            # used the FULL batch's ts_min/ts_max, so trigger timing is
            # unchanged — only the accumulated row set narrows, and
            # those rows belonged to no survivor's class.
            keep_in = np.asarray(self._ingest_pred.eval(batch), dtype=bool)
            if not keep_in.all():
                if not keep_in.any():
                    if not self._src_watermarks:
                        if (
                            self._watermark_ms is None
                            or ts_min > self._watermark_ms
                        ):
                            self._watermark_ms = ts_min
                    yield from self._trigger()
                    return
                batch = batch.take(np.nonzero(keep_in)[0])
                ts = ts[keep_in]
                units = units[keep_in]
                n = batch.num_rows
        self._metrics["rows_ingested"] += n
        # group ids for every row (keys intern regardless of lateness,
        # matching StreamingWindowExec)
        if self._grouped:
            key_cols = [g.eval(batch) for g in self.group_exprs]
            gid = self._interner.intern(key_cols)
            ngroups = len(self._interner)
        else:
            gid = np.zeros(n, dtype=np.int32)
            ngroups = 1
        self._sw.update(gid)
        values64, colvalid, aux = self._eval_values(batch, n)

        # residual re-filter masks, one per filter class, computed over
        # the FULL batch (row-lane predicates need batch alignment)
        # before the late-drop subset below
        t_ref0 = time.perf_counter()
        masks: list[np.ndarray | None] = []
        for cls in self._classes:
            if cls.pred is None:
                masks.append(None)
            elif cls.gid_lane:
                self._extend_gid_pass(cls, ngroups)
                masks.append(refilter_gid_mask(gid, cls.gid_pass))
            else:
                masks.append(np.asarray(cls.pred.eval(batch), dtype=bool))
        refilter_ms = (time.perf_counter() - t_ref0) * 1e3
        if len(self._classes) > 1 or self._classes[0].pred is not None:
            self._obs_refilter_ms.observe(refilter_ms)

        floor = self._floor_unit()
        if floor is not None:
            if (
                self._exact_floor_unit is None
                or floor > self._exact_floor_unit
            ):
                self._exact_floor_unit = floor
            keep = units >= floor
            n_late = int((~keep).sum())
            if n_late:
                self._metrics["late_rows"] += n_late
                self._obs_late.add(n_late)
                units = units[keep]
                gid = gid[keep]
                values64 = values64[keep]
                colvalid = colvalid[keep]
                aux = {j: a[keep] for j, a in aux.items()}
                masks = [m if m is None else m[keep] for m in masks]
        # shared ingest cost (intern + sketch + value eval + masks)
        # splits evenly; per-class accumulate cost charges that class's
        # subscribers — the ledger behind shared_fractions()
        nsubs = max(len(self._subs), 1)
        shared_ms = (time.perf_counter() - t_shared0) * 1e3 / nsubs
        for q in range(len(self._subs)):
            self._sub_cost_ms[q] += shared_ms
        if len(units):
            # one stable (unit, gid) sort serves every sort-lane class:
            # a residual mask applied in sorted order IS that class's
            # own stable sort, so N filter classes pay one argsort
            order_full: np.ndarray | None = None
            for ci, cls in enumerate(self._classes):
                t_cls0 = time.perf_counter()
                m = masks[ci]
                if m is None:
                    if cls.store.add_only:
                        # dense bincount lane — no sort to share
                        cls.store.accumulate(
                            units, gid, values64, colvalid, ngroups
                        )
                    else:
                        if order_full is None:
                            order_full = shared_sort_order(units, gid)
                        cls.store.accumulate(
                            units, gid, values64, colvalid, ngroups,
                            order=order_full, aux=aux,
                        )
                    rows = len(units)
                else:
                    if not m.any():
                        continue
                    if order_full is None:
                        order_full = shared_sort_order(units, gid)
                    o_sub = masked_sorted_order(order_full, m)
                    cls.store.accumulate(
                        units, gid, values64, colvalid, ngroups,
                        order=o_sub, aux=aux,
                    )
                    rows = len(o_sub)
                if ci == 0:
                    self._obs_slice_rows.add(rows)
                cls.rows_kept += rows
                cls_ms = (time.perf_counter() - t_cls0) * 1e3
                owners = [
                    q for q, c in enumerate(self._sub_class) if c is cls
                ]
                if owners:
                    share = cls_ms / len(owners)
                    for q in owners:
                        self._sub_cost_ms[q] += share
            if self._sketch_specs:
                rows_t = sum(c.store.sketch_rows for c in self._classes)
                upd_t = sum(c.store.sketch_update_s for c in self._classes)
                self._obs_sketch_rows.add(rows_t - self._sketch_rows_seen)
                self._obs_sketch_ms.observe(
                    (upd_t - self._sketch_upd_seen) * 1e3
                )
                self._sketch_rows_seen = rows_t
                self._sketch_upd_seen = upd_t
                self._obs_sketch_bytes.set(
                    sum(c.store.sketch_nbytes() for c in self._classes)
                )

        if not self._src_watermarks:
            if self._watermark_ms is None or ts_min > self._watermark_ms:
                self._watermark_ms = ts_min
        yield from self._trigger()

    # -- emission --------------------------------------------------------
    def _trigger(self) -> Iterator:
        if self._obs_wm_lag and self._watermark_ms is not None:
            lag = time.time() * 1000.0 - self._watermark_ms
            self._obs_wm_lag.set(lag)
            self._obs_wm_lag_hist.observe(lag)
        if self._watermark_ms is None:
            return
        for q, sub in enumerate(self._subs):
            nw = self._next_win[q]
            if nw is None:
                continue
            wm_win = self._wm_floor(q)
            while nw < wm_win:
                b = self._emit_window(q, nw)
                nw += 1
                if b is not None:
                    yield b
            self._next_win[q] = nw
        floor = self._floor_unit()
        if floor is not None:
            if (
                self._exact_floor_unit is None
                or floor > self._exact_floor_unit
            ):
                self._exact_floor_unit = floor
            self._metrics["slices_pruned"] += sum(
                cls.store.prune(floor) for cls in self._classes
            )
        # gauge AFTER the prune: the exported number is the retained
        # slice count the catalog text promises, not the pre-prune peak
        self._obs_slice_units.set(
            max(len(cls.store) for cls in self._classes)
        )

    def _emit_window(self, q: int, j: int):
        sub = self._subs[q]
        t0 = time.perf_counter()
        u0 = j * sub.slide_ms // self.unit_ms
        u1 = (j * sub.slide_ms + sub.length_ms) // self.unit_ms
        rows = self._sub_class[q].store.fold(u0, u1)
        self._metrics["slice_folds"] += 1
        self._obs_folds.add(1)
        if rows is None:
            self._sub_cost_ms[q] += (time.perf_counter() - t0) * 1e3
            return None
        ngroups = len(self._interner) if self._grouped else 1
        counts = rows[sa.ROW_COUNT.label]
        active = counts > 0
        active[ngroups:] = False
        if not active.any():
            self._sub_cost_ms[q] += (time.perf_counter() - t0) * 1e3
            return None
        gids = np.nonzero(active)[0].astype(np.int32)
        if sub.has_sketch:
            finals = [
                self._finalize_sketch(s, rows, gids)
                if s[0] == "sketch"
                else sa.finalize([s], rows, active)[0]
                for s in sub.agg_specs
            ]
        else:
            finals = sa.finalize(sub.agg_specs, rows, active)
        batch = self._assemble_emission(sub, j, gids, finals)
        if self._obs_mq_emit_lag[q]:
            self._obs_mq_emit_lag[q].set(
                time.time() * 1000.0 - (j * sub.slide_ms + sub.length_ms)
            )
        fold_ms = (time.perf_counter() - t0) * 1e3
        self._sub_cost_ms[q] += fold_ms
        self._obs_fold_ms.observe(fold_ms)
        self._metrics["windows_emitted"] += 1
        if self._tagged:
            return SubscriberBatch(sub.tag, batch)
        return batch

    def _finalize_sketch(
        self, spec_t: tuple, rows: dict, gids: np.ndarray
    ) -> np.ndarray:
        """Finalize one sketch aggregate's column for the active gids of
        an emitted window from the folded sketch planes."""
        spec = spec_t[2]
        if spec.kind == "hll":
            return spec.finalize(rows, gids)
        if spec.kind == "kll":
            return spec.finalize_quantile(rows, gids, spec_t[3])
        # topk: per-gid [[value, count], …] rows, count-desc — value ids
        # translate back through the exec's value interner
        ka = rows[f"{spec.sid}|k"]
        ca = rows[f"{spec.sid}|c"]
        ea = rows[f"{spec.sid}|e"]
        out = np.empty(len(gids), dtype=object)
        for i, gi in enumerate(np.asarray(gids).tolist()):
            vids, cnts, _errs = spec.cell_top(ka[gi], ca[gi], ea[gi])
            if len(vids):
                kv = self._vid_interner.keys_of(vids.astype(np.int64))[0]
                vals = np.asarray(kv).tolist()
            else:
                vals = []
            out[i] = [
                [v, int(c)] for v, c in zip(vals, cnts.tolist())
            ]
        return out

    def _assemble_emission(
        self, sub: SliceSubscriber, j: int, gids: np.ndarray, finals: list
    ) -> RecordBatch:
        in_schema = self.input_op.schema
        cols: list[np.ndarray] = []
        if self._grouped:
            key_vals = self._interner.keys_of(gids)
            for g, kv in zip(self.group_exprs, key_vals):
                f = g.out_field(in_schema)
                if f.dtype.is_numeric:
                    kv = np.asarray(kv.tolist(), dtype=f.dtype.to_numpy())
                cols.append(kv)
        for a, arr in zip(sub.aggr_exprs, finals):
            f = a.out_field(in_schema)
            arr = np.asarray(arr)
            if f.dtype.is_numeric:
                # LIST outputs (approx_top_k) stay object arrays — same
                # rule UdafWindowExec applies to non-numeric finals
                arr = arr.astype(f.dtype.to_numpy())
            cols.append(arr)
        m = len(gids)
        start = np.full(m, j * sub.slide_ms, dtype=np.int64)
        end = np.full(
            m, j * sub.slide_ms + sub.length_ms, dtype=np.int64
        )
        cols += [start, end, start.copy()]
        self._obs_windows.add(1)
        if self._obs_emit_lag:
            self._obs_emit_lag.observe(
                time.time() * 1000.0 - (j * sub.slide_ms + sub.length_ms)
            )
        if self._dr_lineage is not None:
            # shared pipelines tag the emission with the subscriber's
            # doctor query id so GET /queries/<id>/lineage attributes
            # the chain to the right member query
            qids = getattr(self, "_dr_mq_qids", None)
            self._dr_lineage.emitted(
                self._dr_node_id,
                j * sub.slide_ms,
                j * sub.slide_ms + sub.length_ms,
                query=None if qids is None else qids.get(sub.tag),
            )
        return RecordBatch(sub.schema, cols)

    def _output_low_watermark(self, hint_ts: int) -> int:
        lows = []
        for q, sub in enumerate(self._subs):
            lows.append(
                window_output_low_watermark(
                    self._next_win[q],
                    sub.slide_ms,
                    sub.length_ms,
                    hint_ts,
                    wm_ms=self._watermark_ms if self._src_watermarks else None,
                )
            )
        return min(lows)

    # -- checkpointing ----------------------------------------------------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        self._ckpt = (coord, f"slice_{node_id}")
        self._restore()

    def _snapshot(self, epoch: int) -> None:
        from denormalized_tpu.state.serialization import pack_snapshot

        coord, key = self._ckpt
        ngroups = len(self._interner) if self._grouped else 1
        meta = {
            "epoch": epoch,
            "unit_ms": self.unit_ms,
            "next_win": list(self._next_win),
            "watermark_ms": self._watermark_ms,
            "src_watermarks": self._src_watermarks,
            "max_ts": self._max_ts,
            "var_shift": dict(self._var_shift),
            "ngroups": ngroups,
            "interner": self._interner.snapshot() if self._grouped else None,
            # top-k value-id space: ids are first-seen-order dense, so
            # the summaries in the planes are meaningless without it
            "vid_interner": (
                self._vid_interner.snapshot()
                if self._vid_interner is not None
                else None
            ),
            # live-registration payload: per-subscriber identity records
            # (tag + filter signature + join cursor) and the per-class
            # array layout — restore matches cursors by TAG, never by
            # position, so a mid-stream joiner's kill/restore is exact
            "first_ts": self._first_ts,
            "exact_floor_unit": self._exact_floor_unit,
            "departed": sorted(self._departed),
            "classes": [cls.sig for cls in self._classes],
            "class_exact_from": [
                cls.exact_from_unit for cls in self._classes
            ],
            "subs": [
                {
                    "tag": sub.tag,
                    "label": sub.label,
                    "length_ms": sub.length_ms,
                    "slide_ms": sub.slide_ms,
                    "filter_sig": sub.filter_sig,
                    "class_sig": self._sub_class[q].sig,
                    "next_win": self._next_win[q],
                    "first_exact": self._first_exact[q],
                }
                for q, sub in enumerate(self._subs)
            ],
        }
        arrays: dict[str, np.ndarray] = {}
        for ci, cls in enumerate(self._classes):
            for k, arr in cls.store.snapshot_arrays(ngroups).items():
                # class 0 keeps the legacy un-prefixed key space so
                # pre-subsumption snapshots stay restorable
                arrays[k if ci == 0 else f"c{ci}|{k}"] = arr
        coord.put_snapshot(key, epoch, pack_snapshot(meta, arrays))

    def _restore(self) -> None:
        from denormalized_tpu.common.errors import StateError
        from denormalized_tpu.state.serialization import unpack_snapshot

        coord, key = self._ckpt
        blob = coord.get_snapshot(key)
        if blob is None:
            return
        meta, arrays = unpack_snapshot(blob)
        if int(meta["unit_ms"]) != self.unit_ms:
            raise StateError(
                f"slice snapshot unit {meta['unit_ms']}ms does not match "
                f"the plan's {self.unit_ms}ms — the subscriber set changed "
                "incompatibly since the checkpoint"
            )
        self._watermark_ms = meta["watermark_ms"]
        self._src_watermarks = bool(meta.get("src_watermarks"))
        self._max_ts = meta["max_ts"]
        self._var_shift = dict(meta.get("var_shift") or {})
        vsnap = meta.get("vid_interner")
        if vsnap is not None:
            self._vid_interner = GroupInterner.restore(vsnap)
        self._first_ts = meta.get("first_ts")
        efu = meta.get("exact_floor_unit")
        self._exact_floor_unit = None if efu is None else int(efu)
        self._departed = {int(t) for t in meta.get("departed") or ()}
        if self._grouped and meta["interner"] is not None:
            self._interner = GroupInterner.restore(meta["interner"])
            # gid-lane pass bits re-derive lazily from the restored
            # interner on the next batch
            for cls in self._classes:
                cls.gid_pass = np.zeros(0, dtype=bool)
        ngroups = int(meta.get("ngroups") or 1)
        recs = meta.get("subs")
        if recs is None:
            # legacy (pre-live-registration) snapshot: positional
            # cursors, single filter class
            self._next_win = [
                None if v is None else int(v) for v in meta["next_win"]
            ]
            if len(self._next_win) != len(self._subs):
                raise StateError(
                    f"slice snapshot carries {len(self._next_win)} emission "
                    f"cursors but the plan subscribes "
                    f"{len(self._subs)} queries"
                )
            self._store.restore_arrays(arrays, ngroups)
            return
        by_tag = {int(r["tag"]): r for r in recs}
        for q, sub in enumerate(self._subs):
            rec = by_tag.pop(sub.tag, None)
            if rec is None:
                raise StateError(
                    f"slice snapshot has no cursor for subscriber tag "
                    f"{sub.tag} — subscribers present at restore must "
                    "predate the checkpoint (late joiners attach AFTER "
                    "restore and adopt their cursor then)"
                )
            if (
                rec["filter_sig"] != sub.filter_sig
                or int(rec["length_ms"]) != sub.length_ms
                or int(rec["slide_ms"]) != sub.slide_ms
            ):
                raise StateError(
                    f"subscriber tag {sub.tag} does not match its "
                    "snapshot record (filter signature or window spec "
                    "changed since the checkpoint)"
                )
            nw = rec["next_win"]
            self._next_win[q] = None if nw is None else int(nw)
            fe = rec.get("first_exact")
            self._first_exact[q] = None if fe is None else int(fe)
        # cursors of subscribers not in the current plan: retained for
        # adoption when the (replayed) live registration re-attaches
        self._orphans = by_tag
        if by_tag:
            from denormalized_tpu.runtime.tracing import logger

            logger.info(
                "slice restore retained %d orphan cursor(s) awaiting "
                "re-attachment: %s", len(by_tag),
                ", ".join(
                    f"tag {t} ({r.get('label') or 'unlabeled'}, "
                    f"class {r.get('class_sig') or '?'})"
                    for t, r in sorted(by_tag.items())
                ),
            )
        # split arrays back into per-class stores by snapshot class
        # index, matching classes by residual signature
        snap_sigs = [str(s) for s in meta.get("classes") or [""]]
        snap_efu = meta.get("class_exact_from") or [None] * len(snap_sigs)
        per_class: list[dict[str, np.ndarray]] = [
            {} for _ in snap_sigs
        ]
        for k, arr in arrays.items():
            if k.startswith("c") and "|" in k:
                head, rest = k.split("|", 1)
                if head[1:].isdigit() and "|" in rest:
                    per_class[int(head[1:])][rest] = arr
                    continue
            per_class[0][k] = arr
        live_sigs = {cls.sig: cls for cls in self._classes}
        self._orphan_class_arrays = {}
        for ci, sig in enumerate(snap_sigs):
            efu = snap_efu[ci] if ci < len(snap_efu) else None
            efu = None if efu is None else int(efu)
            cls = live_sigs.get(sig)
            if cls is not None:
                cls.store.restore_arrays(per_class[ci], ngroups)
                cls.exact_from_unit = efu
            else:
                # no live subscriber folds this class yet — stash the
                # partials (and the class's exactness floor) for the
                # re-attaching joiner to revive
                self._orphan_class_arrays[sig] = (per_class[ci], ngroups, efu)

    # -- stream loop -----------------------------------------------------
    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu.runtime.tracing import span

        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # dnzlint: allow(unguarded) boundary fast-path peek: truthiness load is atomic and _drain_ops re-checks _pending_ops under _ops_lock; a stale miss just defers the op to the next batch boundary
                if self._pending_ops and item.num_rows:
                    # live attach/detach lands at batch boundaries; ops
                    # carrying an event-time threshold fire exactly when
                    # the stream reaches it (deterministic under replay)
                    up = int(
                        np.asarray(
                            item.column(CANONICAL_TIMESTAMP_COLUMN),
                            dtype=np.int64,
                        ).min()
                    )
                    yield from self._drain_ops(up)
                t0 = time.perf_counter()
                with span(
                    "slice_window.process_batch",
                    op=self.name,
                    rows=item.num_rows,
                ):
                    out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item
                        continue
                    if (
                        self._watermark_ms is None
                        or item.ts_ms > self._watermark_ms
                    ):
                        self._watermark_ms = item.ts_ms
                        yield from self._trigger()
                    yield WatermarkHint(
                        min(
                            item.ts_ms,
                            self._output_low_watermark(item.ts_ms),
                        ),
                        kind="partition",
                    )
                    continue
                if (
                    self._watermark_ms is None
                    or item.ts_ms > self._watermark_ms
                ):
                    self._watermark_ms = item.ts_ms
                    yield from self._trigger()
                yield WatermarkHint(
                    min(item.ts_ms, self._output_low_watermark(item.ts_ms))
                )
            elif isinstance(item, Marker):
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                yield item
            elif isinstance(item, EndOfStream):
                yield from self._drain_ops(None)
                if self.emit_on_close and self._max_ts is not None:
                    for q, sub in enumerate(self._subs):
                        nw = self._next_win[q]
                        if nw is None:
                            continue
                        while nw * sub.slide_ms <= self._max_ts:
                            b = self._emit_window(q, nw)
                            nw += 1
                            if b is not None:
                                yield b
                        self._next_win[q] = nw
                yield EOS
                return
