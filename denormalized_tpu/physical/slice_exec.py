"""Slice-folding window operator — one ingest, N concurrent window specs.

``SliceWindowExec`` is the execution half of the multi-query engine
(docs/multi_query.md): it accumulates per-(group, slide-unit) partials
ONCE per input batch into a shared :class:`SliceStore` and lets every
subscribed window spec — tumbling, sliding, and any number of
concurrently registered queries over the same source+filter+keys — fold
its windows from those partials.  A sliding window composes ``L/g``
slice partials by exact addition (the constant-pivot Chan combine; see
ops/slice_store.py) instead of re-aggregating raw rows per overlap, and
``N`` shareable queries pay ONE ingest+decode+aggregate pass instead of
``N``.

Two modes:

- **single-subscriber** (the planner's ``EngineConfig(slice_windows=
  True)`` fast path): a drop-in for :class:`StreamingWindowExec` on
  foldable aggregates — emissions flow as plain RecordBatches;
- **tagged** (the multi-query runtime): emissions are wrapped in
  :class:`SubscriberBatch` carrying the subscriber index, and the
  shared drive loop (runtime/multi_query.py) routes each to its query's
  sink.

Checkpointing takes ONE snapshot per epoch: the slice store's partials,
the shared interner, the watermark, and every subscriber's emission
cursor — restore resumes each query exactly where its own emissions
stopped (per-query cursors, one store).  Semantics (late drop against
the per-subscriber open floor, per-partition watermark rebase, idle
hints, EOS flush) mirror StreamingWindowExec so a query moved between
the operators sees the same windows.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import VAR_KINDS, AggregateExpr, Expr
from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.ops.interner import GroupInterner
from denormalized_tpu.ops.slice_store import SliceStore
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)
from denormalized_tpu.physical.window_exec import (
    watermark_floor,
    window_output_low_watermark,
)

#: aggregate kinds whose windows fold exactly from slice partials
FOLDABLE_KINDS = frozenset(
    ("count", "sum", "min", "max", "avg") + tuple(VAR_KINDS)
)


@dataclass
class SliceSubscriber:
    """One window spec folding from the shared slice store."""

    aggr_exprs: list
    length_ms: int
    slide_ms: int
    tag: int = 0
    label: str | None = None
    # filled by the operator: per-subscriber agg specs over the SHARED
    # value-column space, and the output schema
    agg_specs: list = field(default_factory=list)
    schema: Schema | None = None


class SubscriberBatch:
    """A tagged emission in multi-subscriber (shared) mode: ``tag`` is
    the subscriber index, ``batch`` the per-query emission."""

    __slots__ = ("tag", "batch")

    def __init__(self, tag: int, batch: RecordBatch) -> None:
        self.tag = tag
        self.batch = batch


class SliceWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        subscribers: list[SliceSubscriber],
        *,
        emit_on_close: bool = True,
        tagged: bool = False,
        unit_ms: int | None = None,
        sort_lane: bool = False,
        name: str = "slice_window",
    ) -> None:
        if not subscribers:
            raise PlanError("SliceWindowExec needs at least one subscriber")
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self._subs = list(subscribers)
        self.emit_on_close = emit_on_close
        self._tagged = tagged
        self.name = name

        in_schema = input_op.schema
        # shared deduped value-column space across ALL subscribers (the
        # StreamingWindowExec dedup, widened to N aggregate lists)
        self._value_exprs: list[Expr] = []
        self._value_transforms: list[str | None] = []
        self._var_shift: dict[str, float] = {}
        keys: dict = {}

        def col_idx(e: Expr, transform: str | None) -> int:
            k = (transform, repr(e))
            if k not in keys:
                keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
                self._value_transforms.append(transform)
            return keys[k]

        unit = 0
        for sub in self._subs:
            sub.slide_ms = int(sub.slide_ms) if sub.slide_ms else int(
                sub.length_ms
            )
            sub.length_ms = int(sub.length_ms)
            if sub.length_ms <= 0 or sub.slide_ms <= 0:
                raise PlanError(
                    "window length and slide must be positive for the "
                    f"slice path (got L={sub.length_ms} S={sub.slide_ms})"
                )
            unit = math.gcd(unit, math.gcd(sub.length_ms, sub.slide_ms))
            specs: list[tuple] = []
            for a in sub.aggr_exprs:
                if not isinstance(a, AggregateExpr):
                    raise PlanError(f"{a!r} is not an aggregate expression")
                if a.kind not in FOLDABLE_KINDS:
                    raise PlanError(
                        f"aggregate kind {a.kind!r} does not fold from "
                        "slice partials (UDAFs run in UdafWindowExec)"
                    )
                if a.arg is None:
                    specs.append((a.kind, None))
                elif a.kind in sa.VAR_KINDS:
                    specs.append(
                        (
                            a.kind,
                            col_idx(a.arg, "shift"),
                            col_idx(a.arg, "shift_sq"),
                        )
                    )
                else:
                    specs.append((a.kind, col_idx(a.arg, None)))
            sub.agg_specs = specs
            fields = [g.out_field(in_schema) for g in self.group_exprs]
            fields += [a.out_field(in_schema) for a in sub.aggr_exprs]
            fields += [
                Field(
                    WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False
                ),
                Field(
                    WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False
                ),
                Field(
                    CANONICAL_TIMESTAMP_COLUMN,
                    DataType.TIMESTAMP_MS,
                    nullable=False,
                ),
            ]
            sub.schema = Schema(fields)
        if unit_ms is not None:
            # explicit slice-width pin: the fold grouping is part of a
            # query's numeric contract (f64 sums round per fold tree),
            # so an independent oracle comparing against a shared run
            # pins the shared group's unit here.  Any divisor of the
            # natural gcd is valid — slices still tile every window.
            if unit_ms <= 0 or unit % int(unit_ms):
                raise PlanError(
                    f"slice_unit_ms={unit_ms} must divide every "
                    f"subscriber's window length and slide (gcd {unit}ms)"
                )
            unit = int(unit_ms)
        self.unit_ms = unit
        all_specs = [s for sub in self._subs for s in sub.agg_specs]
        self._components = tuple(sa.components_for(all_specs))
        self._store = SliceStore(
            self._components, self.unit_ms, force_sort_lane=sort_lane
        )

        self._grouped = len(self.group_exprs) > 0
        self._interner = (
            GroupInterner(len(self.group_exprs)) if self._grouped else None
        )
        # single-subscriber mode exposes that subscriber's schema (the
        # planner drop-in contract); tagged mode has no single schema —
        # downstream is the multi-query drive loop, not an operator
        self.schema = self._subs[0].schema

        # streaming state
        self._ckpt: tuple | None = None
        self._next_win: list[int | None] = [None] * len(self._subs)
        self._watermark_ms: int | None = None
        self._src_watermarks = False
        self._max_ts: int | None = None
        self._metrics = {
            "rows_in": 0,
            "batches_in": 0,
            "late_rows": 0,
            "windows_emitted": 0,
            "slice_folds": 0,
            "slices_live": 0,
            "slices_pruned": 0,
            "subscribers": len(self._subs),
        }

        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("slice_window")
        self._sw = statewatch.make_watch("slice_window")
        self._obs_late = obs.counter("dnz_late_rows_total", op="slice_window")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="slice_window"
        )
        self._obs_emit_lag = obs.histogram(
            "dnz_emit_event_lag_ms", op="slice_window"
        )
        self._obs_wm_lag = obs.gauge("dnz_watermark_lag_ms", op="slice_window")
        self._obs_wm_lag_hist = obs.histogram(
            "dnz_watermark_lag_hist_ms", op="slice_window"
        )
        self._obs_slice_rows = obs.counter("dnz_slice_rows_total")
        self._obs_slice_units = obs.gauge("dnz_slice_units")
        self._obs_slice_subs = obs.gauge("dnz_slice_subscribers")
        self._obs_folds = obs.counter("dnz_slice_folds_total")
        self._obs_fold_ms = obs.histogram("dnz_slice_fold_ms")
        self._obs_slice_subs.set(len(self._subs))
        # per-subscriber emit lag: the aggregate histogram above sums
        # over subscribers, so a slow query hiding inside a shared
        # pipeline was unattributable — one gauge per query fixes that
        self._obs_mq_emit_lag = [
            obs.gauge(
                "dnz_mq_emit_lag_ms",
                query=sub.label if sub.label is not None else f"q{q}",
            )
            for q, sub in enumerate(self._subs)
        ]

    # ------------------------------------------------------------------
    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        m = dict(self._metrics)
        m["slices_live"] = len(self._store)
        return m

    def _label(self):
        specs = ", ".join(
            f"{s.length_ms}ms/{s.slide_ms}ms" for s in self._subs[:4]
        )
        if len(self._subs) > 4:
            specs += f", … ({len(self._subs)} total)"
        return (
            f"SliceWindowExec(unit={self.unit_ms}ms, windows=[{specs}], "
            f"groups=[{', '.join(g.name for g in self.group_exprs)}])"
        )

    # -- state observatory (obs/statewatch.py) ---------------------------
    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm

        live_keys = len(self._interner) if self._interner is not None else (
            1 if self._max_ts is not None else 0
        )
        store_bytes = self._store.nbytes()
        units = self._store.live_units()
        oldest = units[0] * self.unit_ms if units else None
        wm = self._watermark_ms
        info = {
            "op": "slice_window",
            "state_bytes": store_bytes + live_keys * swm.KEY_EST_BYTES,
            "slice_store_bytes": store_bytes,
            "live_keys": live_keys,
            "slot_capacity": int(self._store.capacity),
            "slot_live": live_keys,
            "slices_live": len(self._store),
            "subscribers": len(self._subs),
            "retention_unit_ms": max(s.length_ms for s in self._subs),
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
        }
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - int(oldest))
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        if self._interner is None:
            return [(None, self._sw, None)]
        from denormalized_tpu.ops.interner import display_keys

        return [
            (None, self._sw, lambda g: display_keys(self._interner, g))
        ]

    # -- cursor / retention arithmetic -----------------------------------
    def _anchor(self, q: int, ts_min: int) -> int:
        """First window of subscriber ``q`` overlapping ``ts_min``."""
        sub = self._subs[q]
        return (ts_min - sub.length_ms) // sub.slide_ms + 1

    def _wm_floor(self, q: int) -> int | None:
        if self._watermark_ms is None:
            return None
        sub = self._subs[q]
        return int(
            watermark_floor(self._watermark_ms, sub.length_ms, sub.slide_ms)
        )

    def _floor_unit(self) -> int | None:
        """Lowest slice unit any subscriber's open (or rebased-open)
        window may still fold — rows below it are late for EVERY
        subscriber and slices below it are prunable.  Under per-
        partition watermarks a slower partition may rebase a cursor
        back down to the watermark floor, so the floor accounts for
        that exactly like StreamingWindowExec's rebase rule."""
        lows = []
        for q, sub in enumerate(self._subs):
            nw = self._next_win[q]
            if nw is None:
                return None
            low_j = nw
            if self._src_watermarks:
                f = self._wm_floor(q)
                if f is not None:
                    low_j = min(low_j, f)
            lows.append(low_j * sub.slide_ms // self.unit_ms)
        return min(lows)

    # -- per-batch processing --------------------------------------------
    def _eval_values(
        self, batch: RecordBatch, n: int
    ) -> tuple[np.ndarray, np.ndarray]:
        from denormalized_tpu.logical.expr import column_validity

        V = max(len(self._value_exprs), 1)
        values64 = np.zeros((n, V), dtype=np.float64)
        colvalid = np.ones((n, V), dtype=bool)
        for j, e in enumerate(self._value_exprs):
            raw = np.asarray(e.eval(batch), dtype=np.float64)
            m = column_validity(e, batch)
            if m is not None:
                colvalid[:, j] = m
            tr = self._value_transforms[j]
            if tr is not None:
                # variance pivot shift: identical rule to
                # StreamingWindowExec — the first finite valid value ever
                # seen for this expression pins K, so shared and
                # independent runs over the same feed shift identically
                key = repr(e)
                K = self._var_shift.get(key)
                if K is None:
                    valid_vals = raw[colvalid[:, j]] if m is not None else raw
                    finite = valid_vals[np.isfinite(valid_vals)]
                    if len(finite):
                        K = float(finite[0])
                        self._var_shift[key] = K
                    else:
                        K = 0.0
                raw = raw - K
                if tr == "shift_sq":
                    raw = raw * raw
            values64[:, j] = raw
        return values64, colvalid

    def _process_batch(self, batch: RecordBatch) -> Iterator:
        n = batch.num_rows
        if n == 0:
            return
        self._metrics["rows_in"] += n
        self._metrics["batches_in"] += 1
        self._obs_rows_in.add(n)
        ts = np.asarray(
            batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
        )
        units = ts // self.unit_ms
        ts_min = int(ts.min())
        ts_max = int(ts.max())
        self._max_ts = ts_max if self._max_ts is None else max(
            self._max_ts, ts_max
        )
        for q in range(len(self._subs)):
            if self._next_win[q] is None:
                self._next_win[q] = self._anchor(q, ts_min)
            elif self._src_watermarks:
                # per-partition watermarks: a slower partition's earlier
                # windows stay legitimate until the min-driven watermark
                # closes them — rebase the cursor down to the watermark
                # floor (never below it: those windows genuinely emitted)
                anchor = self._anchor(q, ts_min)
                if anchor < self._next_win[q]:
                    f = self._wm_floor(q)
                    new = anchor if f is None else max(anchor, f)
                    if new < self._next_win[q]:
                        self._next_win[q] = new
        # group ids for every row (keys intern regardless of lateness,
        # matching StreamingWindowExec)
        if self._grouped:
            key_cols = [g.eval(batch) for g in self.group_exprs]
            gid = self._interner.intern(key_cols)
            ngroups = len(self._interner)
        else:
            gid = np.zeros(n, dtype=np.int32)
            ngroups = 1
        self._sw.update(gid)
        values64, colvalid = self._eval_values(batch, n)

        floor = self._floor_unit()
        if floor is not None:
            keep = units >= floor
            n_late = int((~keep).sum())
            if n_late:
                self._metrics["late_rows"] += n_late
                self._obs_late.add(n_late)
                units = units[keep]
                gid = gid[keep]
                values64 = values64[keep]
                colvalid = colvalid[keep]
        if len(units):
            self._store.accumulate(units, gid, values64, colvalid, ngroups)
            self._obs_slice_rows.add(len(units))

        if not self._src_watermarks:
            if self._watermark_ms is None or ts_min > self._watermark_ms:
                self._watermark_ms = ts_min
        yield from self._trigger()

    # -- emission --------------------------------------------------------
    def _trigger(self) -> Iterator:
        if self._obs_wm_lag and self._watermark_ms is not None:
            lag = time.time() * 1000.0 - self._watermark_ms
            self._obs_wm_lag.set(lag)
            self._obs_wm_lag_hist.observe(lag)
        if self._watermark_ms is None:
            return
        for q, sub in enumerate(self._subs):
            nw = self._next_win[q]
            if nw is None:
                continue
            wm_win = self._wm_floor(q)
            while nw < wm_win:
                b = self._emit_window(q, nw)
                nw += 1
                if b is not None:
                    yield b
            self._next_win[q] = nw
        floor = self._floor_unit()
        if floor is not None:
            self._metrics["slices_pruned"] += self._store.prune(floor)
        # gauge AFTER the prune: the exported number is the retained
        # slice count the catalog text promises, not the pre-prune peak
        self._obs_slice_units.set(len(self._store))

    def _emit_window(self, q: int, j: int):
        sub = self._subs[q]
        t0 = time.perf_counter()
        u0 = j * sub.slide_ms // self.unit_ms
        u1 = (j * sub.slide_ms + sub.length_ms) // self.unit_ms
        rows = self._store.fold(u0, u1)
        self._metrics["slice_folds"] += 1
        self._obs_folds.add(1)
        if rows is None:
            return None
        ngroups = len(self._interner) if self._grouped else 1
        counts = rows[sa.ROW_COUNT.label]
        active = counts > 0
        active[ngroups:] = False
        if not active.any():
            return None
        gids = np.nonzero(active)[0].astype(np.int32)
        finals = sa.finalize(sub.agg_specs, rows, active)
        batch = self._assemble_emission(sub, j, gids, finals)
        if self._obs_mq_emit_lag[q]:
            self._obs_mq_emit_lag[q].set(
                time.time() * 1000.0 - (j * sub.slide_ms + sub.length_ms)
            )
        self._obs_fold_ms.observe((time.perf_counter() - t0) * 1e3)
        self._metrics["windows_emitted"] += 1
        if self._tagged:
            return SubscriberBatch(sub.tag, batch)
        return batch

    def _assemble_emission(
        self, sub: SliceSubscriber, j: int, gids: np.ndarray, finals: list
    ) -> RecordBatch:
        in_schema = self.input_op.schema
        cols: list[np.ndarray] = []
        if self._grouped:
            key_vals = self._interner.keys_of(gids)
            for g, kv in zip(self.group_exprs, key_vals):
                f = g.out_field(in_schema)
                if f.dtype.is_numeric:
                    kv = np.asarray(kv.tolist(), dtype=f.dtype.to_numpy())
                cols.append(kv)
        for a, arr in zip(sub.aggr_exprs, finals):
            f = a.out_field(in_schema)
            cols.append(np.asarray(arr).astype(f.dtype.to_numpy()))
        m = len(gids)
        start = np.full(m, j * sub.slide_ms, dtype=np.int64)
        end = np.full(
            m, j * sub.slide_ms + sub.length_ms, dtype=np.int64
        )
        cols += [start, end, start.copy()]
        self._obs_windows.add(1)
        if self._obs_emit_lag:
            self._obs_emit_lag.observe(
                time.time() * 1000.0 - (j * sub.slide_ms + sub.length_ms)
            )
        if self._dr_lineage is not None:
            self._dr_lineage.emitted(
                self._dr_node_id,
                j * sub.slide_ms,
                j * sub.slide_ms + sub.length_ms,
            )
        return RecordBatch(sub.schema, cols)

    def _output_low_watermark(self, hint_ts: int) -> int:
        lows = []
        for q, sub in enumerate(self._subs):
            lows.append(
                window_output_low_watermark(
                    self._next_win[q],
                    sub.slide_ms,
                    sub.length_ms,
                    hint_ts,
                    wm_ms=self._watermark_ms if self._src_watermarks else None,
                )
            )
        return min(lows)

    # -- checkpointing ----------------------------------------------------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        self._ckpt = (coord, f"slice_{node_id}")
        self._restore()

    def _snapshot(self, epoch: int) -> None:
        from denormalized_tpu.state.serialization import pack_snapshot

        coord, key = self._ckpt
        ngroups = len(self._interner) if self._grouped else 1
        meta = {
            "epoch": epoch,
            "unit_ms": self.unit_ms,
            "next_win": list(self._next_win),
            "watermark_ms": self._watermark_ms,
            "src_watermarks": self._src_watermarks,
            "max_ts": self._max_ts,
            "var_shift": dict(self._var_shift),
            "ngroups": ngroups,
            "interner": self._interner.snapshot() if self._grouped else None,
        }
        coord.put_snapshot(
            key, epoch,
            pack_snapshot(meta, self._store.snapshot_arrays(ngroups)),
        )

    def _restore(self) -> None:
        from denormalized_tpu.state.serialization import unpack_snapshot

        coord, key = self._ckpt
        blob = coord.get_snapshot(key)
        if blob is None:
            return
        meta, arrays = unpack_snapshot(blob)
        if int(meta["unit_ms"]) != self.unit_ms:
            from denormalized_tpu.common.errors import StateError

            raise StateError(
                f"slice snapshot unit {meta['unit_ms']}ms does not match "
                f"the plan's {self.unit_ms}ms — the subscriber set changed "
                "incompatibly since the checkpoint"
            )
        self._next_win = [
            None if v is None else int(v) for v in meta["next_win"]
        ]
        if len(self._next_win) != len(self._subs):
            from denormalized_tpu.common.errors import StateError

            raise StateError(
                f"slice snapshot carries {len(self._next_win)} emission "
                f"cursors but the plan subscribes {len(self._subs)} queries"
            )
        self._watermark_ms = meta["watermark_ms"]
        self._src_watermarks = bool(meta.get("src_watermarks"))
        self._max_ts = meta["max_ts"]
        self._var_shift = dict(meta.get("var_shift") or {})
        if self._grouped and meta["interner"] is not None:
            self._interner = GroupInterner.restore(meta["interner"])
        self._store.restore_arrays(arrays, int(meta.get("ngroups") or 1))

    # -- stream loop -----------------------------------------------------
    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu.runtime.tracing import span

        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                t0 = time.perf_counter()
                with span(
                    "slice_window.process_batch",
                    op=self.name,
                    rows=item.num_rows,
                ):
                    out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item
                        continue
                    if (
                        self._watermark_ms is None
                        or item.ts_ms > self._watermark_ms
                    ):
                        self._watermark_ms = item.ts_ms
                        yield from self._trigger()
                    yield WatermarkHint(
                        min(
                            item.ts_ms,
                            self._output_low_watermark(item.ts_ms),
                        ),
                        kind="partition",
                    )
                    continue
                if (
                    self._watermark_ms is None
                    or item.ts_ms > self._watermark_ms
                ):
                    self._watermark_ms = item.ts_ms
                    yield from self._trigger()
                yield WatermarkHint(
                    min(item.ts_ms, self._output_low_watermark(item.ts_ms))
                )
            elif isinstance(item, Marker):
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                yield item
            elif isinstance(item, EndOfStream):
                if self.emit_on_close and self._max_ts is not None:
                    for q, sub in enumerate(self._subs):
                        nw = self._next_win[q]
                        if nw is None:
                            continue
                        while nw * sub.slide_ms <= self._max_ts:
                            b = self._emit_window(q, nw)
                            nw += 1
                            if b is not None:
                                yield b
                        self._next_win[q] = nw
                yield EOS
                return
