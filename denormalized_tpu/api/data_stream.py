"""DataStream — the fluent user API.

Method-for-method capability mirror of the reference's ``DataStream``
(crates/core/src/datastream.rs) and its Python wrapper
(py-denormalized/python/denormalized/data_stream.py): select / filter /
with_column / drop_columns / join / window / print_stream / sink.  Plan
building is lazy; execution happens in the sink methods, wrapped in the
orchestrator lifecycle when checkpointing is on (with_orchestrator,
datastream.rs:244-307).
"""

from __future__ import annotations

from typing import Callable, Iterator, Sequence

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.logical.expr import AggregateExpr, Column, Expr, col


class DataStream:
    def __init__(self, plan: lp.LogicalPlan, ctx) -> None:
        self._plan = plan
        self._ctx = ctx

    # -- schema (strips internal metadata, datastream.rs:199-210) --------
    def schema(self) -> Schema:
        return self._plan.schema.without_internal()

    def __repr__(self) -> str:
        """String representation (reference data_stream.py:28-34)."""
        fields = ", ".join(
            f"{f.name}: {f.dtype.name.lower()}" for f in self.schema()
        )
        return f"DataStream[{type(self._plan).__name__}]({fields})"

    def __str__(self) -> str:
        return self.__repr__()

    def print_schema(self) -> "DataStream":
        """Print the schema and return self for chaining
        (reference data_stream.py:187-193)."""
        print(self.schema())
        return self

    def logical_plan(self) -> lp.LogicalPlan:
        return self._plan

    def _wrap(self, plan: lp.LogicalPlan) -> "DataStream":
        return DataStream(plan, self._ctx)

    # -- transforms ------------------------------------------------------
    def select(self, *exprs: Expr | str) -> "DataStream":
        # the reference wrapper takes a LIST (`select(expr_list)`,
        # py-denormalized data_stream.py:52) — accept both spellings so a
        # migrating user's call works unchanged
        if len(exprs) == 1 and isinstance(exprs[0], (list, tuple)):
            exprs = tuple(exprs[0])
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        return self._wrap(lp.Project(self._plan, exprs))

    def select_columns(self, *names: str) -> "DataStream":
        return self.select(*[col(n) for n in names])

    def filter(self, predicate: Expr) -> "DataStream":
        return self._wrap(lp.Filter(self._plan, predicate))

    def with_column(self, name: str, expr: Expr) -> "DataStream":
        """Add or replace a column (datastream.rs:107-114)."""
        exprs: list[Expr] = []
        replaced = False
        for f in self._plan.schema.without_internal():
            if f.name == name:
                exprs.append(expr.alias(name))
                replaced = True
            else:
                exprs.append(col(f.name))
        if not replaced:
            exprs.append(expr.alias(name))
        return self.select(*exprs)

    def with_column_renamed(self, old: str, new: str) -> "DataStream":
        exprs = [
            col(f.name).alias(new) if f.name == old else col(f.name)
            for f in self._plan.schema.without_internal()
        ]
        return self.select(*exprs)

    def drop_columns(self, *names: str) -> "DataStream":
        # reference spelling is a list (`drop_columns(columns)`,
        # py-denormalized data_stream.py:95) — accept both
        if len(names) == 1 and isinstance(names[0], (list, tuple)):
            names = tuple(names[0])
        keep = [
            col(f.name)
            for f in self._plan.schema.without_internal()
            if f.name not in set(names)
        ]
        return self.select(*keep)

    # -- windows (datastream.rs:178-197) ---------------------------------
    def window(
        self,
        group_exprs: Sequence[Expr | str],
        aggr_exprs: Sequence[AggregateExpr],
        window_length_ms: int,
        slide_ms: int | None = None,
    ) -> "DataStream":
        """Windowed aggregation: tumbling when ``slide_ms`` is None,
        sliding otherwise (mirrors the reference signature where slide=None
        means tumbling, logical_plan/mod.rs:29-58)."""
        group_exprs = [col(g) if isinstance(g, str) else g for g in group_exprs]
        for a in aggr_exprs:
            if not isinstance(a, AggregateExpr):
                raise PlanError(f"{a!r} is not an aggregate expression")
        wt = lp.WindowType.TUMBLING if slide_ms is None else lp.WindowType.SLIDING
        return self._wrap(
            lp.StreamingWindow(
                self._plan,
                list(group_exprs),
                list(aggr_exprs),
                wt,
                int(window_length_ms),
                int(slide_ms) if slide_ms is not None else None,
            )
        )

    def session_window(
        self,
        group_exprs: Sequence[Expr | str],
        aggr_exprs: Sequence[AggregateExpr],
        gap_ms: int,
    ) -> "DataStream":
        """Session windows — declared in the reference's WindowType but left
        `todo!()` (streaming_window.rs session arm); implemented here."""
        group_exprs = [col(g) if isinstance(g, str) else g for g in group_exprs]
        return self._wrap(
            lp.StreamingWindow(
                self._plan,
                list(group_exprs),
                list(aggr_exprs),
                lp.WindowType.SESSION,
                int(gap_ms),
                None,
            )
        )

    # -- joins (datastream.rs:126-177, Joinable trait :379-395) ----------
    # reference JoinType spellings (datastream.rs:129 exposes DataFusion's
    # enum) → our JoinKind; right-side existence joins normalize to the
    # left-side kind with swapped inputs, so the exec implements only two
    _JOIN_TYPE_ALIASES = {
        "semi": "left_semi", "leftsemi": "left_semi",
        "left_semi": "left_semi",
        "anti": "left_anti", "leftanti": "left_anti",
        "left_anti": "left_anti",
        "rightsemi": "right_semi", "right_semi": "right_semi",
        "rightanti": "right_anti", "right_anti": "right_anti",
    }

    def join(
        self,
        right: "DataStream",
        join_type: str = "inner",
        left_cols: Sequence[str] = (),
        right_cols: Sequence[str] = (),
        filter: Expr | None = None,
        band: "lp.JoinBand | tuple | None" = None,
    ) -> "DataStream":
        """Stream-stream join on equi keys, optionally banded.

        ``band`` adds an interval/range predicate alongside the equi
        keys: ``(left_expr, right_expr, lower_ms, upper_ms)`` (column
        names accepted for the exprs) matches a pair iff ``left -
        right`` lands in ``[lower_ms, upper_ms]`` inclusive, ``None``
        bounds open.  Band expressions evaluate on their OWN side, so
        a band over event time works even though the right side's
        timestamp never appears in the output — the enrichment /
        temporal-correlation join (``ts BETWEEN a AND b``)."""
        jt = self._JOIN_TYPE_ALIASES.get(
            join_type.lower().replace(" ", ""), join_type.lower()
        )
        if jt in ("right_semi", "right_anti"):
            # RightSemi(a,b) == LeftSemi(b,a): swap inputs and key lists
            return right.join(
                self,
                jt.replace("right", "left"),
                list(right_cols),
                list(left_cols),
                filter,
                band=None if band is None else self._flip_band(band),
            )
        if band is not None and not isinstance(band, lp.JoinBand):
            le, re_, lo, hi = band
            band = lp.JoinBand(
                col(le) if isinstance(le, str) else le,
                col(re_) if isinstance(re_, str) else re_,
                lo,
                hi,
            )
        return self._wrap(
            lp.Join(
                self._plan,
                right._plan,
                lp.JoinKind(jt),
                list(left_cols),
                list(right_cols),
                filter,
                band,
            )
        )

    @staticmethod
    def _flip_band(band) -> "lp.JoinBand":
        """Mirror a band across a left/right input swap: ``l - r ∈ [a,
        b]`` becomes ``r - l ∈ [-b, -a]``."""
        if not isinstance(band, lp.JoinBand):
            le, re_, lo, hi = band
            band = lp.JoinBand(
                col(le) if isinstance(le, str) else le,
                col(re_) if isinstance(re_, str) else re_,
                lo,
                hi,
            )
        return lp.JoinBand(
            band.right_expr,
            band.left_expr,
            None if band.upper_ms is None else -band.upper_ms,
            None if band.lower_ms is None else -band.lower_ms,
        )

    def join_on(
        self, right: "DataStream", join_type: str, on_exprs: Sequence[Expr]
    ) -> "DataStream":
        """Join on arbitrary binary expressions (datastream.rs:126-148).

        ``expr_l == expr_r`` conjuncts where each side references exactly
        one input become equi-keys: non-column sides are computed into
        hidden key columns on their input, the hash join runs on those,
        and the hidden columns are dropped from the output.  Inclusive
        inequality conjuncts comparing a pure-left expression against a
        pure-right expression (± a literal) — the ``l.ts >= r.ts - a``
        / ``l.ts <= r.ts + b`` BETWEEN shape — lower to ONE banded
        predicate evaluated per side before pair materialization
        (lp.JoinBand), which is also the only way to bound against the
        right side's canonical timestamp (it never reaches the pair
        schema).  Any other conjunct (strict inequality, non-equi op,
        or an expression mixing both inputs) becomes a residual filter
        evaluated on matched pairs — the same lowering DataFusion
        applies to the reference's ``join_on``."""
        from denormalized_tpu.logical.expr import BinaryExpr, Literal

        left_names = set(self.schema().names)
        right_names = set(right.schema().names)

        def side_of(e: Expr) -> str | None:
            refs = e.columns_referenced()
            if not refs:
                return None  # literal: computable on either side
            if refs <= left_names and not (refs & right_names):
                return "l"
            if refs <= right_names and not (refs & left_names):
                return "r"
            return None  # ambiguous or mixed — not a separable equi side

        def shifted(e: Expr) -> tuple[Expr, float, str | None]:
            """Decompose ``e`` as ``base + const`` with ``base`` purely
            one-sided: peels one additive numeric literal off a
            BinaryExpr (the ``r.ts + 5000`` shape)."""
            if isinstance(e, BinaryExpr) and e.op in ("+", "-"):
                if isinstance(e.right, Literal) and isinstance(
                    e.right.value, (int, float)
                ):
                    c = float(e.right.value)
                    return e.left, c if e.op == "+" else -c, side_of(e.left)
                if e.op == "+" and isinstance(e.left, Literal) and isinstance(
                    e.left.value, (int, float)
                ):
                    return e.right, float(e.left.value), side_of(e.right)
            return e, 0.0, side_of(e)

        def band_constraint(e: Expr):
            """``(l_expr, r_expr, lower, upper)`` for one inclusive
            inequality conjunct over opposite sides, else None.  Strict
            ops stay residual: the band contract is inclusive and the
            operands may be floats, so ``<`` cannot be rewritten."""
            if not isinstance(e, BinaryExpr) or e.op not in ("<=", ">="):
                return None
            a, ca, sa_ = shifted(e.left)
            b, cb, sb_ = shifted(e.right)
            if {sa_, sb_} != {"l", "r"}:
                return None
            # normalize to  left_expr - right_expr  (op)  const
            if sa_ == "l":
                le_, re2, const = a, b, cb - ca
                op = e.op
            else:
                le_, re2, const = b, a, ca - cb
                op = "<=" if e.op == ">=" else ">="
            if op == "<=":
                return (le_, re2, None, const)
            return (le_, re2, const, None)

        lds, rds = self, right
        lcols: list[str] = []
        rcols: list[str] = []
        hidden: list[str] = []
        residual: Expr | None = None
        band_key = None
        band_exprs = None
        band_lo: float | None = None
        band_hi: float | None = None
        for i, e in enumerate(on_exprs):
            sides = None
            if isinstance(e, BinaryExpr) and e.op == "==":
                if isinstance(e.left, Column) and isinstance(e.right, Column):
                    # plain column == column: key names verbatim (including
                    # the shared-name form col('k') == col('k'), which Join
                    # resolves as a once-appearing shared equi-key)
                    lcols.append(e.left.name)
                    rcols.append(e.right.name)
                    continue
                sl, sr = side_of(e.left), side_of(e.right)
                if {sl, sr} == {"l", "r"}:
                    sides = (e.left, e.right) if sl == "l" else (e.right, e.left)
                elif sl == "l" and sr is None and not e.right.columns_referenced():
                    sides = (e.left, e.right)
                elif sl == "r" and sr is None and not e.left.columns_referenced():
                    sides = (e.right, e.left)
            if sides is None:
                bc = band_constraint(e)
                if bc is not None:
                    le_, re2, lo, hi = bc
                    key = (repr(le_), repr(re2))
                    if band_key is None or key == band_key:
                        band_key = key
                        band_exprs = (le_, re2)
                        if lo is not None:
                            band_lo = (
                                lo if band_lo is None else max(band_lo, lo)
                            )
                        if hi is not None:
                            band_hi = (
                                hi if band_hi is None else min(band_hi, hi)
                            )
                        continue
                    # the exec carries ONE band; a second distinct
                    # expression pair stays a residual pair filter
                residual = e if residual is None else (residual & e)
                continue
            le, re_ = sides
            if isinstance(le, Column):
                lcols.append(le.name)
            else:
                name = f"__join_lk_{i}__"
                lds = lds.with_column(name, le)
                lcols.append(name)
                hidden.append(name)
            if isinstance(re_, Column):
                rcols.append(re_.name)
            else:
                name = f"__join_rk_{i}__"
                rds = rds.with_column(name, re_)
                rcols.append(name)
                hidden.append(name)
        if not lcols:
            raise PlanError(
                "join_on needs at least one separable equi conjunct "
                "(expr_over_left == expr_over_right) — a pure theta join "
                "over unbounded streams has no hash key to bound state"
            )
        band = None
        if band_exprs is not None:
            band = lp.JoinBand(
                band_exprs[0], band_exprs[1], band_lo, band_hi
            )
        out = lds.join(
            rds, join_type, lcols, rcols, filter=residual, band=band
        )
        return out.drop_columns(*hidden) if hidden else out

    # -- introspection ---------------------------------------------------
    def print_plan(self) -> "DataStream":
        print(self._plan.display())
        return self

    def optimized_plan(self) -> lp.LogicalPlan:
        """The logical plan after the optimizer pass (what will execute)."""
        from denormalized_tpu.logical.optimizer import optimize

        return optimize(
            self._plan, getattr(self._ctx.config, "optimizer", True)
        )

    def _physical_display(self, plan: lp.LogicalPlan) -> str:
        from denormalized_tpu.planner.planner import Planner

        return Planner(self._ctx.config).create_physical_plan(plan).display()

    def print_physical_plan(self) -> "DataStream":
        print(self._physical_display(self.optimized_plan()))
        return self

    def explain(self, analyze: bool = False) -> "DataStream":
        """Print logical plan, optimized plan, and physical plan — the
        datafusion ``explain`` analog.  With ``analyze=True``, execute the
        stream to completion against a discard sink and print the physical
        plan annotated with each operator's runtime metrics (rows, batches,
        compute time) — the EXPLAIN ANALYZE analog of the reference's
        engine substrate (DataFusion; per-operator MetricsSet exposure at
        streaming_window.rs:491).  Like ``collect``, analyze requires a
        bounded source."""
        opt = self.optimized_plan()
        print("== logical plan ==")
        print(self._plan.display())
        print("== optimized plan ==")
        print(opt.display())
        if not analyze:
            print("== physical plan ==")
            print(self._physical_display(opt))
            return self
        from denormalized_tpu.physical.simple_execs import CallbackSink

        # introspection must not mutate durable recovery state: with
        # checkpointing live, this run would commit epochs (and source
        # offsets) under the SAME node-id keys the real pipeline uses —
        # the next real run would restore at explain's cut.  The override
        # is per-execution (threaded through execute_plan), not a flip of
        # the Context's shared EngineConfig, which concurrent streams on
        # the same Context read mid-run.
        self._execute(CallbackSink(lambda _b: None), checkpoint=False)
        print("== physical plan (analyzed) ==")
        print(self._ctx._last_physical.display(with_metrics=True))
        return self

    def explain_analyze(self, print_output: bool = True) -> str:
        """Execute against a discard sink and return the pipeline
        doctor's annotated plan: every node with live rows/s, batch-time
        share of wall, upstream queue-wait, prefetch queue depth and
        watermark lag, plus the ranked bottleneck attribution — the
        slowest stage is NAMED under a documented rule
        (obs/doctor/attribution.py), not left for the reader to infer.

        Like ``explain(analyze=True)`` this needs a bounded source and
        runs with checkpointing forced off (an introspection run must
        not commit epochs under the real pipeline's node-id keys).  The
        same report is available LIVE for any running query at
        ``GET /queries/<id>/plan`` on the Prometheus HTTP server."""
        from denormalized_tpu.physical.simple_execs import CallbackSink

        self._execute(CallbackSink(lambda _b: None), checkpoint=False)
        handle = getattr(self._ctx, "_last_doctor", None)
        if handle is not None:
            text = handle.render()
        else:  # doctor_enabled=False: fall back to the metrics dump
            text = self._ctx._last_physical.display(with_metrics=True)
        if print_output:
            print(text)
        return text

    # -- execution -------------------------------------------------------
    def _execute(self, sink, checkpoint=None) -> None:
        from denormalized_tpu.runtime.executor import execute_plan

        execute_plan(lp.Sink(self._plan, sink), self._ctx, checkpoint)

    def print_stream(self) -> None:
        """Execute, printing rows as JSON (datastream.rs:311-339)."""
        from denormalized_tpu.physical.simple_execs import PrintSink

        self._execute(PrintSink())

    def sink(
        self, fn: Callable[[RecordBatch], None], *, as_pyarrow: bool = False
    ) -> None:
        """Execute, calling ``fn`` per emitted batch (the PyO3 sink_python
        path, py-denormalized/src/datastream.rs:229-270).  With
        ``as_pyarrow=True`` the callback receives ``pyarrow.RecordBatch``
        objects — the exact shape the reference hands its Python callbacks
        (datastream.rs:244-252 converts via to_pyarrow under the GIL)."""
        from denormalized_tpu.physical.simple_execs import CallbackSink

        if as_pyarrow:
            user_fn = fn
            fn = lambda b: user_fn(b.to_pyarrow())  # noqa: E731

        self._execute(CallbackSink(fn))

    def sink_kafka(self, bootstrap_servers: str, topic: str) -> None:
        """Execute, producing JSON rows to a Kafka topic
        (datastream.rs:346-374)."""
        from denormalized_tpu.sources.kafka import KafkaSinkWriter

        self._execute(KafkaSinkWriter(bootstrap_servers, topic))

    def collect(self) -> RecordBatch:
        """Execute a bounded stream to completion and return all emitted
        rows — the integration-test seam the reference lacks (SURVEY.md §4)."""
        from denormalized_tpu.physical.simple_execs import CollectSink

        s = CollectSink()
        self._execute(s)
        if not s.batches:
            return RecordBatch.empty(self._plan.schema)
        return s.result()

    def stream(self) -> Iterator[RecordBatch]:
        """Incremental pull-based execution (DataStream::execute_stream)."""
        from denormalized_tpu.runtime.executor import stream_plan

        yield from stream_plan(self._plan, self._ctx)
