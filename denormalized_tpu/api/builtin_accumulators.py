"""Built-in non-decomposable aggregates, implemented on the Accumulator
protocol and routed through :class:`UdafWindowExec`'s host frame path.

These are the aggregates that cannot decompose into the device kernel's
running components (sum/count/min/max/moments): exact order statistics,
value collection, and sketches.  The reference gets them from DataFusion
(`array_agg` with checkpoint serialization is prototyped at
crates/core/src/accumulators/serializable_accumulator.rs:10-68); ours
checkpoint through the same ``state()``/``merge()`` contract every user
UDAF uses, so kill/restore covers them for free.
"""

from __future__ import annotations

import hashlib
import math

import numpy as np

from denormalized_tpu.api.udaf import Accumulator


def _jsonable_scalar(x):
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, (np.str_,)):
        return str(x)
    return x


class ArrayAggAccumulator(Accumulator):
    """Collect every value into a list (reference
    serializable_accumulator.rs:10-68 — the one accumulator it ships
    checkpoint serialization for)."""

    def __init__(self):
        self.values: list = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(_jsonable_scalar(v) for v in col.tolist())

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def evaluate(self):
        return list(self.values)


class MedianAccumulator(Accumulator):
    """Exact median (DataFusion `median`); state is the value list."""

    def __init__(self):
        self.values: list[float] = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(float(v) for v in np.asarray(col, np.float64))

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def evaluate(self):
        return float(np.median(self.values)) if self.values else math.nan


class FirstValueAccumulator(Accumulator):
    """First value in arrival order (DataFusion `first_value` with no
    explicit ordering: pick-any-deterministic)."""

    def __init__(self):
        self.value = None
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        if not self.seen and len(col):
            self.value = _jsonable_scalar(col[0])
            self.seen = True

    def merge(self, state) -> None:
        if not self.seen and state[1]:
            self.value, self.seen = state[0], True

    def state(self) -> list:
        return [self.value, self.seen]

    def evaluate(self):
        return self.value


class LastValueAccumulator(Accumulator):
    def __init__(self):
        self.value = None
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        if len(col):
            self.value = _jsonable_scalar(col[-1])
            self.seen = True

    def merge(self, state) -> None:
        if state[1]:
            self.value, self.seen = state[0], True

    def state(self) -> list:
        return [self.value, self.seen]

    def evaluate(self):
        return self.value


class CountDistinctAccumulator(Accumulator):
    """Exact distinct count (DataFusion ``count(distinct x)``); state is
    the value set (jsonable list)."""

    def __init__(self):
        self.seen: set = set()

    def update(self, col: np.ndarray) -> None:
        self.seen.update(_jsonable_scalar(v) for v in col.tolist())

    def merge(self, state) -> None:
        self.seen.update(state[0])

    def state(self) -> list:
        return [list(self.seen)]

    def evaluate(self) -> int:
        return len(self.seen)


class PercentileContAccumulator(Accumulator):
    """Exact continuous percentile (DataFusion ``approx_percentile_cont``'s
    exact cousin): linear interpolation over the sorted values."""

    def __init__(self, q: float):
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"percentile must be in [0, 1], got {q}")
        self.q = q
        self.values: list[float] = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(float(v) for v in np.asarray(col, np.float64))

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def evaluate(self):
        if not self.values:
            return math.nan
        return float(np.quantile(self.values, self.q))


class ApproxDistinctAccumulator(Accumulator):
    """HyperLogLog distinct-count sketch (DataFusion `approx_distinct`).

    2^11 registers (~1.6% standard error), 64-bit stable hash
    (blake2b — NOT Python's salted ``hash``, which would break
    checkpoint/restore across processes).  State is the register list, so
    merge is an elementwise max — the standard HLL union."""

    P = 11
    M = 1 << P

    def __init__(self):
        self.regs = np.zeros(self.M, dtype=np.int8)

    @classmethod
    def _hash64(cls, v) -> int:
        b = repr(v).encode() if not isinstance(v, (str, bytes)) else (
            v.encode() if isinstance(v, str) else v
        )
        return int.from_bytes(
            hashlib.blake2b(b, digest_size=8).digest(), "little"
        )

    def update(self, col: np.ndarray) -> None:
        regs = self.regs
        P, M = self.P, self.M
        for v in col.tolist():
            h = self._hash64(v)
            idx = h & (M - 1)
            rest = h >> P
            # rank: position of first set bit in the remaining 64-P bits
            rank = (64 - P) - rest.bit_length() + 1 if rest else (64 - P) + 1
            if rank > regs[idx]:
                regs[idx] = rank

    def merge(self, state) -> None:
        self.regs = np.maximum(self.regs, np.asarray(state[0], dtype=np.int8))

    def state(self) -> list:
        return [self.regs.tolist()]

    def evaluate(self) -> int:
        m = float(self.M)
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / float(np.sum(2.0 ** (-self.regs.astype(np.float64))))
        zeros = int(np.sum(self.regs == 0))
        if est <= 2.5 * m and zeros:
            est = m * math.log(m / zeros)  # linear counting, small range
        return int(round(est))
