"""Built-in non-decomposable aggregates, implemented on the Accumulator
protocol and routed through :class:`UdafWindowExec`'s host frame path.

These are the aggregates that cannot decompose into the device kernel's
running components (sum/count/min/max/moments): exact order statistics,
value collection, and sketches.  The reference gets them from DataFusion
(`array_agg` with checkpoint serialization is prototyped at
crates/core/src/accumulators/serializable_accumulator.rs:10-68); ours
checkpoint through the same ``state()``/``merge()`` contract every user
UDAF uses, so kill/restore covers them for free.
"""

from __future__ import annotations

import math

import numpy as np

from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.ops import sketches as _skx


def _jsonable_scalar(x):
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, (np.str_,)):
        return str(x)
    return x


class ArrayAggAccumulator(Accumulator):
    """Collect every value into a list (reference
    serializable_accumulator.rs:10-68 — the one accumulator it ships
    checkpoint serialization for)."""

    def __init__(self):
        self.values: list = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(_jsonable_scalar(v) for v in col.tolist())

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def state_nbytes(self) -> int:
        return 64 + 64 * len(self.values)

    def evaluate(self):
        return list(self.values)


class MedianAccumulator(Accumulator):
    """Exact median (DataFusion `median`); state is the value list —
    UNBOUNDED growth, reported exactly via :meth:`state_nbytes` so the
    doctor's budget/growth verdicts (and spill pressure) see it."""

    def __init__(self):
        self.values: list[float] = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(float(v) for v in np.asarray(col, np.float64))

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def state_nbytes(self) -> int:
        # 8 bytes payload + ~24 bytes of boxed-float overhead per entry;
        # derived from the element count, so restore-invariant
        return 64 + 32 * len(self.values)

    def evaluate(self):
        return float(np.median(self.values)) if self.values else math.nan


class FirstValueAccumulator(Accumulator):
    """First value in arrival order (DataFusion `first_value` with no
    explicit ordering: pick-any-deterministic)."""

    def __init__(self):
        self.value = None
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        if not self.seen and len(col):
            self.value = _jsonable_scalar(col[0])
            self.seen = True

    def merge(self, state) -> None:
        if not self.seen and state[1]:
            self.value, self.seen = state[0], True

    def state(self) -> list:
        return [self.value, self.seen]

    def evaluate(self):
        return self.value


class LastValueAccumulator(Accumulator):
    def __init__(self):
        self.value = None
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        if len(col):
            self.value = _jsonable_scalar(col[-1])
            self.seen = True

    def merge(self, state) -> None:
        if state[1]:
            self.value, self.seen = state[0], True

    def state(self) -> list:
        return [self.value, self.seen]

    def evaluate(self):
        return self.value


class CountDistinctAccumulator(Accumulator):
    """Exact distinct count (DataFusion ``count(distinct x)``); state is
    the value set (jsonable list)."""

    def __init__(self):
        self.seen: set = set()

    def update(self, col: np.ndarray) -> None:
        self.seen.update(_jsonable_scalar(v) for v in col.tolist())

    def merge(self, state) -> None:
        self.seen.update(state[0])

    def state(self) -> list:
        return [list(self.seen)]

    def state_nbytes(self) -> int:
        # ~64 bytes per set entry (hash slot + boxed value); derived
        # from the element count, so restore-invariant
        return 64 + 64 * len(self.seen)

    def evaluate(self) -> int:
        return len(self.seen)


class PercentileContAccumulator(Accumulator):
    """Exact continuous percentile (DataFusion ``approx_percentile_cont``'s
    exact cousin): linear interpolation over the sorted values."""

    def __init__(self, q: float):
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"percentile must be in [0, 1], got {q}")
        self.q = q
        self.values: list[float] = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(float(v) for v in np.asarray(col, np.float64))

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def state_nbytes(self) -> int:
        return 64 + 32 * len(self.values)

    def evaluate(self):
        if not self.values:
            return math.nan
        return float(np.quantile(self.values, self.q))


class BitAndAccumulator(Accumulator):
    """Bitwise AND over int64 values (DataFusion ``bit_and``)."""

    _init = -1  # all bits set
    _op = staticmethod(lambda a, b: a & b)
    _ufunc = np.bitwise_and

    def __init__(self):
        self.acc = self._init
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        vals = np.asarray(col, np.int64)
        if len(vals):
            self.seen = True
            self.acc = self._op(
                self.acc, int(type(self)._ufunc.reduce(vals))
            )

    def merge(self, state) -> None:
        if state[1]:
            self.acc = self._op(self.acc, int(state[0]))
            self.seen = True

    def state(self) -> list:
        return [self.acc, self.seen]

    def evaluate(self):
        return self.acc if self.seen else None


class BitOrAccumulator(BitAndAccumulator):
    _init = 0
    _op = staticmethod(lambda a, b: a | b)
    _ufunc = np.bitwise_or


class BitXorAccumulator(BitAndAccumulator):
    _init = 0
    _op = staticmethod(lambda a, b: a ^ b)
    _ufunc = np.bitwise_xor


class BoolAndAccumulator(Accumulator):
    """TRUE iff every value is true (DataFusion ``bool_and``)."""

    _all = True

    def __init__(self):
        self.acc = self._all
        self.seen = False

    def update(self, col: np.ndarray) -> None:
        vals = np.asarray(col, np.bool_)
        if len(vals):
            self.seen = True
            agg = bool(vals.all()) if self._all else bool(vals.any())
            self.acc = (self.acc and agg) if self._all else (self.acc or agg)

    def merge(self, state) -> None:
        if state[1]:
            self.seen = True
            self.acc = (
                (self.acc and state[0]) if self._all else (self.acc or state[0])
            )

    def state(self) -> list:
        return [bool(self.acc), self.seen]

    def evaluate(self):
        return bool(self.acc) if self.seen else None


class BoolOrAccumulator(BoolAndAccumulator):
    _all = False


class StringAggAccumulator(Accumulator):
    """Concatenate values with a delimiter in arrival order (DataFusion
    ``string_agg``)."""

    def __init__(self, delimiter: str = ","):
        self.delimiter = delimiter
        self.values: list[str] = []

    def update(self, col: np.ndarray) -> None:
        self.values.extend(
            str(v) for v in col.tolist() if v is not None
        )

    def merge(self, state) -> None:
        self.values.extend(state[0])

    def state(self) -> list:
        return [list(self.values)]

    def state_nbytes(self) -> int:
        return 64 + 64 * len(self.values)

    def evaluate(self):
        return self.delimiter.join(self.values) if self.values else None


class NthValueAccumulator(Accumulator):
    """N-th value in arrival order, 1-based (DataFusion ``nth_value``);
    keeps only the first N values, not the whole stream."""

    def __init__(self, n: int = 1):
        if n < 1:
            raise ValueError(f"nth_value position must be >= 1, got {n}")
        self.n = n
        self.values: list = []

    def update(self, col: np.ndarray) -> None:
        need = self.n - len(self.values)
        if need > 0:
            self.values.extend(
                _jsonable_scalar(v) for v in col.tolist()[:need]
            )

    def merge(self, state) -> None:
        need = self.n - len(self.values)
        if need > 0:
            self.values.extend(state[0][:need])

    def state(self) -> list:
        return [list(self.values)]

    def evaluate(self):
        return self.values[self.n - 1] if len(self.values) >= self.n else None


class TwoColStatsAccumulator(Accumulator):
    """Shared sufficient statistics for every bivariate aggregate —
    corr / covar_samp / covar_pop / the regr_* family (reference
    functions.py:1658-2066).  State is (n, Σx, Σy, Σxx, Σyy, Σxy) over
    pairwise-non-null pairs; each public aggregate is a finalizer over
    these six numbers.  Column convention follows DataFusion:
    ``(value_y, value_x)``."""

    stat = "corr"

    def __init__(self):
        self.n = 0
        self.sx = self.sy = self.sxx = self.syy = self.sxy = 0.0

    def update(self, ycol: np.ndarray, xcol: np.ndarray = None) -> None:
        if xcol is None:
            raise ValueError(f"{self.stat} takes two argument columns")
        y = np.asarray(ycol, np.float64)
        x = np.asarray(xcol, np.float64)
        ok = ~(np.isnan(x) | np.isnan(y))
        x, y = x[ok], y[ok]
        self.n += int(len(x))
        self.sx += float(x.sum())
        self.sy += float(y.sum())
        self.sxx += float((x * x).sum())
        self.syy += float((y * y).sum())
        self.sxy += float((x * y).sum())

    def merge(self, state) -> None:
        n, sx, sy, sxx, syy, sxy = state
        self.n += n
        self.sx += sx
        self.sy += sy
        self.sxx += sxx
        self.syy += syy
        self.sxy += sxy

    def state(self) -> list:
        return [self.n, self.sx, self.sy, self.sxx, self.syy, self.sxy]

    # centered moments (numerically fine for window-scale data; the
    # device kernel's compensated path is for the billion-row axis)
    def _mxx(self):
        return self.sxx - self.sx * self.sx / self.n

    def _myy(self):
        return self.syy - self.sy * self.sy / self.n

    def _mxy(self):
        return self.sxy - self.sx * self.sy / self.n

    def evaluate(self):
        import math as _m

        n = self.n
        if n == 0:
            # regr_count is 0 over an empty pair set (postgres/DataFusion);
            # every other bivariate stat is undefined -> NULL
            return 0 if self.stat == "regr_count" else None
        s = self.stat
        if s == "regr_count":
            return n
        if s == "regr_avgx":
            return self.sx / n
        if s == "regr_avgy":
            return self.sy / n
        if s == "regr_sxx":
            return self._mxx()
        if s == "regr_syy":
            return self._myy()
        if s == "regr_sxy":
            return self._mxy()
        if s == "covar_pop":
            return self._mxy() / n
        if s in ("covar", "covar_samp"):
            return self._mxy() / (n - 1) if n > 1 else None
        if s == "corr":
            d = _m.sqrt(self._mxx() * self._myy())
            return self._mxy() / d if d > 0 else None
        if s == "regr_slope":
            return self._mxy() / self._mxx() if self._mxx() != 0 else None
        if s == "regr_intercept":
            if self._mxx() == 0:
                return None
            slope = self._mxy() / self._mxx()
            return (self.sy - slope * self.sx) / n
        if s == "regr_r2":
            if self._mxx() == 0 or self._myy() == 0:
                return None
            r = self._mxy() / _m.sqrt(self._mxx() * self._myy())
            return r * r
        raise ValueError(f"unknown bivariate stat {s!r}")


class WeightedPercentileAccumulator(Accumulator):
    """Exact weighted continuous percentile (DataFusion
    ``approx_percentile_cont_with_weight``'s exact cousin)."""

    def __init__(self, q: float):
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"percentile must be in [0, 1], got {q}")
        self.q = q
        self.values: list[float] = []
        self.weights: list[float] = []

    def update(self, col: np.ndarray, wcol: np.ndarray = None) -> None:
        v = np.asarray(col, np.float64)
        w = (
            np.ones_like(v)
            if wcol is None
            else np.asarray(wcol, np.float64)
        )
        self.values.extend(v.tolist())
        self.weights.extend(w.tolist())

    def merge(self, state) -> None:
        self.values.extend(state[0])
        self.weights.extend(state[1])

    def state(self) -> list:
        return [list(self.values), list(self.weights)]

    def state_nbytes(self) -> int:
        return 64 + 64 * len(self.values)

    def evaluate(self):
        if not self.values:
            return math.nan
        v = np.asarray(self.values)
        w = np.asarray(self.weights)
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        cw = np.cumsum(w)
        total = cw[-1]
        if total <= 0:
            return math.nan
        # weighted quantile with linear interpolation on the cumulative
        # weight midpoints (the standard Hazen-type definition)
        mid = (cw - 0.5 * w) / total
        return float(np.interp(self.q, mid, v))


class ApproxDistinctAccumulator(Accumulator):
    """HyperLogLog distinct-count sketch (DataFusion `approx_distinct`).

    Thin shim over the shared :mod:`denormalized_tpu.ops.sketches`
    kernels — the UDAF fallback lane of the first-class
    ``approx_distinct`` slice aggregate.  2^11 registers (~2.3%
    standard error), 64-bit stable hash (blake2b — NOT Python's salted
    ``hash``, which would break checkpoint/restore across processes);
    this class keeps its historical LOW-bit register-index convention
    (``h & (M-1)``), so checkpointed register state from earlier builds
    restores bit-for-bit.  State is the register list; merge is an
    elementwise max — the standard HLL union."""

    P = 11
    M = 1 << P

    def __init__(self):
        self.regs = np.zeros(self.M, dtype=np.int8)

    @classmethod
    def _hash64(cls, v) -> int:
        return _skx.blake2b64(v)

    def update(self, col: np.ndarray) -> None:
        vals = col.tolist()
        if not vals:
            return
        hs = np.fromiter(
            (_skx.blake2b64(v) for v in vals),
            dtype=np.uint64,
            count=len(vals),
        )
        idx = (hs & np.uint64(self.M - 1)).astype(np.int64)
        rest = hs >> np.uint64(self.P)
        # rank: position of first set bit in the remaining 64-P bits;
        # exact bit-length from the shared kernel (bit-identical to the
        # old per-row int.bit_length loop)
        width = np.uint64(64 - self.P)
        rank = (
            width + np.uint64(1) - _skx.u64_bit_length(rest)
        ).astype(np.int8)
        np.maximum.at(self.regs, idx, rank)

    def merge(self, state) -> None:
        self.regs = np.maximum(self.regs, np.asarray(state[0], dtype=np.int8))

    def state(self) -> list:
        return [self.regs.tolist()]

    def state_nbytes(self) -> int:
        return int(self.regs.nbytes)  # constant — the sketch's point

    def evaluate(self) -> int:
        m = float(self.M)
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / float(np.sum(2.0 ** (-self.regs.astype(np.float64))))
        zeros = int(np.sum(self.regs == 0))
        if est <= 2.5 * m and zeros:
            est = m * math.log(m / zeros)  # linear counting, small range
        return int(round(est))


class ApproxTopKAccumulator(Accumulator):
    """Exact top-k heavy hitters for the ``approx_top_k`` UDAF fallback
    lane: a value → count dict, evaluated as ``[value, count]`` pairs
    count-descending (insertion order breaks ties, so the output is a
    pure function of the feed).  Unbounded in distinct values — the
    slice path's Space-Saving planes are the bounded-state lane; this
    accumulator reports its real growth via :meth:`state_nbytes`."""

    def __init__(self, k: int = 10):
        if k < 1:
            raise ValueError(f"approx_top_k needs k >= 1, got {k}")
        self.k = int(k)
        self.counts: dict = {}

    def update(self, col: np.ndarray) -> None:
        counts = self.counts
        for v in col.tolist():
            v = _jsonable_scalar(v)
            counts[v] = counts.get(v, 0) + 1

    def merge(self, state) -> None:
        counts = self.counts
        for v, c in state[0]:
            counts[v] = counts.get(v, 0) + int(c)

    def state(self) -> list:
        return [[[v, c] for v, c in self.counts.items()]]

    def state_nbytes(self) -> int:
        return 64 + 80 * len(self.counts)

    def evaluate(self) -> list:
        items = sorted(self.counts.items(), key=lambda kv: -kv[1])
        return [[v, int(c)] for v, c in items[: self.k]]
