"""Feast feature-store integration.

Mirror of the reference's ``FeastDataStream``
(py-denormalized/python/denormalized/feast_data_stream.py:19-123): a
DataStream whose transform methods keep returning FeastDataStream (the
reference does this with a metaclass rewriting DataStream-returning
methods), plus ``write_feast_feature`` pushing each emitted batch to a Feast
push source.  Feast itself is an optional dependency — any object with
``push(push_source_name, df)`` works (tests use a fake store).
"""

from __future__ import annotations

from typing import Any

from denormalized_tpu.api.data_stream import DataStream


class _FeastMeta(type):
    """Rewrap DataStream-returning methods so chaining stays Feast-typed
    (the reference's metaclass trick)."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        for attr in (
            "select",
            "select_columns",
            "filter",
            "with_column",
            "with_column_renamed",
            "drop_columns",
            "window",
            "session_window",
            "join",
            "join_on",
        ):
            base_fn = getattr(DataStream, attr)

            def wrapped(self, *a, __fn=base_fn, **kw):
                out = __fn(self, *a, **kw)
                return (
                    FeastDataStream(out._plan, out._ctx)
                    if isinstance(out, DataStream)
                    else out
                )

            setattr(cls, attr, wrapped)
        return cls


class FeastDataStream(DataStream, metaclass=_FeastMeta):
    @classmethod
    def from_data_stream(cls, ds: DataStream) -> "FeastDataStream":
        return cls(ds._plan, ds._ctx)

    def write_feast_feature(
        self, feature_store: Any, push_source_name: str
    ) -> None:
        """Execute the stream, pushing each batch to the feature store
        (reference feast_data_stream.py write_feast_feature)."""

        def push(batch):
            rows = {
                f.name: batch.column(f.name)
                for f in batch.schema.without_internal()
            }
            df = _to_frame(rows)
            feature_store.push(push_source_name, df)

        self.sink(push)


def _to_frame(rows: dict):
    """Feast expects a pandas DataFrame; fall back to the dict when pandas
    is unavailable (fake stores in tests accept both)."""
    try:
        import pandas as pd

        return pd.DataFrame(rows)
    except ImportError:
        return rows
