"""Aggregate / scalar function constructors.

The public surface mirroring datafusion-python's ``functions`` module as the
reference re-exports it
(py-denormalized/python/denormalized/datafusion/functions.py, 2,659 LoC):
string/math/date/conditional scalar functions, the full aggregate set
(count/sum/min/max/avg, the variance family, median, array_agg,
first/last_value, approx_distinct), CASE expressions, and UDF/UDAF
factories.

Scalar functions evaluate vectorized on host (numpy); the math subset also
lowers to jax for device-fused post-aggregation filters.  Non-decomposable
aggregates (median, array_agg, first/last, approx_distinct) run through the
host accumulator frame path with checkpoint support; everything else
decomposes into the device kernel's running components.
"""

from __future__ import annotations

from typing import Callable

from denormalized_tpu.common.schema import DataType
from denormalized_tpu.logical.expr import (
    AggregateExpr,
    CaseBuilder,
    Expr,
    ScalarFunctionExpr,
    ScalarUDFExpr,
    col,
    lit,
)
from denormalized_tpu.logical.scalar_functions import REGISTRY, lookup

__all__ = [  # noqa: F822 - scalar names are injected below
    "count", "count_star", "sum", "min", "max", "avg", "mean",
    "stddev", "stddev_samp", "stddev_pop", "var", "var_samp", "var_sample",
    "var_pop",
    "median", "approx_median", "array_agg", "first_value", "last_value",
    "nth_value", "string_agg",
    "approx_distinct", "approx_top_k", "count_distinct", "percentile_cont",
    "approx_percentile_cont", "approx_percentile_cont_with_weight",
    "bit_and", "bit_or", "bit_xor", "bool_and", "bool_or",
    "corr", "covar", "covar_pop", "covar_samp",
    "regr_avgx", "regr_avgy", "regr_count", "regr_intercept", "regr_r2",
    "regr_slope", "regr_sxx", "regr_sxy", "regr_syy",
    "case", "when", "udf", "udaf", "col", "lit",
    "alias", "order_by", "in_list",
    "window", "lead", "lag", "row_number", "rank", "dense_rank",
    "percent_rank", "cume_dist", "ntile",
] + sorted(REGISTRY)


def _e(expr: Expr | str) -> Expr:
    return col(expr) if isinstance(expr, str) else expr


# -- aggregates ----------------------------------------------------------


def count(expr: Expr | str | None = None) -> AggregateExpr:
    return AggregateExpr("count", _e(expr) if expr is not None else None)


def sum(expr: Expr | str) -> AggregateExpr:  # noqa: A001 - mirrors SQL name
    return AggregateExpr("sum", _e(expr))


def min(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    return AggregateExpr("min", _e(expr))


def max(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    return AggregateExpr("max", _e(expr))


def avg(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("avg", _e(expr))


def stddev(expr: Expr | str) -> AggregateExpr:
    """Sample standard deviation (decomposes onto the device kernel)."""
    return AggregateExpr("stddev", _e(expr))


def stddev_samp(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("stddev", _e(expr))


def stddev_pop(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("stddev_pop", _e(expr))


def var(expr: Expr | str) -> AggregateExpr:
    """Sample variance (DataFusion ``var``/``var_samp``)."""
    return AggregateExpr("var", _e(expr))


def var_samp(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("var", _e(expr))


def var_pop(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("var_pop", _e(expr))


def _builtin_udaf(acc_cls, return_type: DataType, name: str):
    from denormalized_tpu.api.udaf import UDAF

    def make(expr: Expr | str) -> AggregateExpr:
        e = _e(expr)
        u = UDAF(acc_cls, (e,), return_type, name)
        return AggregateExpr("udaf", e, None, u)

    make.__name__ = name
    make.__doc__ = f"{name} aggregate (host accumulator frame path)."
    return make


def _builtin_accs():
    from denormalized_tpu.api import builtin_accumulators as b

    return b


def array_agg(expr: Expr | str) -> AggregateExpr:
    """Collect values into a list per group-window; checkpoints through
    accumulator state (reference serializable_accumulator.rs:10-68)."""
    b = _builtin_accs()
    return _builtin_udaf(b.ArrayAggAccumulator, DataType.LIST, "array_agg")(expr)


def median(expr: Expr | str) -> AggregateExpr:
    b = _builtin_accs()
    return _builtin_udaf(b.MedianAccumulator, DataType.FLOAT64, "median")(expr)


def approx_median(expr: Expr | str) -> AggregateExpr:
    """Approximate median: a first-class mergeable quantile sketch on
    the multi-query slice path (documented rank-error bound, O(1) state
    per group — ops/sketches.py KllSpec); lowers to the exact
    MedianAccumulator on every other path."""
    from denormalized_tpu.api.udaf import UDAF

    b = _builtin_accs()
    e = _e(expr)
    u = UDAF(b.MedianAccumulator, (e,), DataType.FLOAT64, "approx_median")
    return AggregateExpr("approx_median", e, None, u)


def first_value(expr: Expr | str) -> AggregateExpr:
    """First value in arrival order; result type follows the argument."""
    b = _builtin_accs()
    return _builtin_udaf(b.FirstValueAccumulator, None, "first_value")(expr)


def last_value(expr: Expr | str) -> AggregateExpr:
    """Last value in arrival order; result type follows the argument."""
    b = _builtin_accs()
    return _builtin_udaf(b.LastValueAccumulator, None, "last_value")(expr)


def approx_distinct(expr: Expr | str) -> AggregateExpr:
    """HyperLogLog distinct count (~1.6% error, mergeable sketch state).

    First-class on the multi-query slice path: a vectorized (G, 4096)
    int8 register plane per slice unit, shared across concurrent
    queries, byte-identical through kill/restore (stable blake2b /
    splitmix64 hashing).  Lowers to the accumulator-frame HLL shim on
    every other path."""
    from denormalized_tpu.api.udaf import UDAF

    b = _builtin_accs()
    e = _e(expr)
    u = UDAF(
        b.ApproxDistinctAccumulator, (e,), DataType.INT64, "approx_distinct"
    )
    return AggregateExpr("approx_distinct", e, None, u)


def approx_top_k(expr: Expr | str, k: int = 10) -> AggregateExpr:
    """Top-k most frequent values as ``[value, count]`` pairs,
    count-descending — Space-Saving planes on the multi-query slice
    path (``count - err <= true <= count`` per reported value, O(k)
    state per group); exact dict counting on the fallback path."""
    from denormalized_tpu.api.udaf import UDAF

    b = _builtin_accs()
    e = _e(expr)
    k = int(k)

    class _Bound(b.ApproxTopKAccumulator):
        def __init__(self):
            super().__init__(k)

    _Bound.__name__ = f"ApproxTopK[{k}]"
    u = UDAF(_Bound, (e,), DataType.LIST, f"approx_top_k_{k}")
    return AggregateExpr("approx_top_k", e, None, u, (k,))


def count_distinct(expr: Expr | str) -> AggregateExpr:
    """Exact distinct count (DataFusion ``count(distinct x)``)."""
    b = _builtin_accs()
    return _builtin_udaf(
        b.CountDistinctAccumulator, DataType.INT64, "count_distinct"
    )(expr)


def percentile_cont(expr: Expr | str, q: float) -> AggregateExpr:
    """Exact continuous percentile with linear interpolation (covers
    DataFusion's approx_percentile_cont use cases exactly)."""
    b = _builtin_accs()

    class _Bound(b.PercentileContAccumulator):
        def __init__(self):
            super().__init__(q)

    _Bound.__name__ = f"PercentileCont[{q}]"
    return _builtin_udaf(
        _Bound, DataType.FLOAT64, f"percentile_cont_{q}"
    )(expr)


def approx_percentile_cont(expr: Expr | str, q: float) -> AggregateExpr:
    """Approximate continuous percentile: compactor quantile sketch on
    the multi-query slice path (self-reported rank-error bound, O(1)
    state per group); lowers to the exact interpolating
    :func:`percentile_cont` accumulator on every other path."""
    from denormalized_tpu.api.udaf import UDAF

    b = _builtin_accs()

    class _Bound(b.PercentileContAccumulator):
        def __init__(self):
            super().__init__(q)

    _Bound.__name__ = f"PercentileCont[{q}]"
    e = _e(expr)
    u = UDAF(_Bound, (e,), DataType.FLOAT64, f"percentile_cont_{q}")
    return AggregateExpr(
        "approx_percentile_cont", e, None, u, (float(q),)
    )


def approx_percentile_cont_with_weight(
    expr: Expr | str, weight: Expr | str, q: float
) -> AggregateExpr:
    """Weighted continuous percentile (reference functions.py
    approx_percentile_cont_with_weight; exact here)."""
    b = _builtin_accs()

    class _Bound(b.WeightedPercentileAccumulator):
        def __init__(self):
            super().__init__(q)

    _Bound.__name__ = f"WeightedPercentile[{q}]"
    from denormalized_tpu.api.udaf import UDAF

    e, w = _e(expr), _e(weight)
    u = UDAF(_Bound, (e, w), DataType.FLOAT64, f"percentile_weight_{q}")
    return AggregateExpr("udaf", e, None, u)


def count_star() -> AggregateExpr:
    """COUNT(*) (reference functions.py:371)."""
    return count(None)


def mean(expr: Expr | str) -> AggregateExpr:
    """Alias of :func:`avg` (reference functions.py:1760)."""
    return avg(expr)


def var_sample(expr: Expr | str) -> AggregateExpr:
    """Alias of :func:`var` (reference functions.py:1893)."""
    return var(expr)


def string_agg(expr: Expr | str, delimiter: str = ",") -> AggregateExpr:
    """Concatenate values with a delimiter (reference ``string_agg``)."""
    b = _builtin_accs()

    class _Bound(b.StringAggAccumulator):
        def __init__(self):
            super().__init__(delimiter)

    _Bound.__name__ = f"StringAgg[{delimiter!r}]"
    return _builtin_udaf(_Bound, DataType.STRING, "string_agg")(expr)


def nth_value(expr: Expr | str, n: int) -> AggregateExpr:
    """N-th value in arrival order, 1-based (reference ``nth_value``)."""
    b = _builtin_accs()

    class _Bound(b.NthValueAccumulator):
        def __init__(self):
            super().__init__(n)

    _Bound.__name__ = f"NthValue[{n}]"
    return _builtin_udaf(_Bound, None, f"nth_value_{n}")(expr)


def _bool_bit_agg(acc_attr: str, name: str, rt: DataType):
    def make(expr: Expr | str) -> AggregateExpr:
        b = _builtin_accs()
        return _builtin_udaf(getattr(b, acc_attr), rt, name)(expr)

    make.__name__ = name
    make.__doc__ = f"{name} aggregate (reference functions.py exports it)."
    return make


bit_and = _bool_bit_agg("BitAndAccumulator", "bit_and", DataType.INT64)
bit_or = _bool_bit_agg("BitOrAccumulator", "bit_or", DataType.INT64)
bit_xor = _bool_bit_agg("BitXorAccumulator", "bit_xor", DataType.INT64)
bool_and = _bool_bit_agg("BoolAndAccumulator", "bool_and", DataType.BOOL)
bool_or = _bool_bit_agg("BoolOrAccumulator", "bool_or", DataType.BOOL)


def _bivariate(stat: str, rt: DataType = DataType.FLOAT64):
    """Two-column aggregate over shared sufficient statistics (reference
    functions.py:1658-2066 corr/covar/regr_* — DataFusion's argument
    order ``(value_y, value_x)``)."""

    def make(value_y: Expr | str, value_x: Expr | str) -> AggregateExpr:
        b = _builtin_accs()

        class _Bound(b.TwoColStatsAccumulator):
            pass

        _Bound.stat = stat
        _Bound.__name__ = f"TwoColStats[{stat}]"
        from denormalized_tpu.api.udaf import UDAF

        ey, ex = _e(value_y), _e(value_x)
        u = UDAF(_Bound, (ey, ex), rt, stat)
        return AggregateExpr("udaf", ey, None, u)

    make.__name__ = stat
    make.__doc__ = (
        f"{stat}(value_y, value_x) bivariate aggregate "
        "(sufficient-statistics decomposition, mergeable for checkpoints)."
    )
    return make


corr = _bivariate("corr")
covar = _bivariate("covar")
covar_pop = _bivariate("covar_pop")
covar_samp = _bivariate("covar_samp")
regr_avgx = _bivariate("regr_avgx")
regr_avgy = _bivariate("regr_avgy")
regr_count = _bivariate("regr_count", DataType.INT64)
regr_intercept = _bivariate("regr_intercept")
regr_r2 = _bivariate("regr_r2")
regr_slope = _bivariate("regr_slope")
regr_sxx = _bivariate("regr_sxx")
regr_sxy = _bivariate("regr_sxy")
regr_syy = _bivariate("regr_syy")


# -- CASE ----------------------------------------------------------------


def case(expr: Expr | str) -> CaseBuilder:
    """Simple CASE: ``case(col('x')).when(1, 'one').otherwise('other')``."""
    return CaseBuilder(_e(expr))


def when(cond, result) -> CaseBuilder:
    """Searched CASE: ``when(col('x') > 0, 'pos').otherwise('neg')``."""
    return CaseBuilder(None).when(cond, result)


# -- scalar functions (registry-driven) ----------------------------------


def _scalar_constructor(fname: str):
    spec = lookup(fname)

    def make(*args) -> Expr:
        lo = spec.min_args
        hi = spec.max_args if spec.max_args is not None else spec.min_args
        if not (lo <= len(args) <= hi):
            from denormalized_tpu.common.errors import PlanError

            want = str(lo) if lo == hi else f"{lo}..{hi}"
            raise PlanError(
                f"{fname}() takes {want} argument(s), got {len(args)}"
            )
        # string-arg convention: the FIRST argument names a column, later
        # string arguments are literals (`replace("name", "from", "to")`);
        # unit-taking date functions treat every string as a literal
        # (`date_trunc("minute", col("ts"))`).  Pass col()/lit() explicitly
        # to override.
        exprs = tuple(
            col(a)
            if isinstance(a, str) and i == 0 and fname not in _ALL_STR_LITERAL
            else _wrap_arg(a)
            for i, a in enumerate(args)
        )
        return ScalarFunctionExpr(fname, exprs)

    make.__name__ = fname
    make.__doc__ = (
        f"Scalar function ``{fname}`` (datafusion parity).  A bare string "
        "as the first argument is a column name; later bare strings are "
        "literals."
    )
    return make


def _wrap_arg(a) -> Expr:
    from denormalized_tpu.logical.expr import _wrap

    return _wrap(a)


# functions whose FIRST string argument is a literal (unit name), not a
# column reference
_ALL_STR_LITERAL = {
    "date_trunc", "date_part", "datetrunc", "datepart", "extract", "chr",
    "named_struct",
}

for _fname in REGISTRY:
    globals()[_fname] = _scalar_constructor(_fname)
del _fname

# -- explicit overrides of registry-generated constructors ---------------
# (defined AFTER the injection loop so these richer signatures win)

_registry_in_list = globals()["in_list"]
_registry_array_sort = globals()["array_sort"]
_registry_named_struct = globals()["named_struct"]


def in_list(arg: Expr | str, values: list, negated: bool = False) -> Expr:
    """Membership test (reference functions.py:323): ``values`` is a
    python list of expressions/literals; ``negated=True`` gives NOT IN."""
    e = _registry_in_list(arg, *[_wrap_arg(v) for v in values])
    return ~e if negated else e


def array_sort(
    array: Expr | str, descending: bool = False, null_first: bool = False
) -> Expr:
    """Sort list elements (reference functions.py:1401 — python bool
    flags, converted to literals for the row-wise kernel)."""
    return _registry_array_sort(array, lit(bool(descending)), lit(bool(null_first)))


list_sort = array_sort


def named_struct(*args) -> Expr:
    """STRUCT with named fields.  Accepts the reference's list-of-pairs
    form ``named_struct([("a", e1), ("b", e2)])`` (functions.py:1059) or
    flat ``named_struct("a", e1, "b", e2)``."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        flat: list = []
        for name, value in args[0]:
            flat.extend([name, value])
        args = tuple(flat)
    return _registry_named_struct(*args)


def alias(expr: Expr | str, name: str) -> Expr:
    """Function form of ``expr.alias(name)`` (reference functions.py:361)."""
    return _e(expr).alias(name)


def order_by(
    expr: Expr | str, ascending: bool = True, nulls_first: bool = True
):
    """Sort specification (reference functions.py:356) — consumed by
    order-aware aggregate options and ``DataStream.sort`` on bounded
    collects."""
    from denormalized_tpu.logical.expr import SortExpr

    return SortExpr(_e(expr), ascending, nulls_first)


# -- ranking / offset window functions -----------------------------------


def _win(wname, args=(), partition_by=None, order_by=None, params=()):
    from denormalized_tpu.logical.expr import SortExpr, WindowFunctionExpr

    def _sort(x):
        if isinstance(x, SortExpr):
            return x
        return SortExpr(_e(x))

    return WindowFunctionExpr(
        wname,
        tuple(_e(a) for a in args),
        tuple(_e(p) for p in (partition_by or ())),
        tuple(_sort(s) for s in (order_by or ())),
        params,
    )


def window(name, args, partition_by=None, order_by=None, window_frame=None):
    """Window function by name (reference functions.py:405).  Custom
    window frames are not supported — the ranking/offset family ignores
    frames in DataFusion too."""
    if window_frame is not None:
        from denormalized_tpu.common.errors import PlanError

        raise PlanError(
            "custom window frames are not supported; the ranking/offset "
            "window functions operate over the whole partition"
        )
    name = name.lower()
    if name in ("lead", "lag"):
        a = list(args)
        shift = a[1] if len(a) > 1 else 1
        default = a[2] if len(a) > 2 else None
        return _win(name, a[:1], partition_by, order_by,
                    (int(getattr(shift, "value", shift)),
                     getattr(default, "value", default)))
    if name == "ntile":
        n = args[0] if args else 1
        return _win(name, (), partition_by, order_by,
                    (int(getattr(n, "value", n)),))
    if name in ("row_number", "rank", "dense_rank", "percent_rank",
                "cume_dist"):
        return _win(name, (), partition_by, order_by)
    from denormalized_tpu.common.errors import PlanError

    raise PlanError(f"unknown window function {name!r}")


def lead(arg, shift_offset: int = 1, default_value=None,
         partition_by=None, order_by=None):
    """Value from the row ``shift_offset`` AFTER the current one in the
    partition (reference functions.py:2292)."""
    return _win("lead", (arg,), partition_by, order_by,
                (shift_offset, default_value))


def lag(arg, shift_offset: int = 1, default_value=None,
        partition_by=None, order_by=None):
    """Value from the row ``shift_offset`` BEFORE the current one in the
    partition (reference functions.py:2347)."""
    return _win("lag", (arg,), partition_by, order_by,
                (shift_offset, default_value))


def row_number(partition_by=None, order_by=None):
    """1-based row number within the partition (reference :2399)."""
    return _win("row_number", (), partition_by, order_by)


def rank(partition_by=None, order_by=None):
    """Olympic-medal rank with gaps after ties (reference :2435)."""
    return _win("rank", (), partition_by, order_by)


def dense_rank(partition_by=None, order_by=None):
    """Rank without gaps after ties (reference :2476)."""
    return _win("dense_rank", (), partition_by, order_by)


def percent_rank(partition_by=None, order_by=None):
    """(rank - 1) / (rows - 1) (reference :2500)."""
    return _win("percent_rank", (), partition_by, order_by)


def cume_dist(partition_by=None, order_by=None):
    """Cumulative distribution: rows with key <= current / rows."""
    return _win("cume_dist", (), partition_by, order_by)


def ntile(arg, partition_by=None, order_by=None):
    """Bucket number 1..N over the partition (reference :2560)."""
    n = int(getattr(arg, "value", arg))
    return _win("ntile", (), partition_by, order_by, (n,))


def udf(fn: Callable, return_type: DataType, name: str | None = None):
    """Scalar UDF over vectorized columns (reference udf_example.rs:22-60,
    py udf.py)."""

    name = name or getattr(fn, "__name__", "udf")

    def make(*args: Expr | str) -> Expr:
        exprs = tuple(col(a) if isinstance(a, str) else a for a in args)
        return ScalarUDFExpr(fn, exprs, name, return_type)

    return make


def udaf(accumulator_cls, return_type: DataType, name: str | None = None):
    """User-defined aggregate: ``accumulator_cls`` subclasses
    :class:`denormalized_tpu.api.udaf.Accumulator` (reference
    py-denormalized python/denormalized/datafusion/udf.py Accumulator +
    python/examples/udaf_example.py)."""
    from denormalized_tpu.api.udaf import UDAF

    name = name or getattr(accumulator_cls, "__name__", "udaf")

    def make(*args: Expr | str) -> AggregateExpr:
        exprs = [col(a) if isinstance(a, str) else a for a in args]
        u = UDAF(accumulator_cls, tuple(exprs), return_type, name)
        return AggregateExpr("udaf", exprs[0] if exprs else None, None, u)

    return make
