"""Aggregate / scalar function constructors.

The public surface mirroring datafusion-python's ``functions`` module as the
reference re-exports it
(py-denormalized/python/denormalized/datafusion/functions.py, 2,659 LoC):
string/math/date/conditional scalar functions, the full aggregate set
(count/sum/min/max/avg, the variance family, median, array_agg,
first/last_value, approx_distinct), CASE expressions, and UDF/UDAF
factories.

Scalar functions evaluate vectorized on host (numpy); the math subset also
lowers to jax for device-fused post-aggregation filters.  Non-decomposable
aggregates (median, array_agg, first/last, approx_distinct) run through the
host accumulator frame path with checkpoint support; everything else
decomposes into the device kernel's running components.
"""

from __future__ import annotations

from typing import Callable

from denormalized_tpu.common.schema import DataType
from denormalized_tpu.logical.expr import (
    AggregateExpr,
    CaseBuilder,
    Expr,
    ScalarFunctionExpr,
    ScalarUDFExpr,
    col,
    lit,
)
from denormalized_tpu.logical.scalar_functions import REGISTRY, lookup

__all__ = [  # noqa: F822 - scalar names are injected below
    "count", "sum", "min", "max", "avg",
    "stddev", "stddev_samp", "stddev_pop", "var", "var_samp", "var_pop",
    "median", "approx_median", "array_agg", "first_value", "last_value",
    "approx_distinct", "count_distinct", "percentile_cont",
    "approx_percentile_cont",
    "case", "when", "udf", "udaf", "col", "lit",
] + sorted(REGISTRY)


def _e(expr: Expr | str) -> Expr:
    return col(expr) if isinstance(expr, str) else expr


# -- aggregates ----------------------------------------------------------


def count(expr: Expr | str | None = None) -> AggregateExpr:
    return AggregateExpr("count", _e(expr) if expr is not None else None)


def sum(expr: Expr | str) -> AggregateExpr:  # noqa: A001 - mirrors SQL name
    return AggregateExpr("sum", _e(expr))


def min(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    return AggregateExpr("min", _e(expr))


def max(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    return AggregateExpr("max", _e(expr))


def avg(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("avg", _e(expr))


def stddev(expr: Expr | str) -> AggregateExpr:
    """Sample standard deviation (decomposes onto the device kernel)."""
    return AggregateExpr("stddev", _e(expr))


def stddev_samp(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("stddev", _e(expr))


def stddev_pop(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("stddev_pop", _e(expr))


def var(expr: Expr | str) -> AggregateExpr:
    """Sample variance (DataFusion ``var``/``var_samp``)."""
    return AggregateExpr("var", _e(expr))


def var_samp(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("var", _e(expr))


def var_pop(expr: Expr | str) -> AggregateExpr:
    return AggregateExpr("var_pop", _e(expr))


def _builtin_udaf(acc_cls, return_type: DataType, name: str):
    from denormalized_tpu.api.udaf import UDAF

    def make(expr: Expr | str) -> AggregateExpr:
        e = _e(expr)
        u = UDAF(acc_cls, (e,), return_type, name)
        return AggregateExpr("udaf", e, None, u)

    make.__name__ = name
    make.__doc__ = f"{name} aggregate (host accumulator frame path)."
    return make


def _builtin_accs():
    from denormalized_tpu.api import builtin_accumulators as b

    return b


def array_agg(expr: Expr | str) -> AggregateExpr:
    """Collect values into a list per group-window; checkpoints through
    accumulator state (reference serializable_accumulator.rs:10-68)."""
    b = _builtin_accs()
    return _builtin_udaf(b.ArrayAggAccumulator, DataType.LIST, "array_agg")(expr)


def median(expr: Expr | str) -> AggregateExpr:
    b = _builtin_accs()
    return _builtin_udaf(b.MedianAccumulator, DataType.FLOAT64, "median")(expr)


def approx_median(expr: Expr | str) -> AggregateExpr:
    """Exact median under the approx_median name (we can afford exact)."""
    b = _builtin_accs()
    return _builtin_udaf(b.MedianAccumulator, DataType.FLOAT64, "approx_median")(
        expr
    )


def first_value(expr: Expr | str) -> AggregateExpr:
    """First value in arrival order; result type follows the argument."""
    b = _builtin_accs()
    return _builtin_udaf(b.FirstValueAccumulator, None, "first_value")(expr)


def last_value(expr: Expr | str) -> AggregateExpr:
    """Last value in arrival order; result type follows the argument."""
    b = _builtin_accs()
    return _builtin_udaf(b.LastValueAccumulator, None, "last_value")(expr)


def approx_distinct(expr: Expr | str) -> AggregateExpr:
    """HyperLogLog distinct count (~1.6% error, mergeable sketch state)."""
    b = _builtin_accs()
    return _builtin_udaf(
        b.ApproxDistinctAccumulator, DataType.INT64, "approx_distinct"
    )(expr)


def count_distinct(expr: Expr | str) -> AggregateExpr:
    """Exact distinct count (DataFusion ``count(distinct x)``)."""
    b = _builtin_accs()
    return _builtin_udaf(
        b.CountDistinctAccumulator, DataType.INT64, "count_distinct"
    )(expr)


def percentile_cont(expr: Expr | str, q: float) -> AggregateExpr:
    """Exact continuous percentile with linear interpolation (covers
    DataFusion's approx_percentile_cont use cases exactly)."""
    b = _builtin_accs()

    class _Bound(b.PercentileContAccumulator):
        def __init__(self):
            super().__init__(q)

    _Bound.__name__ = f"PercentileCont[{q}]"
    return _builtin_udaf(
        _Bound, DataType.FLOAT64, f"percentile_cont_{q}"
    )(expr)


def approx_percentile_cont(expr: Expr | str, q: float) -> AggregateExpr:
    """Alias of :func:`percentile_cont` (we can afford exact)."""
    return percentile_cont(expr, q)


# -- CASE ----------------------------------------------------------------


def case(expr: Expr | str) -> CaseBuilder:
    """Simple CASE: ``case(col('x')).when(1, 'one').otherwise('other')``."""
    return CaseBuilder(_e(expr))


def when(cond, result) -> CaseBuilder:
    """Searched CASE: ``when(col('x') > 0, 'pos').otherwise('neg')``."""
    return CaseBuilder(None).when(cond, result)


# -- scalar functions (registry-driven) ----------------------------------


def _scalar_constructor(fname: str):
    spec = lookup(fname)

    def make(*args) -> Expr:
        lo = spec.min_args
        hi = spec.max_args if spec.max_args is not None else spec.min_args
        if not (lo <= len(args) <= hi):
            from denormalized_tpu.common.errors import PlanError

            want = str(lo) if lo == hi else f"{lo}..{hi}"
            raise PlanError(
                f"{fname}() takes {want} argument(s), got {len(args)}"
            )
        # string-arg convention: the FIRST argument names a column, later
        # string arguments are literals (`replace("name", "from", "to")`);
        # unit-taking date functions treat every string as a literal
        # (`date_trunc("minute", col("ts"))`).  Pass col()/lit() explicitly
        # to override.
        exprs = tuple(
            col(a)
            if isinstance(a, str) and i == 0 and fname not in _ALL_STR_LITERAL
            else _wrap_arg(a)
            for i, a in enumerate(args)
        )
        return ScalarFunctionExpr(fname, exprs)

    make.__name__ = fname
    make.__doc__ = (
        f"Scalar function ``{fname}`` (datafusion parity).  A bare string "
        "as the first argument is a column name; later bare strings are "
        "literals."
    )
    return make


def _wrap_arg(a) -> Expr:
    from denormalized_tpu.logical.expr import _wrap

    return _wrap(a)


# functions whose FIRST string argument is a literal (unit name), not a
# column reference
_ALL_STR_LITERAL = {"date_trunc", "date_part", "extract", "chr"}

for _fname in REGISTRY:
    globals()[_fname] = _scalar_constructor(_fname)
del _fname


def udf(fn: Callable, return_type: DataType, name: str | None = None):
    """Scalar UDF over vectorized columns (reference udf_example.rs:22-60,
    py udf.py)."""

    name = name or getattr(fn, "__name__", "udf")

    def make(*args: Expr | str) -> Expr:
        exprs = tuple(col(a) if isinstance(a, str) else a for a in args)
        return ScalarUDFExpr(fn, exprs, name, return_type)

    return make


def udaf(accumulator_cls, return_type: DataType, name: str | None = None):
    """User-defined aggregate: ``accumulator_cls`` subclasses
    :class:`denormalized_tpu.api.udaf.Accumulator` (reference
    py-denormalized python/denormalized/datafusion/udf.py Accumulator +
    python/examples/udaf_example.py)."""
    from denormalized_tpu.api.udaf import UDAF

    name = name or getattr(accumulator_cls, "__name__", "udaf")

    def make(*args: Expr | str) -> AggregateExpr:
        exprs = [col(a) if isinstance(a, str) else a for a in args]
        u = UDAF(accumulator_cls, tuple(exprs), return_type, name)
        return AggregateExpr("udaf", exprs[0] if exprs else None, None, u)

    return make
