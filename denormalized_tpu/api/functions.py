"""Aggregate / scalar function constructors.

The public surface mirroring the subset of datafusion-python's
``functions`` module the reference re-exports
(py-denormalized/python/denormalized/datafusion/functions.py) and the Rust
examples use (count/min/max/avg at examples/examples/simple_aggregation.rs:40-46).
"""

from __future__ import annotations

from typing import Callable

from denormalized_tpu.common.schema import DataType
from denormalized_tpu.logical.expr import (
    AggregateExpr,
    Expr,
    ScalarUDFExpr,
    col,
)


def count(expr: Expr | str | None = None) -> AggregateExpr:
    e = col(expr) if isinstance(expr, str) else expr
    return AggregateExpr("count", e)


def sum(expr: Expr | str) -> AggregateExpr:  # noqa: A001 - mirrors SQL name
    e = col(expr) if isinstance(expr, str) else expr
    return AggregateExpr("sum", e)


def min(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    e = col(expr) if isinstance(expr, str) else expr
    return AggregateExpr("min", e)


def max(expr: Expr | str) -> AggregateExpr:  # noqa: A001
    e = col(expr) if isinstance(expr, str) else expr
    return AggregateExpr("max", e)


def avg(expr: Expr | str) -> AggregateExpr:
    e = col(expr) if isinstance(expr, str) else expr
    return AggregateExpr("avg", e)


def udf(fn: Callable, return_type: DataType, name: str | None = None):
    """Scalar UDF over vectorized columns (reference udf_example.rs:22-60,
    py udf.py)."""

    name = name or getattr(fn, "__name__", "udf")

    def make(*args: Expr | str) -> Expr:
        exprs = tuple(col(a) if isinstance(a, str) else a for a in args)
        return ScalarUDFExpr(fn, exprs, name, return_type)

    return make


def udaf(accumulator_cls, return_type: DataType, name: str | None = None):
    """User-defined aggregate: ``accumulator_cls`` subclasses
    :class:`denormalized_tpu.api.udaf.Accumulator` (reference
    py-denormalized python/denormalized/datafusion/udf.py Accumulator +
    python/examples/udaf_example.py)."""
    from denormalized_tpu.api.udaf import UDAF

    name = name or getattr(accumulator_cls, "__name__", "udaf")

    def make(*args: Expr | str) -> AggregateExpr:
        exprs = [col(a) if isinstance(a, str) else a for a in args]
        u = UDAF(accumulator_cls, tuple(exprs), return_type, name)
        return AggregateExpr("udaf", exprs[0] if exprs else None, None, u)

    return make
