"""User-defined aggregate functions.

Mirrors the reference's Python UDAF surface: users subclass ``Accumulator``
(py-denormalized python/denormalized/datafusion/udf.py; example stateful
accumulator at python/examples/udaf_example.py) with
update/merge/state/evaluate methods over numpy arrays instead of pyarrow.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from denormalized_tpu.common.schema import DataType


class Accumulator:
    """Stateful aggregate over one group within one window.

    Methods mirror datafusion-python's Accumulator protocol:
    - ``update(*columns)``: fold in a chunk of argument columns (numpy arrays)
    - ``merge(states)``: fold in another accumulator's ``state()`` output
    - ``state()``: serializable partial-aggregation state (list of values)
    - ``evaluate()``: final result
    """

    def update(self, *columns: np.ndarray) -> None:
        raise NotImplementedError

    def merge(self, states: Sequence) -> None:
        raise NotImplementedError

    def state(self) -> list:
        raise NotImplementedError

    def evaluate(self) -> Any:
        raise NotImplementedError


class UDAF:
    """Descriptor binding an Accumulator class to argument expressions.
    ``return_type=None`` means "same type as the first argument" (used by
    first_value/last_value, which are type-preserving like DataFusion's)."""

    def __init__(
        self, accumulator_cls, args, return_type: DataType | None, name: str
    ):
        self.accumulator_cls = accumulator_cls
        self.args = args  # tuple[Expr, ...]
        self.return_type = return_type
        self.name = name

    def make(self) -> Accumulator:
        return self.accumulator_cls()
