"""Session context — the framework entry point.

Mirror of the reference's ``Context`` (crates/core/src/context.rs:24-89) and
its Python wrapper (py-denormalized python/denormalized/context.py): builds
the session with streaming defaults, registers topics/sources as named
tables, and hands out :class:`DataStream` builders.  Where the reference
configures DataFusion (batch_size=32, coalesce off, custom planner/optimizer,
context.rs:27-58), we configure the TPU execution profile: batch bucketing,
accumulator dtype, state capacities, device mesh, and the checkpoint backend.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.sources.base import Source


@dataclass
class EngineConfig:
    """Engine tuning knobs (the reference's SessionConfig + the
    ``denormalized_config`` extension, config_extensions/denormalized_config.rs:4-13).

    The reference runs 32-row micro-batches with coalescing disabled to keep
    latency low on CPU; a TPU step amortizes dispatch over much larger
    buckets, so the default bucket is 8192 rows and sources should aim for
    ms-scale batches."""

    # logical optimizer (projection pruning / project merge / filter
    # pushdown — the reference's curated rule list analog,
    # utils/default_optimizer_rules.rs:29-65)
    optimizer: bool = True
    # checkpoint flag — mirror of denormalized_config.checkpoint
    checkpoint: bool = False
    checkpoint_interval_s: float = 10.0  # orchestrator cadence (orchestrator.rs:58)
    state_backend_path: str | None = None

    # device execution profile.  accum_dtype=jnp.float64 additionally
    # requires jax.config.update("jax_enable_x64", True) — the engine
    # REFUSES to run f64 without it (JAX would silently compute in f32).
    accum_dtype: Any = jnp.float32
    # compensated (Kahan-style) summation: sum components keep a (hi, lo)
    # buffer pair and each batch folds in via exact TwoSum.  Error bound vs
    # an f64 oracle: ~1e-6 relative at 1M f32 values per group (see
    # segment_agg.WindowKernelSpec.compensated); plain f32 drifts ~1e-4.
    compensated_sums: bool = False
    # streaming joins: rows older than the join watermark by more than this
    # are evicted (and emitted unmatched for outer joins)
    join_retention_ms: int = 300_000
    # band-aware eviction for interval joins (docs/joins.md): when set,
    # a retained row is also evictable once its band value falls more
    # than this slack below the horizon the other side's band watermark
    # implies — rows a band strictly tighter than retention can never
    # match stop occupying state.  The slack absorbs band-space
    # lateness: 0 is exact for per-side in-order band values, and with
    # event-time-like band expressions set it to your allowed lateness.
    # None (default) disables band-aware eviction (retention-only, the
    # pre-existing semantics: matches exist while co-retained).
    join_band_slack_ms: int | None = None
    # closed-loop skew adaptation (obs/doctor/actions.py): when a key's
    # sketched share crosses the skewed-join-side verdict thresholds, the
    # policy migrates it into a dense hot sub-partition (and folds it
    # back on decay).  Emissions are byte-identical either way — this is
    # a performance layout, not a semantics switch (docs/joins.md).
    join_adaptive: bool = True
    join_adapt_interval_s: float = 1.0
    min_batch_bucket: int = 256
    min_group_capacity: int = 128
    min_window_slots: int = 16
    emit_on_close: bool = True

    # idle sources: when EVERY partition of a live source has produced no
    # rows for this long, emit a WatermarkHint advancing event time to the
    # max timestamp seen, so windows over a quiet topic still close.
    # None (default) = reference behavior: the last windows of a quiet
    # stream wait for more data forever.
    source_idle_timeout_ms: int | None = None
    # per-partition watermarks: the source-level watermark is the MIN over
    # each partition's own max-of-batch-min-ts, so one fast-draining
    # partition cannot race the watermark ahead and drop the slower
    # partitions' backlog as late (replay/catch-up skew — the reference's
    # global max-of-min rule shares this flaw).  'auto' (default) enables
    # it for multi-partition sources whose liveness is guaranteed: bounded
    # sources, or unbounded ones with source_idle_timeout_ms set (quiet
    # partitions then leave the min instead of stalling it).  True forces
    # it on, False keeps reference semantics everywhere.
    partition_watermarks: bool | str = "auto"

    # sharding (parallel/): number of devices to shard group-state over;
    # None = single device
    mesh_devices: int | None = None
    # 2-D layout: split mesh_devices into this many row-parallel slices
    # (keys sharded within each slice, cross-slice merge at emission only
    # — the dp x tp analog; see parallel/sharded_state.TwoLevelWindowState)
    mesh_slices: int | None = None
    # 'auto' | 'key_sharded' | 'partial_final' | 'two_level'
    # (see parallel/sharded_state.py)
    shard_strategy: str = "auto"
    # single-device kernel strategy:
    #   'scatter'       — ship rows, device scatters them into the window
    #                     ring (general; right when host↔device bandwidth
    #                     is plentiful, e.g. CPU JAX or co-located TPU)
    #   'pallas_dense'  — ship rows, dense MXU/VPU pallas kernel for
    #                     low-cardinality aggregation (auto-falls-back)
    #   'partial_merge' — reduce each batch on host (native C++ single
    #                     pass) and ship per-(slide-unit, group) partials;
    #                     the device merges them into the ring.  Traffic
    #                     scales with cardinality, not rows — the right
    #                     choice behind a narrow host↔device link
    #   'auto'          — partial_merge on single-device TPU (host
    #                     edge-reduction wins on the narrow link) and CPU
    #                     (it beats XLA scatter adds there too), except
    #                     f64 accumulators on CPU, which keep scatter:
    #                     the partial stripe's f32 hi/lo transport cannot
    #                     carry finite f64 sums beyond f32 range.  On
    #                     backends neither measurement covers (e.g. a
    #                     co-located GPU) 'auto' keeps row shipping
    device_strategy: str = "auto"
    # partial_merge pacing: merge the host stripe after this many rows even
    # if no window closed, and defer emission up to emit_lag_ms after a
    # window becomes closable so replay-speed runs batch several windows
    # per device round-trip.  None = backend default: 0 on CPU (merges
    # are memcpy-cheap, and deferral would hold a paused live stream's
    # final windows until the next rowful batch), 200ms on every
    # accelerator backend (TPU, GPU, ...) where the remote merge
    # round-trip is worth amortizing
    partial_merge_rows: int = 4_000_000
    emit_lag_ms: int | None = None
    # run backend.accumulate (native stripe reduction, GIL-releasing) on a
    # worker thread so batch N's reduction overlaps batch N+1's
    # decode/eval/intern.  Default OFF: on CPU JAX the worker contends
    # with device programs for the same cores (measured 13-21% SLOWER);
    # worth A/B-ing on a real chip where device work leaves the host idle
    host_pipeline: bool = False
    # device-side emission compaction: permute active groups to the front on
    # device and transfer only a pow2 bucket covering them, instead of all G
    # rows per component.  Wins when emitted windows are sparse vs the
    # padded capacity; default off pending real-chip A/B.
    emission_compaction: bool = False
    # on-device finalization: emission ships the FINAL output columns
    # (count/sum/min/max/avg, computed on device in accum dtype) plus an
    # active-group bitmask, instead of the raw component planes — fewer
    # bytes per emitted window on a narrow link, and no host finalize.
    # Falls back per-operator when an aggregate isn't finalizable on
    # device (variance family) or the state layout doesn't support it.
    device_finalize: bool = True
    # -- observability (denormalized_tpu/obs, docs/observability.md) ----
    # default-level metrics: typed registry instruments across every
    # layer (per-operator batch time + rows, watermark/emit lag, kafka
    # consumer lag, prefetch depth/restarts, checkpoint/LSM timings).
    # False binds every handle to a shared no-op null — the hot paths
    # then do literally nothing (pinned by tests/test_obs.py)
    metrics_enabled: bool = True
    # opt-in Prometheus text-exposition endpoint on a stdlib HTTP server
    # (127.0.0.1); 0 = ephemeral port (read it back from
    # ctx._last_exporters.prometheus.port), None = off
    prometheus_port: int | None = None
    # periodic JSONL registry snapshots (soak/bench telemetry stream);
    # None = off
    metrics_jsonl_path: str | None = None
    metrics_jsonl_interval_s: float = 1.0
    # Chrome trace-event JSON (Perfetto-loadable) dumped at stream end
    # from the ring-buffered span recorder; None = off.  trace_events
    # sizes the ring (newest events win; 0 = default 65536)
    trace_path: str | None = None
    trace_events: int = 0
    # -- pipeline doctor (obs/doctor, docs/observability.md §doctor) ----
    # live query introspection: every execution registers its physical
    # plan (node-id keyed) with per-operator busy/queue-wait stats and
    # ranked bottleneck attribution, served at /queries[/<id>/plan] on
    # the Prometheus HTTP server and via df.explain_analyze().  Costs a
    # few plain attribute adds per batch; False opts a query out.
    doctor_enabled: bool = True
    # sampled record lineage: tag every Nth row per partition at ingest
    # with (source, partition, offset, event time) and follow it through
    # operator handoffs into window emission — "why is this window late"
    # becomes GET /queries/<id>/lineage.  None (default) = off; when on,
    # adds an O(rows) timestamp min/max per batch per operator.
    lineage_sample_every: int | None = None
    lineage_max_samples: int = 256
    # on-demand sampling profiler (sys._current_frames folded stacks for
    # flamegraphs): started per query via the HTTP surface or
    # QueryHandle.start_profiler(); this sets only the sample rate
    profiler_hz: float = 100.0
    # -- state observatory (obs/statewatch.py, docs §state observatory) -
    # soft budget for TOTAL live keyed state across a query's stateful
    # operators: GET /queries/<id>/state projects time-to-budget from
    # each operator's growth ring and raises state-budget-pressure
    # verdicts as the projection closes in.  None = no budget (growth
    # forecasts still reported, without a time-to-budget).
    state_budget_bytes: int | None = None
    # tiered state (state/tiering.py, docs/state_spill.md): when a budget
    # AND a state backend are both configured, stateful operators evict
    # their coldest key/batch/window blocks to the LSM once accounted
    # state crosses the budget, and reload them on touch — the query
    # degrades to disk speed instead of OOMing.  'auto' (default) =
    # active exactly when budget + state_backend_path are set; False
    # disables (budget stays forecast-only, PR-8 semantics); True
    # additionally REQUIRES a backend path (loud error instead of a
    # silently forecast-only budget).
    state_spill: bool | str = "auto"

    # -- multi-query engine (docs/multi_query.md) -----------------------
    # slice-folding window path: tumbling/sliding windows with builtin
    # (foldable) aggregates run on SliceWindowExec — per-(group,
    # slide-unit) partials accumulated once per batch, windows folded
    # from slice partials instead of scattering each row into every
    # overlapping window.  This is the kernel the multi-query sharing
    # runtime (runtime/multi_query.py) always uses; setting True here
    # additionally applies it to SINGLE queries planned through the
    # normal executor (the sliding-window fast path; A/B'd in
    # BENCH_HISTORY.jsonl under config=multi_query).  Default False: the
    # device ring operator stays the single-query default pending a
    # real-chip A/B — slice folds are host-side f64, so emitted floats
    # can differ from the f32 device ring in the last ulp.
    slice_windows: bool = False
    # explicit slice width for the slice path (must divide the window's
    # length AND slide; None = their gcd).  The fold grouping is part of
    # a query's numeric contract — f64 sums round per fold tree — so an
    # independent oracle comparing byte-identically against a shared
    # group pins the group's gcd unit here (tests/bench do).
    slice_unit_ms: int | None = None
    # pin the slice store's lexsort accumulation lane (add-only
    # component sets otherwise take the faster bincount lane, which
    # associates long-segment adds differently).  A shared group whose
    # aggregate UNION carries min/max always sorts, so an add-only
    # member's byte-identity oracle sets this True to match.
    slice_sort_lane: bool = False
    # approximate aggregates (approx_distinct / approx_top_k /
    # approx_percentile_cont / approx_median) as first-class sketch
    # planes on the slice path — constant state per group regardless of
    # value cardinality (ops/sketches.py).  Only takes effect with
    # slice_windows=True; False lowers them to their exact accumulator
    # UDAFs everywhere (the historical behavior, and the bench's A/B
    # control for the approx_scale sweep).
    approx_native: bool = True
    # predicate-subsumption sharing in the multi-query runtime: a query
    # whose filter is provably implied by another's (conjunct
    # containment over equality/range/IN bounds — planner/predicates.py)
    # joins that query's share group, ingesting once under the weakest
    # member predicate with a vectorized residual re-filter per
    # stronger member.  False restores exact-signature matching only
    # (the pre-subsumption behavior; the bench's A/B control).
    mq_subsumption: bool = True

    # persistent XLA compilation cache (jax_compilation_cache_dir): the
    # engine prewarms its program ladders at stream start, which on a
    # remote-compile TPU backend costs seconds per program on FIRST run;
    # with the cache every later process start loads compiled binaries
    # from disk instead.  None disables; default under ~/.cache.
    compilation_cache_dir: str | None = "~/.cache/denormalized_tpu/xla"

    def set(self, key: str, value) -> "EngineConfig":
        """String-keyed setter for parity with SessionConfig::set
        (README.md:105 `denormalized_config.checkpoint`)."""
        k = key.removeprefix("denormalized_config.")
        if not hasattr(self, k):
            raise PlanError(f"unknown config key {key!r}")
        setattr(self, k, value)
        return self


_cache_enabled = False
_pending_cache_path: str | None = None


def _activate_compilation_cache(path: str) -> None:
    import os

    import jax

    full = os.path.expanduser(path)
    os.makedirs(full, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", full)
    # cache even fast compiles: the ladder programs are individually
    # cheap to compile locally but each costs a round-trip on a
    # remote-compile backend
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _enable_compilation_cache(path: str | None) -> None:
    """Point JAX's persistent compilation cache at ``path`` (once per
    process).  A user-set ``JAX_COMPILATION_CACHE_DIR`` or an earlier
    explicit configuration wins; failures are non-fatal (a read-only HOME
    must not kill the stream — it just recompiles).

    Only worthwhile for remote-compile accelerator backends; local CPU
    compiles are fast, and caching them risks loading AOT artifacts whose
    target machine features don't match the host (XLA warns of possible
    SIGILL).  When the platform is explicitly configured we decide here;
    when it is auto-detected (no JAX_PLATFORMS — the common TPU
    deployment) the decision is DEFERRED to
    :func:`ensure_compilation_cache_for_backend`, called from the device
    chokepoint once a real backend exists, so auto-detected TPUs still
    get the cache (round-2 ADVICE item)."""
    global _cache_enabled, _pending_cache_path
    if path is None or _cache_enabled:
        return
    _cache_enabled = True
    import os

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return
        plat = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
        if not plat:
            # platform unknown until backend init — don't guess "cpu";
            # remember the path and let the first device touch decide
            _pending_cache_path = path
            return
        if "cpu" in plat:
            return
        _activate_compilation_cache(path)
    except Exception:  # dnzlint: allow(broad-except) the compilation cache is a pure optimization — a jax-version quirk here must never take the engine down
        pass


def ensure_compilation_cache_for_backend() -> None:
    """Finish a deferred cache decision now that a backend is initialized
    (called from the window-state factory, the first point that touches
    the device).  No-op unless Context deferred with a pending path."""
    global _pending_cache_path
    if _pending_cache_path is None:
        return
    path, _pending_cache_path = _pending_cache_path, None
    try:
        import jax

        if jax.default_backend() != "cpu":
            _activate_compilation_cache(path)
    except Exception:  # dnzlint: allow(broad-except) the compilation cache is a pure optimization — a jax-version quirk here must never take the engine down
        pass


class Context:
    """Session factory: registers sources, builds streams."""

    def __init__(self, config: EngineConfig | None = None) -> None:
        self.config = config or EngineConfig()
        self._tables: dict[str, Source] = {}
        self._orchestrator = None
        # metrics_enabled is resolved by the EXECUTOR per execution
        # (runtime/executor.py _resolve_registry): each query binds its
        # operators against its own resolved registry — live handles or
        # shared nulls — so concurrently EXECUTING queries with
        # different settings no longer fight over a process-global flag
        # (the PR-6 documented limitation, since fixed).
        _enable_compilation_cache(self.config.compilation_cache_dir)

    def __repr__(self) -> str:
        """String representation (reference context.py:16-30)."""
        return (
            f"Context(tables=[{', '.join(sorted(self._tables))}], "
            f"checkpoint={self.config.checkpoint})"
        )

    def __str__(self) -> str:
        return self.__repr__()

    # -- registration (Context::from_topic, context.rs:65-72) -----------
    def register_source(self, name: str, source: Source) -> None:
        self._tables[name] = source

    def from_source(self, source: Source, name: str | None = None):
        from denormalized_tpu.api.data_stream import DataStream

        name = name or source.name
        self.register_source(name, source)
        scan = lp.Scan(name, source, source.schema)
        return DataStream(scan, self)

    def from_topic(
        self,
        topic: str,
        sample_json: str | None = None,
        bootstrap_servers: str = "localhost:9092",
        timestamp_column: str | None = None,
        group_id: str = "denormalized-tpu",
        encoding: str = "json",
        schema: Schema | None = None,
        avro_schema=None,
        timestamp_unit: str | None = None,
    ):
        """Kafka source entry point (PyContext::from_topic,
        py-denormalized/src/context.rs:50-117): schema comes from an explicit
        Schema, is inferred from ``sample_json``, or — for
        ``encoding="avro"`` — derives from ``avro_schema`` (an Avro record
        declaration as JSON string or dict).

        Parameter ORDER matches the reference wrapper exactly
        (py-denormalized/python/denormalized/context.py:32-39:
        topic, sample_json, bootstrap_servers, timestamp_column,
        group_id) — a migrating user's positional call
        ``from_topic("t", sample, server, "occurred_at_ms")`` must bind
        the timestamp column, not the consumer group id; getting this
        wrong silently demotes event-time to broker arrival time."""
        from denormalized_tpu.sources.kafka import KafkaTopicBuilder

        builder = (
            KafkaTopicBuilder(bootstrap_servers)
            .with_topic(topic)
            .with_encoding(encoding)
            .with_group_id(group_id)
        )
        if timestamp_column:
            builder = builder.with_timestamp_column(timestamp_column)
        if timestamp_unit:
            builder = builder.with_timestamp_unit(timestamp_unit)
        if avro_schema is not None:
            # conflicting arguments are errors, not silent overrides
            if schema is not None:
                raise PlanError(
                    "pass either schema= or avro_schema=, not both (the "
                    "Avro declaration defines the schema)"
                )
            if encoding.lower() != "avro":
                raise PlanError(
                    f"avro_schema= conflicts with encoding={encoding!r}"
                )
            builder = builder.with_avro_schema(avro_schema)
        elif schema is not None:
            builder = builder.with_schema(schema)
        elif sample_json is not None:
            builder = builder.infer_schema_from_json(sample_json)
        return self.from_source(builder.build_reader(), name=topic)

    def table(self, name: str) -> Source:
        if name not in self._tables:
            raise PlanError(f"unknown table {name!r}")
        return self._tables[name]

    # -- state backend (Context::with_slatedb_backend, context.rs:77-86) -
    def with_state_backend(self, path: str) -> "Context":
        self.config.state_backend_path = path
        self.config.checkpoint = True
        return self
