"""Python binding for the native LSM KV store (ctypes; builds the shared
library on first use with g++).

API mirror of the reference's ``SlateDBWrapper``
(state_backend/slatedb.rs:28-92): string-keyed put/get/delete/close with a
process-global instance (``initialize_global_state_backend`` /
``get_global_state_backend`` mirroring ``initialize_global_slatedb`` /
``get_global_slatedb``, :9-26).  A pure-Python engine with the identical
segment format is the fallback when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import time
import zlib
from pathlib import Path

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.runtime import faults
from denormalized_tpu.runtime.tracing import logger

_NATIVE_SRC = Path(__file__).resolve().parent.parent / "native" / "lsmkv.cpp"
_BUILD_LOCK = threading.Lock()
_LIB = None
_LIB_FAILED = False


def _load_native():
    global _LIB, _LIB_FAILED
    if os.environ.get("DENORMALIZED_LSM_PY"):
        # force the pure-Python engine (chaos soak / tests: its replay
        # accounting and torn-tail handling must be exercisable on boxes
        # where the native build exists)
        return None
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            # one staleness rule for the artifact: build.compile hashes
            # source + quoted includes + flags into a stamp, so a flag or
            # header change rebuilds here too (the old mtime-only check
            # ignored both and could serve a stale .so forever)
            from denormalized_tpu.native import build

            so_path = build.compile("lsmkv")
            lib = ctypes.CDLL(str(so_path))
            lib.lsm_open.restype = ctypes.c_void_p
            lib.lsm_open.argtypes = [ctypes.c_char_p]
            lib.lsm_put.restype = ctypes.c_int
            lib.lsm_put.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_uint32,
            ]
            lib.lsm_delete.restype = ctypes.c_int
            lib.lsm_delete.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
            ]
            lib.lsm_get.restype = ctypes.c_int64
            lib.lsm_get.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ]
            lib.lsm_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
            lib.lsm_flush.restype = ctypes.c_int
            lib.lsm_flush.argtypes = [ctypes.c_void_p]
            lib.lsm_count.restype = ctypes.c_uint64
            lib.lsm_count.argtypes = [ctypes.c_void_p]
            lib.lsm_keys.restype = ctypes.c_int64
            lib.lsm_keys.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ]
            lib.lsm_compact.restype = ctypes.c_int
            lib.lsm_compact.argtypes = [ctypes.c_void_p]
            lib.lsm_close.argtypes = [ctypes.c_void_p]
            _LIB = lib
        except Exception as e:  # dnzlint: allow(broad-except) no-compiler boxes fall back to _PyLsm by design; the failure is logged below and test_native_build_gate fails CI images where the build SHOULD work
            # the silent version of this except is how the JSON parser
            # shipped broken for five rounds (CHANGES.md PR 1) — the
            # fallback stays, the silence does not (build.compile embeds
            # the compiler's stderr in its RuntimeError)
            logger.warning(
                "native LSM build/load failed — falling back to the "
                "pure-Python engine (slower, same format): %s",
                str(e)[-600:],
            )
            _LIB_FAILED = True
    return _LIB


class LsmStore:
    """String/bytes-keyed durable KV store."""

    def __init__(self, path: str):
        self.path = str(path)
        # op-latency histograms (falsy no-ops when metrics are disabled,
        # so the timing brackets below cost nothing then)
        from denormalized_tpu import obs

        self._obs_put_ms = obs.histogram("dnz_lsm_op_ms", op="put")
        self._obs_get_ms = obs.histogram("dnz_lsm_op_ms", op="get")
        self._obs_flush_ms = obs.histogram("dnz_lsm_op_ms", op="flush")
        # state observatory: the backend's live footprint joins the same
        # dnz_state_* families the operators report under, keyed
        # node="state_backend".  Weakref'd like every pull gauge — the
        # registry must never pin a closed store.
        import weakref

        ref = weakref.ref(self)

        def _disk_bytes():
            st = ref()
            if st is None or st._closed:
                return 0
            total = 0
            try:
                for p in Path(st.path).iterdir():
                    if p.is_file():
                        total += p.stat().st_size
            except OSError:
                return 0
            return total

        def _live_keys():
            st = ref()
            if st is None or st._closed:
                return 0
            return len(st)

        obs.gauge_fn("dnz_state_bytes", _disk_bytes, node="state_backend")
        obs.gauge_fn(
            "dnz_state_live_keys", _live_keys, node="state_backend"
        )
        lib = _load_native()
        if lib is not None:
            self._lib = lib
            self._h = lib.lsm_open(self.path.encode())
            if not self._h:
                raise StateError(f"cannot open state backend at {path!r}")
            self._py = None
        else:
            self._lib = None
            self._py = _PyLsm(self.path)
        self._closed = False

    def _check_open(self) -> None:
        """Every op checks this FIRST: a put/get/delete/flush on a closed
        native store would hand ctypes a freed handle — a potential
        segfault, not a Python error — so the guard must precede any
        native call."""
        if self._closed:
            raise StateError("state backend closed")

    # -- API (mirrors SlateDBWrapper::{put,get,close}) -------------------
    def put(self, key: str | bytes, value: bytes) -> None:
        self._check_open()
        k = key.encode() if isinstance(key, str) else key
        if faults.armed():  # unarmed path builds no key string
            value = faults.inject(
                "lsm.put", key=k.decode("utf-8", "replace"), payload=value
            )
        t0 = time.perf_counter() if self._obs_put_ms else 0.0
        if self._lib:
            if self._lib.lsm_put(self._h, k, len(k), value, len(value)) != 0:
                raise StateError("put failed")
        else:
            self._py.put(k, value)
        if self._obs_put_ms:
            self._obs_put_ms.observe((time.perf_counter() - t0) * 1e3)

    def get(self, key: str | bytes) -> bytes | None:
        self._check_open()
        k = key.encode() if isinstance(key, str) else key
        if faults.armed():  # unarmed path builds no key string
            faults.inject("lsm.get", key=k.decode("utf-8", "replace"))
        t0 = time.perf_counter() if self._obs_get_ms else 0.0
        try:
            if self._lib:
                out = ctypes.POINTER(ctypes.c_uint8)()
                n = self._lib.lsm_get(self._h, k, len(k), ctypes.byref(out))
                if n < 0:
                    return None
                try:
                    return ctypes.string_at(out, n)
                finally:
                    self._lib.lsm_free(out)
            return self._py.get(k)
        finally:
            if self._obs_get_ms:
                self._obs_get_ms.observe((time.perf_counter() - t0) * 1e3)

    def delete(self, key: str | bytes) -> None:
        self._check_open()
        k = key.encode() if isinstance(key, str) else key
        if self._lib:
            self._lib.lsm_delete(self._h, k, len(k))
        else:
            self._py.delete(k)

    def keys(self) -> list[bytes]:
        self._check_open()
        if self._lib:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.lsm_keys(self._h, ctypes.byref(out))
            try:
                raw = ctypes.string_at(out, n) if n > 0 else b""
            finally:
                self._lib.lsm_free(out)
            return [k for k in raw.split(b"\n") if k]
        return self._py.keys()

    def __len__(self) -> int:
        self._check_open()
        if self._lib:
            return int(self._lib.lsm_count(self._h))
        return len(self._py.index)

    def flush(self) -> None:
        self._check_open()
        faults.inject("lsm.flush")
        t0 = time.perf_counter() if self._obs_flush_ms else 0.0
        if self._lib:
            self._lib.lsm_flush(self._h)
        else:
            self._py.flush()
        if self._obs_flush_ms:
            self._obs_flush_ms.observe((time.perf_counter() - t0) * 1e3)

    def compact(self) -> None:
        self._check_open()
        if self._lib:
            if self._lib.lsm_compact(self._h) != 0:
                raise StateError("compact failed")
        else:
            self._py.compact()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._lib:
            self._lib.lsm_close(self._h)
        else:
            self._py.close()

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    @property
    def replay_truncated(self) -> int:
        """How many torn segment tails startup replay dropped (0 on the
        native engine, whose replay truncation happens inside lsmkv.cpp
        and is not counted here).  A nonzero value after recovery is the
        signal that a crash landed mid-append — expected after SIGKILL,
        alarming after a clean shutdown."""
        return self._py.replay_truncated if self._py is not None else 0


class _PyLsm:
    """Pure-Python fallback speaking the exact same segment format."""

    _HDR = struct.Struct("<III B")

    def __init__(self, path: str):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.index: dict[bytes, tuple[int, int, int]] = {}
        #: torn segment tails dropped by startup replay — recovery after a
        #: crash mid-append is EXPECTED to bump this; a silent count was
        #: the old behavior and hid real tears from every operator
        self.replay_truncated = 0
        from denormalized_tpu import obs

        self._obs_replay_trunc = obs.counter(
            "dnz_lsm_replay_truncated_total"
        )
        segs = sorted(
            int(p.name[4:12]) for p in self.dir.glob("seg-*.log")
        )
        for seg in segs:
            self._replay(seg)
        self.active_seg = (segs[-1] + 1) if segs else 0
        self.active = open(self._seg(self.active_seg), "ab")
        self.active_size = 0

    def _seg(self, n: int) -> Path:
        return self.dir / f"seg-{n:08d}.log"

    def _replay(self, seg: int):
        off = 0
        with open(self._seg(seg), "rb") as f:
            data = f.read()
        torn_at = None
        while off + 13 <= len(data):
            crc, klen, vlen, tomb = self._HDR.unpack_from(data, off)
            end = off + 13 + klen + vlen
            if end > len(data) or zlib.crc32(data[off + 4 : end]) != crc:
                # torn tail: every byte from here on is untrusted (records
                # are not self-synchronizing, so resyncing past a bad CRC
                # could resurrect stale garbage as live records) — keep
                # the truncation semantics, but LOUDLY
                torn_at = off
                break
            key = data[off + 13 : off + 13 + klen]
            if tomb:
                self.index.pop(key, None)
            else:
                self.index[key] = (seg, off + 13 + klen, vlen)
            off = end
        if torn_at is None and off < len(data):
            torn_at = off  # trailing partial header (< 13 bytes)
        if torn_at is not None:
            self.replay_truncated += 1
            self._obs_replay_trunc.add(1)
            logger.warning(
                "lsm %s: segment %d torn at offset %d — dropping %d "
                "trailing byte(s) (crash mid-append; later records, if "
                "any, are unrecoverable)",
                self.dir, seg, torn_at, len(data) - torn_at,
            )

    def _append(self, key: bytes, value: bytes, tomb: int):
        body = self._HDR.pack(0, len(key), len(value), tomb)[4:] + key + value
        rec = struct.pack("<I", zlib.crc32(body)) + body
        self.active.write(rec)
        if tomb:
            self.index.pop(key, None)
        else:
            self.index[key] = (
                self.active_seg,
                self.active_size + 13 + len(key),
                len(value),
            )
        self.active_size += len(rec)

    def put(self, key: bytes, value: bytes):
        self._append(key, value, 0)

    def delete(self, key: bytes):
        self._append(key, b"", 1)

    def get(self, key: bytes) -> bytes | None:
        e = self.index.get(key)
        if e is None:
            return None
        seg, off, vlen = e
        if seg == self.active_seg:
            self.active.flush()
        with open(self._seg(seg), "rb") as f:
            f.seek(off)
            return f.read(vlen)

    def keys(self) -> list[bytes]:
        return sorted(self.index)

    def flush(self):
        self.active.flush()
        os.fsync(self.active.fileno())

    def compact(self):
        new_seg = self.active_seg + 1
        self.active.flush()
        new_index = {}
        size = 0
        with open(self._seg(new_seg), "ab") as nf:
            for key in sorted(self.index):
                val = self.get(key)
                body = (
                    self._HDR.pack(0, len(key), len(val), 0)[4:] + key + val
                )
                rec = struct.pack("<I", zlib.crc32(body)) + body
                nf.write(rec)
                new_index[key] = (new_seg, size + 13 + len(key), len(val))
                size += len(rec)
            nf.flush()
            os.fsync(nf.fileno())
        old = self.active_seg
        self.active.close()
        self.active = open(self._seg(new_seg), "ab")
        self.active_seg = new_seg
        self.active_size = size
        self.index = new_index
        for p in self.dir.glob("seg-*.log"):
            if int(p.name[4:12]) <= old:
                p.unlink()

    def close(self):
        self.flush()
        self.active.close()


# -- process-global instance (mirror of slatedb.rs:9-26) -----------------

_GLOBAL: LsmStore | None = None
_GLOBAL_LOCK = threading.Lock()


def initialize_global_state_backend(path: str) -> LsmStore:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None or _GLOBAL.path != str(path) or _GLOBAL._closed:
            if _GLOBAL is not None and not _GLOBAL._closed:
                # flush + release the previous store before replacing it —
                # silently dropping it would leak the fd and lose its
                # buffered tail records
                _GLOBAL.close()
            _GLOBAL = LsmStore(path)
        return _GLOBAL


def get_global_state_backend() -> LsmStore:
    if _GLOBAL is None:
        raise StateError("state backend not initialized")
    return _GLOBAL


def close_global_state_backend() -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
            _GLOBAL = None
