"""Checkpoint wiring: connect the orchestrator, the state backend, and the
physical plan.

Mirrors the reference's checkpoint topology (SURVEY.md §3.4): sources persist
their offsets when a barrier passes (kafka_stream_read.rs:275-289) and window
streams persist watermark + frames (grouped_window_agg_stream.rs:355-418),
all keyed by ``{node_id}_{partition}`` tags in the state backend; on startup
operators probe the backend by tag and restore
(kafka_stream_read.rs:110-140, grouped_window_agg_stream.rs:160-211).  The
fork's ``node_id`` plumbing (``with_node_id``) becomes a deterministic DFS
numbering of the physical plan here — stable across runs because the plan is
rebuilt deterministically from the same query.

Atomicity — an improvement over the reference's fire-and-forget puts
(slatedb.rs:60-66): snapshots for barrier epoch ``E`` are written under
epoch-suffixed keys ``{key}@{E}`` as the in-band marker passes each
operator; when the marker drains at the plan root, the executor calls
:meth:`CheckpointCoordinator.commit`, which writes the epoch's key
manifest, fsyncs the store, and only then writes the ``committed_epoch``
record (also fsynced).  Restore reads the committed epoch and loads
exactly that epoch's snapshots — a half-written barrier (crash between
operator snapshots) is invisible, so recovery never mixes epochs.

Integrity + fallback (the self-healing half): every snapshot blob is
framed with a small header (magic, version, CRC32, length) written by
:meth:`put_snapshot` and verified on read; commit retains the last
``RETAINED_EPOCHS`` committed epochs instead of GC-ing N-1 immediately;
restore verifies ALL snapshots of the committed epoch up front (manifest
completeness + per-blob CRC) and falls back to the previous committed
epoch — with a loud warning and ``restored_from_fallback`` set — when any
blob is corrupt, torn, or missing, so one bad write degrades recovery to
an older cut instead of bricking it.  Pre-header (legacy) blobs and
manifest-less epochs still load.  Transient ``StateError`` during commit
is retried a bounded number of times (``commit_retries`` counts them)
before surfacing.

Consistency: barriers flow in-band (see orchestrator.py), so on single-input
chains the snapshot is an aligned cut and recovery is exactly-once w.r.t.
engine state; emission to sinks remains at-least-once (windows that closed
after the last barrier re-emit on recovery), matching the reference.  Join
operators checkpoint too (both sides' retained build rows + matched flags +
watermarks, physical/join_exec.py enable_checkpointing) — BEYOND the
reference, which checkpoints only sources and window state; at a join the
early side's post-marker items are buffered until the other side's marker
arrives, so the two-input cut is aligned as well.
"""

from __future__ import annotations

import json
import struct
import time
import zlib

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.physical.base import ExecOperator
from denormalized_tpu.runtime import faults
from denormalized_tpu.runtime.tracing import logger
from denormalized_tpu.state.lsm import initialize_global_state_backend
from denormalized_tpu.state.orchestrator import CheckpointBarrier, Orchestrator

_COMMIT_KEY = "committed_epoch"
_HISTORY_KEY = "committed_epoch_history"

#: committed epochs kept on disk — the fallback depth.  2 = one corrupt
#: committed epoch can always fall back to an intact predecessor.
RETAINED_EPOCHS = 2

# snapshot blob framing: magic + version + payload CRC32 + payload length.
# Verification is how a torn/corrupt blob is DETECTED instead of being
# json-decoded into garbage (or half-garbage) at restore.  Blobs that do
# not start with the magic are legacy pre-header snapshots and pass
# through verbatim — existing checkpoints stay readable.
_SNAP_MAGIC = b"DNZ1"
_SNAP_HDR = struct.Struct("<4sBII")
_SNAP_VERSION = 1

_COMMIT_ATTEMPTS = 3  # transient-StateError retries inside commit


def epoch_of_key(kb: bytes) -> int | None:
    """Epoch suffix of a ``{key}@{epoch}`` store key, or None — the ONE
    place the suffix grammar is parsed (GC, discovery, and verification
    must never disagree about which keys belong to an epoch)."""
    k = kb.decode("utf-8", "replace")
    sep = k.rfind("@")
    if sep < 0:
        return None
    try:
        return int(k[sep + 1:])
    except ValueError:
        return None


def frame_snapshot(blob: bytes) -> bytes:
    """Wrap a snapshot payload in the integrity header."""
    return _SNAP_HDR.pack(
        _SNAP_MAGIC, _SNAP_VERSION, zlib.crc32(blob), len(blob)
    ) + blob


def unframe_snapshot(raw: bytes) -> tuple[bool, bytes | None]:
    """→ (intact, payload).  Headerless (legacy) blobs are intact by
    definition — there is nothing to verify them against."""
    if not raw.startswith(_SNAP_MAGIC):
        # a framed blob torn to < 4 bytes loses the magic itself; every
        # such cut leaves a strict prefix of the magic (incl. b"") — that
        # is corruption, not a legacy payload
        if len(raw) < len(_SNAP_MAGIC) and _SNAP_MAGIC.startswith(raw):
            return False, None
        return True, raw
    if len(raw) < _SNAP_HDR.size:
        return False, None
    magic, version, crc, length = _SNAP_HDR.unpack_from(raw)
    payload = raw[_SNAP_HDR.size:]
    if (
        version != _SNAP_VERSION
        or len(payload) != length
        or zlib.crc32(payload) != crc
    ):
        return False, None
    return True, payload


def walk(op: ExecOperator):
    yield op
    for c in op.children:
        yield from walk(c)


def assign_node_ids(root: ExecOperator) -> dict[int, str]:
    """Deterministic DFS-preorder node ids (the fork's node_id analog)."""
    ids: dict[int, str] = {}
    for i, op in enumerate(walk(root)):
        ids[id(op)] = f"{i}_{type(op).__name__}"
    return ids


class CheckpointCoordinator:
    """Epoch-aware snapshot IO shared by all operators of one query."""

    def __init__(self, backend):
        from denormalized_tpu import obs

        self.backend = backend
        self.commit_retries = 0
        self._obs_commit_ms = obs.histogram("dnz_checkpoint_commit_ms")
        self._obs_snap_bytes = obs.histogram(
            "dnz_checkpoint_snapshot_bytes"
        )
        self._obs_epoch = obs.gauge("dnz_checkpoint_committed_epoch")
        self._obs_retries = obs.counter(
            "dnz_checkpoint_commit_retries_total"
        )
        #: True when the committed epoch failed integrity verification and
        #: recovery degraded to an older retained epoch
        self.restored_from_fallback = False
        committed, commit_corrupt = self._read_committed()
        history = self._read_history(committed)
        selected = self._select_restore_epoch(
            committed, history, commit_corrupt
        )
        # retained history after selection: epochs at or below the
        # recovery point, capped at the retention window.  A REJECTED
        # newer epoch must leave, but older intact epochs must STAY —
        # a torn commit record repaired to the newest intact epoch keeps
        # its full safety margin instead of collapsing to depth 1 (which
        # would GC an intact epoch a second crash might still need)
        kept = (
            sorted(
                set(e for e in history if e <= selected) | {selected}
            )[-RETAINED_EPOCHS:]
            if selected is not None else []
        )
        if selected is not None and selected != committed:
            # make the fallback decision DURABLE before any GC touches the
            # rejected epoch: a crash before the next commit must land on
            # this same (intact) epoch, not re-read a commit record whose
            # blobs are gone and "restore" empty state.  Retried like
            # commit's writes — a transient hiccup here would otherwise
            # abort a recovery that has already found an intact epoch.
            last: StateError | None = None
            for attempt in range(_COMMIT_ATTEMPTS):
                try:
                    backend.put(_COMMIT_KEY, str(selected).encode())
                    backend.put(
                        _HISTORY_KEY, json.dumps(kept).encode()
                    )
                    backend.flush()
                    last = None
                    break
                except StateError as e:
                    last = e
                    if attempt < _COMMIT_ATTEMPTS - 1:
                        time.sleep(0.01 * (attempt + 1))
            if last is not None:
                raise last
        self.committed_epoch: int | None = selected
        #: the epoch this run RECOVERED from, frozen at construction —
        #: committed_epoch moves with every new commit, but transactional
        #: sinks need the recovery point itself: output the previous
        #: incarnation wrote with an in-flight epoch beyond this value is
        #: exactly the uncommitted suffix a restore regenerates, and a
        #: recovery reader must discard it (truncate-on-restore)
        self.restored_epoch: int | None = selected
        self.committed_history: list[int] = kept
        self._epoch_keys: dict[int, list[str]] = {}
        #: epochs inherited from previous incarnations (restored history)
        #: — commit-time GC must sweep these too once they leave the
        #: retention window; in-memory _epoch_keys only knows THIS
        #: incarnation's writes
        self._known_epochs: set[int] = set(self.committed_history)
        if selected is not None:
            self._gc_stale_epochs()

    def _gc_stale_epochs(self) -> None:
        """Startup GC: drop epoch-suffixed keys outside the retained
        history — snapshots of a half-written (never committed) barrier,
        the corrupt epoch a fallback just skipped, and epochs a previous
        incarnation wrote but never lived to GC (in-process bookkeeping
        dies with the process; this scan is the cross-restart sweep)."""
        keep = set(self.committed_history)
        if self.committed_epoch is not None:
            keep.add(self.committed_epoch)
        for kb in list(self.backend.keys()):
            epoch = epoch_of_key(kb)
            if epoch is not None and epoch not in keep:
                self.backend.delete(kb)

    # -- restore-time integrity ------------------------------------------
    def _read_committed(self) -> tuple[int | None, bool]:
        """→ (epoch, record_corrupt).  A missing record is a fresh store;
        a PRESENT-but-unparseable record is a torn commit — the two must
        never be conflated (a torn record with intact snapshots on disk
        should recover or fail loudly, not silently restart empty)."""
        raw = self._get_verified_read(_COMMIT_KEY)
        if raw is None:
            return None, False
        try:
            return int(raw.decode()), False
        except ValueError:
            # torn commit record: fall through to the history (the epoch
            # it pointed at was mid-commit anyway — not a safe cut)
            logger.warning(
                "checkpoint: committed_epoch record unreadable (%r) — "
                "consulting %s", raw[:32], _HISTORY_KEY,
            )
            return None, True

    def _get_verified_read(self, key: str) -> bytes | None:
        """Backend read with a bounded transient-error retry, used by
        every recovery-critical read (commit record, history, manifest
        probes, epoch verification): these are the paths whose failure
        either aborts recovery outright or durably discards an epoch
        (pointer rewrite + GC), so a momentary hiccup must not throw away
        an intact checkpoint — same courtesy commit() gives its writes."""
        last: StateError | None = None
        for attempt in range(_COMMIT_ATTEMPTS):
            try:
                return self.backend.get(key)
            except StateError as e:
                last = e
                if attempt < _COMMIT_ATTEMPTS - 1:
                    time.sleep(0.01 * (attempt + 1))  # dnzlint: allow(replay-impure) transient-error backoff — timing never feeds stored bytes
        raise last

    def _read_history(self, committed: int | None) -> list[int]:
        raw = self._get_verified_read(_HISTORY_KEY)
        history: list[int] = []
        if raw is not None:
            try:
                history = [int(e) for e in json.loads(raw.decode())]
            except (ValueError, TypeError):
                logger.warning("checkpoint: epoch history unreadable")
        if committed is not None and committed not in history:
            history.append(committed)
        return sorted(set(history))

    def _probe_manifest(self, epoch: int) -> bool:
        """Discovery-time manifest probe.  A persistently unreadable
        manifest demotes the epoch to the legacy (manifest-less) ordering
        instead of aborting discovery — _verify_epoch still does the
        authoritative (retried) read before the epoch is ever selected."""
        try:
            return self._get_verified_read(f"manifest@{epoch}") is not None
        except StateError:
            return False

    def _discover_epochs(self) -> list[int]:
        """Epochs present as key suffixes on disk, newest first — the
        last resort when the commit record is torn and no history key
        exists (pre-history checkpoints)."""
        epochs = {
            e for kb in self.backend.keys()
            if (e := epoch_of_key(kb)) is not None
        }
        return sorted(epochs, reverse=True)

    def _select_restore_epoch(
        self,
        committed: int | None,
        history: list[int],
        commit_corrupt: bool = False,
    ) -> int | None:
        """Verify candidate epochs newest-first; the first fully-intact
        one becomes the recovery point."""
        if committed is None and not history and not commit_corrupt:
            return None  # fresh store
        candidates = sorted(set(history), reverse=True)
        if committed is not None and committed not in candidates:
            candidates.insert(0, committed)
        if not candidates:
            # torn commit record on a history-less (legacy) store: the
            # snapshots themselves may be intact — discover their epochs
            # from the keys rather than silently restarting empty, and
            # fail LOUDLY (like the pre-history code did) if nothing
            # usable exists.  Ordering matters: an epoch WITH a manifest
            # is provably complete (the manifest is written only after
            # every operator snapshotted), so newest-manifested-first;
            # manifest-less epochs are legacy and completeness is
            # unknowable — the NEWEST one may be a half-written barrier
            # (a mixed cut), while under legacy GC-on-commit the OLDEST
            # epoch on disk is the committed one, so those try
            # oldest-first.
            discovered = self._discover_epochs()  # newest-first
            with_manifest = [
                e for e in discovered if self._probe_manifest(e)
            ]
            legacy = [e for e in discovered if e not in set(with_manifest)]
            candidates = with_manifest + list(reversed(legacy))
            if not candidates:
                raise StateError(
                    "committed_epoch record unreadable and no epoch "
                    "snapshots found — refusing to silently restore "
                    "empty state"
                )
        for epoch in candidates:
            ok, why = self._verify_epoch(epoch)
            if ok:
                if commit_corrupt or (
                    committed is not None and epoch != committed
                ):
                    self.restored_from_fallback = True
                    logger.warning(
                        "checkpoint: RESTORING FROM FALLBACK epoch %d — "
                        "committed epoch %s failed integrity "
                        "verification; windows since that cut will "
                        "re-emit (at-least-once sink contract)",
                        epoch,
                        committed if committed is not None
                        else "(record unreadable)",
                    )
                return epoch
            logger.warning(
                "checkpoint: epoch %d failed verification (%s)", epoch, why
            )
        raise StateError(
            f"no intact checkpoint epoch among {candidates}: every "
            "retained epoch has a corrupt, torn, or missing snapshot"
        )

    def _verify_epoch(self, epoch: int) -> tuple[bool, str | None]:
        """Verify EVERY snapshot of one epoch up front: completeness via
        the commit-time manifest (when present), integrity via the blob
        header.  Manifest-less epochs (legacy) verify whatever
        epoch-suffixed keys exist — headerless blobs pass vacuously."""
        try:
            raw = self._get_verified_read(f"manifest@{epoch}")
        except StateError as e:
            return False, f"manifest unreadable: {e}"
        if raw is not None:
            try:
                keys = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                return False, "manifest corrupt"
            if not keys:
                # same invariant as the manifest-less 'seen == 0' check
                # below: a committed epoch always has snapshots, and an
                # empty manifest would otherwise verify vacuously and
                # restore empty state while claiming success
                return False, "manifest lists no snapshots"
            for key in keys:
                try:
                    blob = self._get_verified_read(f"{key}@{epoch}")
                except StateError as e:
                    # a PERSISTENT read error (retries exhausted) fails
                    # the epoch and lets fallback try the next one — it
                    # must not abort recovery outright
                    return False, f"snapshot {key!r} unreadable: {e}"
                if blob is None:
                    return False, f"snapshot {key!r} missing"
                ok, _ = unframe_snapshot(blob)
                if not ok:
                    return False, f"snapshot {key!r} corrupt or torn"
            return True, None
        seen = 0
        try:
            all_keys = self.backend.keys()
        except StateError as e:
            return False, f"key scan failed: {e}"
        for kb in all_keys:
            if epoch_of_key(kb) != epoch or kb.startswith(b"manifest@"):
                continue
            try:
                blob = self._get_verified_read(kb)
            except StateError as e:
                return False, f"snapshot {kb!r} unreadable: {e}"
            ok, _ = unframe_snapshot(blob) if blob is not None else (False, None)
            if not ok:
                return False, f"snapshot {kb!r} corrupt or torn"
            seen += 1
        if seen == 0:
            # a committed epoch ALWAYS has snapshots (sources persist
            # offsets at minimum); manifest-less AND key-less means the
            # epoch's blobs are gone — selecting it would restore empty
            # state while claiming success
            return False, "no snapshots found for epoch"
        return True, None

    # -- write side ------------------------------------------------------
    def put_snapshot(self, key: str, epoch: int, blob: bytes) -> None:
        from denormalized_tpu import obs

        framed = frame_snapshot(blob)
        self._obs_snap_bytes.observe(len(framed))
        # per-state-key last-snapshot size: the aggregate histogram says
        # "restores got bigger", this gauge says WHICH operator's blob
        # grew (keys embed the node id, e.g. session_3_SessionWindowExec).
        # Bound lazily per key — binding is idempotent and runs at epoch
        # cadence, on the operator thread that owns the series.
        obs.gauge(
            "dnz_checkpoint_last_snapshot_bytes", key=key
        ).set(len(framed))
        self.backend.put(f"{key}@{epoch}", framed)
        self._epoch_keys.setdefault(epoch, []).append(key)

    def commit(self, epoch: int) -> None:
        """Marker drained at the root: make epoch E durable (manifest →
        fsync → commit record + history → fsync), then GC epochs beyond
        the retention window.  Transient backend errors retry — a commit
        is the one place a momentary hiccup must not kill the query."""
        manifest = json.dumps(
            sorted(set(self._epoch_keys.get(epoch, [])))
        ).encode()
        new_history = sorted(
            set(h for h in self.committed_history if h < epoch) | {epoch}
        )[-RETAINED_EPOCHS:]
        t0_commit = time.perf_counter()  # dnzlint: allow(replay-impure) commit-latency metric — observability only, not manifest bytes
        last_err = None
        for attempt in range(1, _COMMIT_ATTEMPTS + 1):
            try:
                faults.inject("checkpoint.commit")
                self.backend.put(f"manifest@{epoch}", manifest)
                self.backend.flush()
                self.backend.put(_COMMIT_KEY, str(epoch).encode())
                self.backend.put(
                    _HISTORY_KEY, json.dumps(new_history).encode()
                )
                self.backend.flush()
                last_err = None
                break
            except StateError as e:
                last_err = e
                self.commit_retries += 1
                self._obs_retries.add(1)
                logger.warning(
                    "checkpoint commit epoch %d: %s (attempt %d/%d)",
                    epoch, e, attempt, _COMMIT_ATTEMPTS,
                )
                if attempt < _COMMIT_ATTEMPTS:
                    time.sleep(0.01 * attempt)  # dnzlint: allow(replay-impure) commit-retry backoff — timing never feeds stored bytes
        if last_err is not None:
            raise last_err
        self._obs_commit_ms.observe((time.perf_counter() - t0_commit) * 1e3)  # dnzlint: allow(replay-impure) commit-latency metric — observability only
        self._obs_epoch.set(epoch)
        retained = set(new_history)
        self.committed_epoch = epoch
        self.committed_history = new_history
        # Only epochs BELOW the committing one are stale.  A later barrier
        # can already have snapshots on disk while E is still aligning
        # (join inputs are pumped by threads: one side's source may inject
        # barrier E+1 and persist its offsets before the other side's
        # Marker E drains) — those blobs are E+1's future checkpoint, and
        # deleting them here would leave commit(E+1) with a partial
        # manifest that verifies vacuously and restores without offsets.
        stale = {
            e
            for e in (set(self._epoch_keys) | self._known_epochs) - retained
            if e < epoch
        }
        try:
            for old in sorted(stale):
                keys = self._epoch_keys.pop(old, None)
                if keys is None:
                    # a prior incarnation's epoch: its key list lives in
                    # the manifest (always present post-manifest code; a
                    # legacy manifest-less epoch waits for the next
                    # startup sweep)
                    raw = self.backend.get(f"manifest@{old}")
                    if raw is None:
                        continue
                    try:
                        keys = json.loads(raw.decode())
                    except (ValueError, UnicodeDecodeError):
                        keys = []
                for key in keys:
                    self.backend.delete(f"{key}@{old}")
                self.backend.delete(f"manifest@{old}")
        except StateError as e:
            # the commit record is already durable at this point; GC is
            # best-effort cleanup and the next startup sweep collects any
            # leftovers — a hiccup here must not abort the query
            logger.warning(
                "checkpoint commit epoch %d: post-commit GC failed (%s) — "
                "leftover epochs will be swept at next startup", epoch, e,
            )
        self._known_epochs = retained | {epoch}

    def note_aborted(self, epoch: int) -> None:
        """The cluster coordinator aborted in-flight epoch ``epoch`` (a
        peer died before the barrier aligned everywhere; the number is
        never reused — epochs are VALUES here, not dense indexes, and
        commit/GC/history already tolerate gaps).  Eagerly drop any
        blobs this worker wrote for it — source offsets persisted at
        the barrier poll, early keyed snapshots — instead of letting
        them linger until the next commit's sweep.  Best-effort and
        race-tolerant: a put landing after the delete is collected by
        that later sweep; an epoch at or below the committed point is
        ignored (it is durable, not abortable)."""
        if self.committed_epoch is not None and epoch <= self.committed_epoch:
            return
        keys = self._epoch_keys.pop(epoch, []) or []
        self._known_epochs.discard(epoch)
        try:
            for key in keys:
                self.backend.delete(f"{key}@{epoch}")
            self.backend.delete(f"manifest@{epoch}")
        except StateError:
            # cleanup only — the startup sweep or the next commit's GC
            # collects leftovers; an abort must never fail the worker
            pass

    # -- read side -------------------------------------------------------
    def get_snapshot(self, key: str) -> bytes | None:
        if self.committed_epoch is None:
            return None
        # retried like every other recovery-critical read: one transient
        # hiccup must not abort a restore of a verified-intact epoch
        raw = self._get_verified_read(f"{key}@{self.committed_epoch}")
        if raw is None:
            return None
        ok, payload = unframe_snapshot(raw)
        if not ok:
            # construction verified this epoch; reaching here means the
            # store changed underneath us — surface, never feed an
            # operator half a snapshot
            raise StateError(
                f"snapshot {key!r}@{self.committed_epoch} failed "
                "integrity verification"
            )
        return payload


def wire_checkpointing(
    root: ExecOperator, ctx, orch: Orchestrator
) -> CheckpointCoordinator:
    path = ctx.config.state_backend_path
    if not path:
        raise StateError(
            "checkpoint=True requires state_backend_path "
            "(Context.with_state_backend)"
        )
    backend = initialize_global_state_backend(path)
    coord = CheckpointCoordinator(backend)
    ids = assign_node_ids(root)
    for op in walk(root):
        node_id = ids[id(op)]
        hook = getattr(op, "enable_checkpointing", None)
        if hook is not None:
            hook(node_id, coord, orch)
    return coord


def make_barrier_poll(channel):
    """Source-side poll: returns an epoch when a barrier is pending."""

    def poll():
        msg = channel.poll()
        if isinstance(msg, CheckpointBarrier):
            return msg.epoch
        return None

    return poll


def jsonable(v):
    """Recursively convert numpy scalars/arrays for json.dumps."""
    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [jsonable(x) for x in v.tolist()]
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): jsonable(x) for k, x in v.items()}
    return v


def put_json(coord: CheckpointCoordinator, key: str, epoch: int, obj) -> None:
    coord.put_snapshot(key, epoch, json.dumps(jsonable(obj)).encode())


def get_json(coord: CheckpointCoordinator, key: str):
    raw = coord.get_snapshot(key)
    return None if raw is None else json.loads(raw.decode())
