"""Checkpoint wiring: connect the orchestrator, the state backend, and the
physical plan.

Mirrors the reference's checkpoint topology (SURVEY.md §3.4): sources persist
their offsets when a barrier passes (kafka_stream_read.rs:275-289) and window
streams persist watermark + frames (grouped_window_agg_stream.rs:355-418),
all keyed by ``{node_id}_{partition}`` tags in the state backend; on startup
operators probe the backend by tag and restore
(kafka_stream_read.rs:110-140, grouped_window_agg_stream.rs:160-211).  The
fork's ``node_id`` plumbing (``with_node_id``) becomes a deterministic DFS
numbering of the physical plan here — stable across runs because the plan is
rebuilt deterministically from the same query.

Atomicity — an improvement over the reference's fire-and-forget puts
(slatedb.rs:60-66): snapshots for barrier epoch ``E`` are written under
epoch-suffixed keys ``{key}@{E}`` as the in-band marker passes each
operator; when the marker drains at the plan root, the executor calls
:meth:`CheckpointCoordinator.commit`, which fsyncs the store and only then
writes the ``committed_epoch`` record (also fsynced).  Restore reads the
committed epoch and loads exactly that epoch's snapshots — a half-written
barrier (crash between operator snapshots) is invisible, so recovery never
mixes epochs.  Older epochs are garbage-collected after commit.

Consistency: barriers flow in-band (see orchestrator.py), so on single-input
chains the snapshot is an aligned cut and recovery is exactly-once w.r.t.
engine state; emission to sinks remains at-least-once (windows that closed
after the last barrier re-emit on recovery), matching the reference.  Join
operators checkpoint too (both sides' retained build rows + matched flags +
watermarks, physical/join_exec.py enable_checkpointing) — BEYOND the
reference, which checkpoints only sources and window state; at a join the
early side's post-marker items are buffered until the other side's marker
arrives, so the two-input cut is aligned as well.
"""

from __future__ import annotations

import json

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.physical.base import ExecOperator
from denormalized_tpu.state.lsm import initialize_global_state_backend
from denormalized_tpu.state.orchestrator import CheckpointBarrier, Orchestrator

_COMMIT_KEY = "committed_epoch"


def walk(op: ExecOperator):
    yield op
    for c in op.children:
        yield from walk(c)


def assign_node_ids(root: ExecOperator) -> dict[int, str]:
    """Deterministic DFS-preorder node ids (the fork's node_id analog)."""
    ids: dict[int, str] = {}
    for i, op in enumerate(walk(root)):
        ids[id(op)] = f"{i}_{type(op).__name__}"
    return ids


class CheckpointCoordinator:
    """Epoch-aware snapshot IO shared by all operators of one query."""

    def __init__(self, backend):
        self.backend = backend
        raw = backend.get(_COMMIT_KEY)
        self.committed_epoch: int | None = (
            int(raw.decode()) if raw is not None else None
        )
        #: the epoch this run RECOVERED from, frozen at construction —
        #: committed_epoch moves with every new commit, but transactional
        #: sinks need the recovery point itself: output the previous
        #: incarnation wrote with an in-flight epoch beyond this value is
        #: exactly the uncommitted suffix a restore regenerates, and a
        #: recovery reader must discard it (truncate-on-restore)
        self.restored_epoch: int | None = self.committed_epoch
        self._epoch_keys: dict[int, list[str]] = {}

    # -- write side ------------------------------------------------------
    def put_snapshot(self, key: str, epoch: int, blob: bytes) -> None:
        self.backend.put(f"{key}@{epoch}", blob)
        self._epoch_keys.setdefault(epoch, []).append(key)

    def commit(self, epoch: int) -> None:
        """Marker drained at the root: make epoch E durable, then GC."""
        self.backend.flush()
        self.backend.put(_COMMIT_KEY, str(epoch).encode())
        self.backend.flush()
        prev = self.committed_epoch
        self.committed_epoch = epoch
        if prev is not None and prev != epoch:
            for key in self._epoch_keys.pop(prev, []):
                self.backend.delete(f"{key}@{prev}")

    # -- read side -------------------------------------------------------
    def get_snapshot(self, key: str) -> bytes | None:
        if self.committed_epoch is None:
            return None
        return self.backend.get(f"{key}@{self.committed_epoch}")


def wire_checkpointing(
    root: ExecOperator, ctx, orch: Orchestrator
) -> CheckpointCoordinator:
    path = ctx.config.state_backend_path
    if not path:
        raise StateError(
            "checkpoint=True requires state_backend_path "
            "(Context.with_state_backend)"
        )
    backend = initialize_global_state_backend(path)
    coord = CheckpointCoordinator(backend)
    ids = assign_node_ids(root)
    for op in walk(root):
        node_id = ids[id(op)]
        hook = getattr(op, "enable_checkpointing", None)
        if hook is not None:
            hook(node_id, coord, orch)
    return coord


def make_barrier_poll(channel):
    """Source-side poll: returns an epoch when a barrier is pending."""

    def poll():
        msg = channel.poll()
        if isinstance(msg, CheckpointBarrier):
            return msg.epoch
        return None

    return poll


def jsonable(v):
    """Recursively convert numpy scalars/arrays for json.dumps."""
    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [jsonable(x) for x in v.tolist()]
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): jsonable(x) for k, x in v.items()}
    return v


def put_json(coord: CheckpointCoordinator, key: str, epoch: int, obj) -> None:
    coord.put_snapshot(key, epoch, json.dumps(jsonable(obj)).encode())


def get_json(coord: CheckpointCoordinator, key: str):
    raw = coord.get_snapshot(key)
    return None if raw is None else json.loads(raw.decode())
