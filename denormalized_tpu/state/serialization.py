"""Checkpoint payload format.

Counterpart of the reference's ``ArrayContainer`` bincode scheme
(crates/core/src/utils/serialization.rs:130-235: recursive ArrayData ⇄
buffers) and its ScalarValue-JSON serde (accumulators/serialize.rs): one
self-describing binary blob per checkpoint key holding a JSON metadata
header plus raw little-endian array buffers.  No pickle — payloads are
loadable across processes and safe to read from untrusted stores.

Layout:  [u32 header_len][header JSON utf-8][buf 0][buf 1]...
Header: {"meta": <json>, "arrays": [{"name","dtype","shape","nbytes"},...]}
"""

from __future__ import annotations

import json
import struct

import numpy as np

from denormalized_tpu.common.errors import StateError

_MAGIC = b"DTCK"  # denormalized-tpu checkpoint
_VERSION = 1


def pack_snapshot(meta: dict, arrays: dict[str, np.ndarray]) -> bytes:
    entries = []
    bufs = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype == object:
            raise StateError(f"array {name!r} has object dtype; not packable")
        raw = arr.tobytes()
        entries.append(
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "nbytes": len(raw),
            }
        )
        bufs.append(raw)
    header = json.dumps({"v": _VERSION, "meta": meta, "arrays": entries}).encode()
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<I", len(header))
    out += header
    for b in bufs:
        out += b
    return bytes(out)


def unpack_snapshot(blob: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    if blob[:4] != _MAGIC:
        raise StateError("bad checkpoint magic")
    (hlen,) = struct.unpack_from("<I", blob, 4)
    header = json.loads(blob[8 : 8 + hlen].decode())
    if header.get("v") != _VERSION:
        raise StateError(f"unsupported checkpoint version {header.get('v')}")
    arrays = {}
    off = 8 + hlen
    for e in header["arrays"]:
        n = e["nbytes"]
        arr = np.frombuffer(blob[off : off + n], dtype=np.dtype(e["dtype"]))
        arrays[e["name"]] = arr.reshape(e["shape"]).copy()
        off += n
    return header["meta"], arrays
