"""Global tag-addressed channel registry.

Mirror of the reference's ``channel_manager``
(crates/orchestrator/src/channel_manager/mod.rs:19-51): a process-global map
of unbounded channels addressed by string tag (``"orchestrator"``,
``"{node_id}_{partition}"``), with ``create_channel`` / ``get_sender`` /
take-once ``take_receiver`` semantics.  Queues stand in for crossbeam
channels; the orchestrator broadcasts barriers through it and sources poll
their tagged channel between batches.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

_LOCK = threading.RLock()
_CHANNELS: dict[str, "Channel"] = {}


class Channel:
    def __init__(self, tag: str):
        self.tag = tag
        self._q: queue.Queue = queue.Queue()
        self._receiver_taken = False

    def send(self, item) -> None:
        self._q.put(item)

    def poll(self):
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None


def create_channel(tag: str) -> Channel:
    with _LOCK:
        ch = _CHANNELS.get(tag)
        if ch is None:
            ch = Channel(tag)
            _CHANNELS[tag] = ch
        return ch


def get_sender(tag: str) -> Optional[Channel]:
    with _LOCK:
        return _CHANNELS.get(tag)


def take_receiver(tag: str) -> Optional[Channel]:
    """Take-once receiver semantics (mod.rs:40-47)."""
    with _LOCK:
        ch = _CHANNELS.get(tag)
        if ch is None or ch._receiver_taken:
            return None
        ch._receiver_taken = True
        return ch


def remove_channel(tag: str) -> None:
    with _LOCK:
        _CHANNELS.pop(tag, None)


def all_tags() -> list[str]:
    with _LOCK:
        return list(_CHANNELS)
