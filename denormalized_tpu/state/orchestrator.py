"""Checkpoint barrier orchestrator.

Mirror of the reference's ``Orchestrator`` (crates/orchestrator/src/
orchestrator.rs:30-80): a background worker that accepts stream
registrations and broadcasts ``CheckpointBarrier(epoch_millis)`` to every
registered channel on a fixed cadence (10s in the reference, :58).

Difference by design: the reference delivers barriers out-of-band to EVERY
operator, giving only approximate consistency (SURVEY.md §3.4).  Here only
SOURCES register; the barrier enters the dataflow as an in-band
:class:`~denormalized_tpu.physical.base.Marker` right after the batch the
source is currently emitting, and every downstream operator snapshots when
the marker reaches it — an aligned (Chandy-Lamport-consistent) cut on
single-input chains.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from denormalized_tpu.state import channel_manager as cm

ORCHESTRATOR_TAG = "orchestrator"


@dataclass(frozen=True)
class RegisterStream:
    tag: str


@dataclass(frozen=True)
class CheckpointBarrier:
    epoch: int


class Orchestrator:
    _seq = 0

    def __init__(self, interval_s: float = 10.0):
        self.interval_s = interval_s
        self._registered: set[str] = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # per-instance control tag: concurrent queries in one process must
        # not steal each other's RegisterStream messages
        Orchestrator._seq += 1
        self._control_tag = f"{ORCHESTRATOR_TAG}_{Orchestrator._seq}"
        self._control = cm.create_channel(self._control_tag)
        self.epochs_sent = 0
        self._last_epoch = 0
        self._epoch_lock = threading.Lock()

    def _next_epoch(self) -> int:
        """Strictly increasing epoch: wall-clock millis, bumped past the
        previous value when two barriers land in the same millisecond (or
        the clock steps back) — identical epochs would collide checkpoint
        keys ``{key}@{epoch}`` across distinct cuts and double-count in the
        join's per-epoch marker alignment.  Locked: trigger_now runs on the
        caller's thread concurrently with the cadence thread."""
        with self._epoch_lock:
            e = max(self._last_epoch + 1, int(time.time() * 1000))
            self._last_epoch = e
            return e

    def register(self, tag: str) -> cm.Channel:
        """Register a stream; returns its barrier channel (sources poll it)."""
        ch = cm.create_channel(tag)
        self._control.send(RegisterStream(tag))
        return ch

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        last = time.monotonic()
        while not self._stop.is_set():
            # drain control messages (RegisterStream)
            while True:
                msg = self._control.poll()
                if msg is None:
                    break
                if isinstance(msg, RegisterStream):
                    self._registered.add(msg.tag)
            if time.monotonic() - last >= self.interval_s:
                last = time.monotonic()
                epoch = self._next_epoch()
                for tag in list(self._registered):
                    ch = cm.get_sender(tag)
                    if ch is not None:
                        ch.send(CheckpointBarrier(epoch))
                self.epochs_sent += 1
            self._stop.wait(min(0.05, self.interval_s / 4))

    def trigger_now(self) -> int:
        """Force an immediate barrier (tests / graceful shutdown)."""
        while True:
            msg = self._control.poll()
            if msg is None:
                break
            if isinstance(msg, RegisterStream):
                self._registered.add(msg.tag)
        epoch = self._next_epoch()
        for tag in list(self._registered):
            ch = cm.get_sender(tag)
            if ch is not None:
                ch.send(CheckpointBarrier(epoch))
        self.epochs_sent += 1
        return epoch

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # drop this query's channels so a later run reusing the same node-id
        # tags doesn't receive stale barriers
        cm.remove_channel(self._control_tag)
        for tag in self._registered:
            cm.remove_channel(tag)
        self._registered.clear()
