"""Tiered state: budgeted cold-state spill to the LSM.

ROADMAP item 3's reaction half.  PR 8 shipped the *detection* layer —
exact ``state_info()`` accounting, growth forecasts, and the
``state-budget-pressure`` verdict; this module ships the *reaction*: when
a query's accounted live state crosses ``EngineConfig(state_budget_bytes)``,
a per-query :class:`SpillController` evicts the COLDEST blocks of keyed
state (coldest-by-last-touch, vectorized block granularity, never the
keys the current batch is touching) out of RAM into the existing
:class:`~denormalized_tpu.state.lsm.LsmStore` under a namespaced key
space, and transparently reloads them — batch-granular — when a later
batch, a watermark close, or a checkpoint touches them.  The placement
policy is StreamBox-HBM's hot/cold tiering (hot = recently touched keys
stay in the fast tier); the spill/reload mechanics follow the
window-frame spilling design of "Support Aggregate Analytic Window
Function over Large Data by Spilling" (PAPERS.md).

Layering:

- **This module** owns the generic machinery: budget arithmetic over the
  same ``state_info()`` accounting that feeds the PR-8 forecast ring, the
  namespaced block store (``spill/{node_id}/{block_id}`` keys — no ``@``
  suffix, so checkpoint epoch GC can never collect them), per-node spill
  manifests, the cold-rank helper (:class:`ColdTracker`), RecordBatch
  blob packing, spill/reload latency + volume metrics, the
  spill-thrashing stats the doctor's verdict reads, and the end-of-line
  backpressure gate the prefetch pump polls.
- **The operators** own the state layouts, so each implements its own
  adapter (``enable_spill(node_id, controller)`` hook): the session
  operator spills cold gid blocks out of its SoA slot table, the join
  spills cold retained batches per side, the UDAF operator spills cold
  groups' accumulator states (dict order preserved via in-place
  markers), and the window operator spills cold watermark-deferred ring
  slots.  Every adapter keeps a MEMBERSHIP mask resident so the hot path
  pays one ``any_spilled`` attribute check when nothing is spilled.

Checkpoint consistency: spilled blocks are referenced from the owning
operator's snapshot meta and their payloads are copied under the SAME
epoch via :meth:`SpillController.copy_block_to_epoch` — CRC-framed by
``put_snapshot`` like every other blob, listed in the epoch manifest, so
verification/fallback/GC cover the cold tier too.  Restore rebuilds the
tier map by streaming each block back into the spill namespace (one
block resident at a time — a restore never materializes the whole cold
tier).

Degradation ladder: over budget → spill cold blocks down to
``SPILL_LOW_RATIO`` of the budget; nothing cold left to evict and still
over the hard ceiling → engage END-OF-LINE BACKPRESSURE on the prefetch
pump (sources pause reads, broker-side backlog absorbs the burst) rather
than grow without bound.  The gate releases as soon as accounted state
drops back under budget.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

import numpy as np

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.runtime import faults
from denormalized_tpu.runtime.tracing import logger
from denormalized_tpu.state.serialization import pack_snapshot, unpack_snapshot

#: key namespace for spilled blocks.  Deliberately ``@``-free: checkpoint
#: epoch GC (state/checkpoint.py epoch_of_key) parses ``{key}@{epoch}``
#: suffixes, so spill keys are invisible to it by construction.
SPILL_PREFIX = "spill/"

#: gid-granular adapters (session/udaf) group cold keys into blocks of at
#: most this many slots/groups — one LSM value per block, vectorized
#: gather/scatter at spill and reload
SPILL_BLOCK_SLOTS = 8192

#: spill target: evict down to this fraction of the budget, so one spill
#: pass buys headroom instead of re-triggering on the next batch
SPILL_LOW_RATIO = 0.8

#: hard ceiling multiplier: accounted state above budget x this with no
#: cold state left to evict escalates to prefetch backpressure
HARD_CEILING_RATIO = 1.25

#: rolling window for the spill-thrashing stats the doctor verdict reads
THRASH_WINDOW_S = 60.0

#: bounded transient-StateError retries on reload reads (same courtesy
#: checkpoint recovery reads get — a reloaded block is the only copy)
_RELOAD_ATTEMPTS = 3


# -- end-of-line backpressure gate ----------------------------------------
# Module-level so the prefetch workers can poll it with one global read;
# engaged/released by controllers under a lock, keyed by (controller,
# node) so two queries' gates never mask each other's release.
#
# SCOPE: the gate itself is process-wide — while ANY budgeted query is
# over its hard ceiling, every prefetch worker in the process throttles.
# That matches the tier's one-budgeted-query-per-backend scope (see
# docs/state_spill.md) and errs toward shedding load when the process is
# genuinely memory-pressured; per-query gate plumbing (workers knowing
# their query's controller) is the follow-up if multi-budget processes
# become real.

_GATE_LOCK = threading.Lock()
_GATE_HOLDERS: set[tuple[int, str]] = set()
_GATE_ENGAGED = False  # lock-free fast-path mirror of bool(_GATE_HOLDERS)


def pressure_engaged() -> bool:
    """Lock-free fast path for the prefetch read loop: one global load
    when no controller has ever escalated."""
    return _GATE_ENGAGED


def backpressure_pause(slice_s: float = 0.05) -> bool:
    """One bounded pause slice for a producer loop under state pressure.
    Returns True when it actually paused — callers keep their own loop
    (checking shutdown flags between slices) instead of blocking here."""
    if not _GATE_ENGAGED:
        return False
    time.sleep(slice_s)
    return True


def _gate_set(holder: tuple[int, str], engaged: bool) -> bool:
    """Add/remove one holder; returns True when this call flipped the
    global gate state (edge, not level — callers count escalations)."""
    global _GATE_ENGAGED
    with _GATE_LOCK:
        before = bool(_GATE_HOLDERS)
        if engaged:
            _GATE_HOLDERS.add(holder)
        else:
            _GATE_HOLDERS.discard(holder)
        _GATE_ENGAGED = bool(_GATE_HOLDERS)
        return before != _GATE_ENGAGED and engaged


# -- cold tracking ---------------------------------------------------------


class ColdTracker:
    """Vectorized per-id last-touch clock.

    One int64 cell per dense id; ``touch`` stamps a batch's ids with a
    monotonically increasing batch clock (one scatter, no per-row
    Python).  Cold candidates are ranked by ``last_touch`` ascending —
    ids never touched rank coldest (stamp 0)."""

    __slots__ = ("clock", "last_touch")

    def __init__(self, capacity: int = 1024) -> None:
        self.clock = 0
        self.last_touch = np.zeros(max(int(capacity), 16), dtype=np.int64)

    def ensure(self, n: int) -> None:
        cap = len(self.last_touch)
        if n <= cap:
            return
        while cap < n:
            cap *= 2
        new = np.zeros(cap, dtype=np.int64)
        new[: len(self.last_touch)] = self.last_touch
        self.last_touch = new

    def touch(self, ids: np.ndarray) -> None:
        self.clock += 1
        self.last_touch[ids] = self.clock

    def order_cold(self, candidates: np.ndarray) -> np.ndarray:
        """``candidates`` sorted coldest-first (stable, so equal stamps
        keep a deterministic id order)."""
        if len(candidates) == 0:
            return candidates
        return candidates[
            np.argsort(self.last_touch[candidates], kind="stable")
        ]


# -- RecordBatch <-> blob --------------------------------------------------


def rb_to_blob(batch: RecordBatch, extra_meta: dict | None = None) -> bytes:
    """Pack one RecordBatch into a self-describing blob.  Columnar
    string/nested columns pack their RAW buffers (offsets+bytes — the
    same codec the exchange frames use, so cold state shrinks and never
    round-trips through Python values); plain object columns keep the
    legacy JSON-meta ``strings`` lane."""
    from denormalized_tpu.common.columns import Column, column_to_arrays

    meta: dict = {"strings": {}, "masked": [], "rows": batch.num_rows}
    if extra_meta:
        meta["extra"] = extra_meta
    arrays: dict[str, np.ndarray] = {}
    colspecs: dict[str, dict] = {}
    for f in batch.schema:
        col = batch.column(f.name)
        if isinstance(col, Column):
            colspecs[f.name] = column_to_arrays(
                col, f"cc_{f.name}_", arrays
            )
        elif np.asarray(col).dtype == object:
            meta["strings"][f.name] = [
                None if v is None else str(v) for v in np.asarray(col)
            ]
        else:
            arrays[f"col_{f.name}"] = np.asarray(col)
        m = batch.mask(f.name)
        # a columnar column's validity already rides its own buffers —
        # don't store the identical batch mask twice
        if m is not None and m is not getattr(col, "validity", None):
            meta["masked"].append(f.name)
            arrays[f"mask_{f.name}"] = np.asarray(m, dtype=bool)
    if colspecs:
        meta["columnar"] = colspecs
    return pack_snapshot(meta, arrays)


def rb_from_blob(blob: bytes, schema) -> tuple[RecordBatch, dict | None]:
    """Inverse of :func:`rb_to_blob` (schema supplied by the owner —
    spilled blocks never carry schemas).  Legacy blobs (no ``columnar``
    meta) load unchanged."""
    from denormalized_tpu.common.columns import column_from_arrays

    meta, arrays = unpack_snapshot(blob)
    colspecs = meta.get("columnar", {})
    cols, masks = [], []
    for f in schema:
        if f.name in colspecs:
            cols.append(
                column_from_arrays(
                    colspecs[f.name], f"cc_{f.name}_", arrays
                )
            )
        elif f.name in meta["strings"]:
            vals = meta["strings"][f.name]
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
            cols.append(arr)
        else:
            cols.append(arrays[f"col_{f.name}"])
        if f.name in meta["masked"]:
            masks.append(arrays.get(f"mask_{f.name}"))
        else:
            # columnar columns surface their own validity as the mask
            # (the pack side elided the redundant copy)
            masks.append(getattr(cols[-1], "validity", None))
    return RecordBatch(schema, cols, masks), meta.get("extra")


def key_columns_from_meta(cols: list[list]) -> list[np.ndarray]:
    """Rebuild interner-ready key columns from JSON-round-tripped value
    lists (same dtype sniff as the session checkpoint restore: numeric/
    bool/datetime kinds re-enter the exact-value path, everything else —
    strings, mixed objects — stays an object array built element-wise so
    ``np.asarray`` cannot stringify it)."""
    out = []
    for lst in cols:
        arr = np.asarray(lst)
        if arr.dtype.kind not in "ifbM":
            arr = np.empty(len(lst), dtype=object)
            arr[:] = lst
        out.append(arr)
    return out


# -- per-node stats (the doctor's spill-thrashing signal) ------------------


class _NodeStats:
    """One node's spill/reload accounting + rolling thrash window.

    Lock-guarded: ``note`` runs on the owning operator's thread, but
    ``snapshot``/``recent`` are read by the doctor's /state endpoint,
    the statedoc verdict pass, and soak sampler threads — iterating the
    deque while the operator appends would raise (PR-8's state reads
    are documented cross-thread-safe, so this field must be too)."""

    __slots__ = (
        "spill_blocks", "reload_blocks", "spill_bytes", "reload_bytes",
        "events", "backpressure", "_lock",
    )

    def __init__(self) -> None:
        self.spill_blocks = 0
        self.reload_blocks = 0
        self.spill_bytes = 0
        self.reload_bytes = 0
        self.backpressure = 0
        # (wall, kind) ring for the rolling thrash ratio
        self.events: deque = deque(maxlen=4096)
        self._lock = threading.Lock()

    def note(self, kind: str, blocks: int, nbytes: int) -> None:
        now = time.time()
        with self._lock:
            if kind == "spill":
                self.spill_blocks += blocks
                self.spill_bytes += nbytes
            else:
                self.reload_blocks += blocks
                self.reload_bytes += nbytes
            self.events.append((now, kind, blocks))

    def _recent_locked(self) -> tuple[int, int]:
        cutoff = time.time() - THRASH_WINDOW_S
        s = r = 0
        for t, kind, blocks in self.events:
            if t < cutoff:
                continue
            if kind == "spill":
                s += blocks
            else:
                r += blocks
        return s, r

    def recent(self) -> tuple[int, int]:
        """(spills, reloads) inside the rolling window."""
        with self._lock:
            return self._recent_locked()

    def snapshot(self) -> dict:
        with self._lock:
            s, r = self._recent_locked()
            return self._snapshot_locked(s, r)

    def _snapshot_locked(self, s: int, r: int) -> dict:
        return {
            "spill_blocks_total": self.spill_blocks,
            "reload_blocks_total": self.reload_blocks,
            "spill_bytes_total": self.spill_bytes,
            "reload_bytes_total": self.reload_bytes,
            "recent_spill_blocks": s,
            "recent_reload_blocks": r,
            "backpressure_engagements": self.backpressure,
        }


# -- the controller --------------------------------------------------------


class SpillController:
    """Per-query spill coordinator shared by every tier adapter.

    Owns the budget arithmetic (driven by the SAME ``state_info()``
    accounting that feeds the PR-8 gauge/forecast ring, via each
    operator's ``_cached_state_info``), the namespaced block store on the
    LSM backend, per-node manifests, metrics, and the backpressure
    escalation.  Operators register at wire time and call
    :meth:`maybe_spill` from their own thread after each batch — all
    state mutation stays single-writer on the operator thread; the
    controller itself only guards the cross-thread gate bookkeeping."""

    def __init__(self, backend, budget_bytes: int) -> None:
        from denormalized_tpu import obs

        self.backend = backend
        self.budget = int(budget_bytes)
        self._ops: dict[str, object] = {}  # node_id -> weakref(operator)
        self._resident_fns: dict[str, object] = {}
        self._stats: dict[str, _NodeStats] = {}
        self._closed = False
        self._obs_spill_ms = obs.histogram("dnz_spill_op_ms", op="spill")
        self._obs_reload_ms = obs.histogram("dnz_spill_op_ms", op="reload")
        self._obs_spill_blocks = obs.counter(
            "dnz_spill_blocks_total", op="spill"
        )
        self._obs_reload_blocks = obs.counter(
            "dnz_spill_blocks_total", op="reload"
        )
        self._obs_backpressure = obs.counter(
            "dnz_spill_backpressure_total"
        )

    # -- registration ----------------------------------------------------
    def register(self, node_id: str, op, resident_fn=None) -> None:
        """``resident_fn`` is the adapter's CHEAP (O(1)-ish) resident-
        bytes estimate — the budget check runs once per batch, so it must
        not walk live state the way the exact ``state_info()`` accounting
        (which feeds the gauges and the forecast ring) is allowed to.
        Falls back to the cached exact accounting when absent."""
        import weakref

        self._ops[node_id] = weakref.ref(op)
        self._resident_fns[node_id] = resident_fn
        self._stats[node_id] = _NodeStats()

    def sweep_namespace(self) -> None:
        """Delete every leftover ``spill/`` key (a previous incarnation's
        cold tier — checkpoint restore re-copies the committed epoch's
        blocks, anything else is unreachable garbage)."""
        try:
            for kb in list(self.backend.keys()):
                if kb.startswith(SPILL_PREFIX.encode()):
                    self.backend.delete(kb)
        except StateError as e:
            logger.warning("spill: startup namespace sweep failed: %s", e)

    # -- block I/O -------------------------------------------------------
    @staticmethod
    def block_key(node_id: str, block_id: str) -> str:
        return f"{SPILL_PREFIX}{node_id}/{block_id}"

    def put_block(self, node_id: str, block_id: str, payload: bytes) -> int:
        """Store one cold block; returns the stored byte count.  A torn
        fault here truncates the payload exactly like ``lsm.put`` — the
        reload path detects it via the pack magic/shape and fails loudly
        instead of resurrecting half a block."""
        key = self.block_key(node_id, block_id)
        payload = faults.inject("lsm.spill_put", key=key, payload=payload)
        t0 = time.perf_counter() if self._obs_spill_ms else 0.0
        self.backend.put(key, payload)
        if self._obs_spill_ms:
            self._obs_spill_ms.observe((time.perf_counter() - t0) * 1e3)
        self._obs_spill_blocks.add(1)
        return len(payload)

    def _read_block_raw(self, key: str) -> bytes:
        """Retried block read shared by reload and the epoch-copy path
        (no metrics — callers attribute the read themselves)."""
        last: StateError | None = None
        raw = None
        for attempt in range(_RELOAD_ATTEMPTS):
            try:
                # the fault site sits INSIDE the retry: an injected (or
                # real) transient read error heals exactly like a
                # backend hiccup would
                faults.inject("lsm.spill_get", key=key)
                raw = self.backend.get(key)
                last = None
                break
            except StateError as e:
                last = e
                if attempt < _RELOAD_ATTEMPTS - 1:
                    time.sleep(0.01 * (attempt + 1))  # dnzlint: allow(replay-impure) reload-retry backoff — timing never feeds block bytes
        if last is not None:
            raise last
        if raw is None:
            raise StateError(
                f"spilled state block {key!r} missing from the backend — "
                "cold tier lost state that was evicted from RAM"
            )
        return raw

    def get_block(self, node_id: str, block_id: str) -> bytes:
        """Load one spilled block (bounded transient retry — the block is
        the ONLY copy of that state; a missing/torn blob is fatal)."""
        key = self.block_key(node_id, block_id)
        t0 = time.perf_counter() if self._obs_reload_ms else 0.0
        raw = self._read_block_raw(key)
        if self._obs_reload_ms:
            self._obs_reload_ms.observe((time.perf_counter() - t0) * 1e3)
        self._obs_reload_blocks.add(1)
        return raw

    def delete_block(self, node_id: str, block_id: str) -> None:
        try:
            self.backend.delete(self.block_key(node_id, block_id))
        except StateError as e:
            # unreachable garbage at worst — the next run's namespace
            # sweep collects it; a delete hiccup must not fail a reload
            logger.warning(
                "spill: delete of reloaded block %s/%s failed: %s",
                node_id, block_id, e,
            )

    def write_manifest(self, node_id: str, block_ids: list[str]) -> None:
        """Persist one node's live-block list (debuggability + the
        sweep's ground truth; NOT the recovery source — checkpoints
        reference blocks from the epoch manifest).  Best-effort: a
        manifest write failure degrades observability, never the data
        path."""
        key = f"{SPILL_PREFIX}{node_id}/manifest"
        payload = json.dumps(sorted(block_ids)).encode()
        try:
            payload = faults.inject("spill.manifest", key=key, payload=payload)
            self.backend.put(key, payload)
        except StateError as e:
            logger.warning(
                "spill: manifest write for %s failed: %s", node_id, e
            )

    # -- checkpoint integration ------------------------------------------
    def copy_block_to_epoch(
        self, coord, state_key: str, epoch: int, node_id: str, block_id: str
    ) -> None:
        """Reference one spilled block from checkpoint epoch ``epoch``:
        the payload is re-put through ``put_snapshot`` (CRC-framed,
        listed in the epoch manifest) under a block-scoped state key —
        spilled + resident state commit under ONE epoch.

        The payload is integrity-checked FIRST: a block torn on its way
        into the LSM would otherwise be framed with a valid CRC over the
        torn bytes and commit a poisoned epoch that verifies clean —
        failing the snapshot here keeps the previous intact epoch the
        recovery point instead.

        Reads through the raw path: an epoch copy is NOT a reload, and
        counting it as one would make every checkpoint inflate the
        dnz_spill_blocks_total{op=reload} series the thrashing
        dashboards watch."""
        raw = self._read_block_raw(self.block_key(node_id, block_id))
        try:
            unpack_snapshot(raw)
        except Exception as e:  # dnzlint: allow(broad-except) any unpack failure (bad magic, short buffer, json) means the stored block is corrupt — the narrow cause doesn't matter, the epoch must not commit it
            raise StateError(
                f"spilled block {block_id!r} of {node_id!r} failed "
                f"integrity verification before epoch commit: {e}"
            ) from e
        coord.put_snapshot(f"{state_key}:spill:{block_id}", epoch, raw)

    def restore_block_from_epoch(
        self, coord, state_key: str, node_id: str, block_id: str
    ) -> bytes:
        """Read one block's payload back out of the committed epoch and
        re-seed the run-time spill namespace with it (the tier map
        rebuild path — one block resident at a time)."""
        raw = coord.get_snapshot(f"{state_key}:spill:{block_id}")
        if raw is None:
            raise StateError(
                f"checkpoint references spilled block {block_id!r} of "
                f"{state_key!r} but the epoch holds no such snapshot"
            )
        self.backend.put(self.block_key(node_id, block_id), raw)
        return raw

    # -- budget arithmetic ------------------------------------------------
    def total_state_bytes(self) -> int:
        """Current resident bytes across every registered operator, from
        the adapters' cheap estimators (exact accounting is pull-only and
        too heavy to run per batch at 10M+ live keys).

        Estimators may belong to operators running on OTHER threads
        (join pumps) — they read defensively, and a torn read here
        degrades to an underestimate for one check rather than killing
        the calling operator's batch (the next check re-reads)."""
        total = 0
        for node_id, ref in self._ops.items():
            op = ref()
            if op is None:
                continue
            fn = self._resident_fns.get(node_id)
            if fn is not None:
                try:
                    total += int(fn())
                except Exception:  # dnzlint: allow(broad-except) a cross-thread estimator racing its owner's mutation (list resize, dict growth) tears benignly — one stale budget check is recoverable, killing the caller's batch is not
                    pass
                continue
            info = op._cached_state_info(max_age_s=0.25)
            if info:
                total += int(info.get("state_bytes") or 0)
        return total

    def over_budget(self) -> int:
        """Bytes to shed to reach the spill target (0 = under budget)."""
        total = self.total_state_bytes()
        if total <= self.budget:
            return 0
        return total - int(self.budget * SPILL_LOW_RATIO)

    def note_spill(self, node_id: str, blocks: int, nbytes: int) -> None:
        self._stats[node_id].note("spill", blocks, nbytes)

    def note_reload(self, node_id: str, blocks: int, nbytes: int) -> None:
        self._stats[node_id].note("reload", blocks, nbytes)

    def spill_stats(self, node_id: str) -> dict | None:
        st = self._stats.get(node_id)
        return st.snapshot() if st is not None else None

    # -- escalation -------------------------------------------------------
    def check_pressure(self, node_id: str) -> None:
        """The one post-spill-pass epilogue every adapter runs: still
        above the hard ceiling → escalate to backpressure, otherwise
        release this node's hold.  Centralized so an escalation-rule
        tweak (hysteresis, per-node ceilings) lands in one place."""
        total = self.total_state_bytes()
        if total > self.hard_ceiling():
            self.escalate(node_id, total - self.budget)
        else:
            self.relax(node_id)

    def escalate(self, node_id: str, over_bytes: int) -> None:
        """Spill could not keep up (nothing cold left to evict, state
        still above the hard ceiling): engage end-of-line backpressure on
        the prefetch pump instead of growing without bound."""
        if _gate_set((id(self), node_id), True):
            self._stats[node_id].backpressure += 1
            self._obs_backpressure.add(1)
            logger.warning(
                "spill: node %s is %d bytes over the hard state ceiling "
                "with no evictable cold state — engaging prefetch "
                "backpressure (sources pause; broker backlog absorbs)",
                node_id, over_bytes,
            )

    def relax(self, node_id: str) -> None:
        _gate_set((id(self), node_id), False)

    def hard_ceiling(self) -> int:
        return int(self.budget * HARD_CEILING_RATIO)

    def close(self) -> None:
        """Query teardown: release every gate this controller holds and
        drop the spill namespace (cold state of a finished query is
        unreachable; checkpointed copies live under their epochs)."""
        if self._closed:
            return
        self._closed = True
        for node_id in list(self._stats):
            self.relax(node_id)
        try:
            if not getattr(self.backend, "_closed", False):
                self.sweep_namespace()
        except Exception as e:  # dnzlint: allow(broad-except) teardown cleanup races backend close by design; leftover keys are swept at next attach
            logger.warning("spill: teardown sweep skipped: %s", e)


# -- wiring ----------------------------------------------------------------


def spill_active(config) -> bool:
    """Spill engages when a budget AND a state backend are configured
    (and ``state_spill`` is not explicitly off).  A budget WITHOUT a
    backend keeps PR-8 semantics: forecasts and pressure verdicts only —
    there is nowhere to spill to."""
    mode = getattr(config, "state_spill", "auto")
    if mode is False or mode == "off":
        return False
    budget = getattr(config, "state_budget_bytes", None)
    path = getattr(config, "state_backend_path", None)
    if not budget or not path:
        if mode is True and budget:
            raise StateError(
                "state_spill=True requires state_backend_path "
                "(Context.with_state_backend) — the cold tier lives in "
                "the LSM state backend"
            )
        return False
    return True


def attach_spill(root, ctx):
    """Walk the physical plan and enable the cold tier on every operator
    that implements ``enable_spill`` — returns the controller (caller
    closes it at query end) or None when spill is not configured.  Must
    run BEFORE checkpoint wiring: restore rebuilds each tier map through
    the adapter installed here."""
    if not spill_active(ctx.config):
        return None
    from denormalized_tpu.state.checkpoint import assign_node_ids, walk
    from denormalized_tpu.state.lsm import initialize_global_state_backend

    backend = initialize_global_state_backend(
        ctx.config.state_backend_path
    )
    controller = SpillController(
        backend, int(ctx.config.state_budget_bytes)
    )
    controller.sweep_namespace()
    ids = assign_node_ids(root)
    wired = 0
    for op in walk(root):
        hook = getattr(op, "enable_spill", None)
        if hook is not None:
            hook(ids[id(op)], controller)
            wired += 1
    if wired == 0:
        controller.close()
        return None
    return controller
