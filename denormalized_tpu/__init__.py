"""denormalized_tpu — a TPU-native stream-processing framework.

A brand-new engine with the capability contract of the reference
(probably-nothing-labs/denormalized: Kafka sources/sinks, JSON/Avro decoding,
event-time watermarks, tumbling/sliding windowed aggregation, stream joins,
barrier checkpointing, fluent Python API — see SURVEY.md), re-designed
TPU-first:

- The windowed-aggregate hot path (the reference's ``GroupedWindowAggStream``,
  crates/core/src/physical_plan/continuous/grouped_window_agg_stream.rs) runs
  as a single ``jax.jit`` step over *device-resident* window x group state in
  HBM with donated buffers; only watermark-triggered windows cross back to
  host.
- Scale-out (the reference's ``RepartitionExec`` hash exchange + per-partition
  tokio tasks) maps to ``jax.sharding.Mesh`` + ``shard_map`` with XLA
  collectives over ICI, not channels.
- The host runtime around the compute path (ingest, decode, state backend) has
  native C++ components, mirroring the reference's use of librdkafka/SlateDB.
"""

from denormalized_tpu.api.context import Context
from denormalized_tpu.api.data_stream import DataStream
from denormalized_tpu.logical.expr import Expr, col, lit

__version__ = "0.1.0"

__all__ = ["Context", "DataStream", "Expr", "col", "lit", "__version__"]
