// Shared dictionary-encoding of a parsed string column, used by both
// columnar parsers (json_parser.cpp / avro_parser.cpp).  Python-side
// string materialization was a per-row slice+decode loop — the dominant
// host cost of the Kafka e2e ingest path at 1M+ rows/s; with dict codes
// the wrapper decodes each DISTINCT value once and fans out with one
// vectorized take (formats/_native_parser_base.py).
#pragma once
#include <cstdint>
#include <string_view>
#include <unordered_map>
#include <vector>

struct StrDict {
  std::vector<int32_t> codes;     // nrows
  std::vector<uint8_t> bytes;     // concatenated unique values
  std::vector<uint64_t> offsets;  // n_uniq + 1
};

// Build ``d`` from a column's (bytes, offsets) pair; returns the number
// of distinct values, or -1 when the column is effectively unique
// (distincts exceed half the rows) — dictionary encoding would then cost
// MORE than the caller's direct per-row decode (hash + byte copy + fanout
// on top of ~n decodes), so the caller falls back.  string_view keys
// alias str_bytes, which is stable for the duration of the call.
inline int64_t build_str_dict(const std::vector<uint8_t>& str_bytes,
                              const std::vector<uint64_t>& offs,
                              uint64_t nrows, StrDict& d) {
  d.codes.clear();
  d.bytes.clear();
  d.offsets.assign(1, 0);
  d.codes.reserve(nrows);
  const uint64_t max_uniq = nrows / 2 + 1;
  std::unordered_map<std::string_view, int32_t> m;
  const char* base = reinterpret_cast<const char*>(str_bytes.data());
  for (uint64_t i = 0; i < nrows; ++i) {
    std::string_view sv(base + offs[i],
                        static_cast<size_t>(offs[i + 1] - offs[i]));
    auto it = m.find(sv);
    int32_t code;
    if (it == m.end()) {
      if (m.size() >= max_uniq) return -1;  // high cardinality: bail
      code = static_cast<int32_t>(m.size());
      m.emplace(sv, code);
      d.bytes.insert(d.bytes.end(), sv.begin(), sv.end());
      d.offsets.push_back(d.bytes.size());
    } else {
      code = it->second;
    }
    d.codes.push_back(code);
  }
  return static_cast<int64_t>(d.offsets.size() - 1);
}
