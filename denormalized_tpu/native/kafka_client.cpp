// kafka_client — minimal native Kafka wire-protocol client.
//
// The reference's Kafka connectivity is librdkafka (native C) behind the
// rdkafka crate (kafka_config.rs make_consumer/make_producer).  This is our
// native equivalent, speaking the Kafka binary protocol directly over TCP:
//
//   ApiVersions v0 | Metadata v1 | ListOffsets v1 | Produce v3 | Fetch v4
//
// with modern magic-2 RecordBatches (varint records, CRC32C).  Scope mirrors
// what the reference engine actually uses: partition discovery
// (get_topic_partition_count, kafka_config.rs:325), earliest/latest offset
// lookup + seek (kafka_stream_read.rs:118-140), per-partition fetch loops
// (:165-296), and fire-and-forget produce (topic_writer.rs KafkaSink).
// Consumer-group coordination is intentionally absent — offsets are owned by
// the engine's checkpoint store, exactly like the reference persists
// BatchReadMetadata to SlateDB rather than committing to Kafka.
//
// C ABI for ctypes; one connection per client object; not thread-safe
// (callers hold one client per partition reader, mirroring rdkafka's
// per-consumer model).

#include <algorithm>
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dlfcn.h>
#include <mutex>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

// ---- CRC32C (Castagnoli), table-driven ----------------------------------
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
uint32_t crc32c(const uint8_t* d, size_t n) {
  static const Crc32cTable tab;
  uint32_t c = ~0u;
  for (size_t i = 0; i < n; i++) c = tab.t[(c ^ d[i]) & 0xFF] ^ (c >> 8);
  return ~c;
}

// ---- TLS via dlopen'd OpenSSL -------------------------------------------
// The image ships the OpenSSL 3 RUNTIME (libssl.so.3 / libcrypto.so.3) but
// not the dev headers, so the needed surface is declared here and resolved
// with dlopen/dlsym at first use.  This matches the capability the
// reference inherits from librdkafka's ssl support (kafka_config.rs:48-58
// passes security.protocol etc. straight through to rdkafka).  All OpenSSL
// object types are opaque pointers at this ABI level.
struct TlsApi {
  void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(void*);
  void (*SSL_CTX_free)(void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_shutdown)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*SSL_set1_host)(void*, const char*);
  void* (*SSL_get0_param)(void*);
  int (*X509_VERIFY_PARAM_set1_ip_asc)(void*, const char*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);
  bool ok = false;
};

TlsApi* tls_api() {
  // std::call_once, not a hand-rolled "tried" flag: per-partition reader
  // threads connect concurrently, and two threads racing the dlopen/dlsym
  // fill would publish half-written function pointers (the data race the
  // TSan hammer in native_test.cpp pins)
  static TlsApi api;
  static std::once_flag once;
  std::call_once(once, [] {
    // libssl declares libcrypto as a dependency, but ERR_* symbols live in
    // libcrypto — resolve each from its own handle
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!ssl) ssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_LOCAL);
    if (!ssl) ssl = dlopen("libssl.so", RTLD_NOW | RTLD_LOCAL);
    void* cry = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!cry) cry = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_LOCAL);
    if (!cry) cry = dlopen("libcrypto.so", RTLD_NOW | RTLD_LOCAL);
    if (ssl && cry) {
      bool all = true;
      auto S = [&](const char* n) {
        void* p = dlsym(ssl, n);
        if (!p) all = false;
        return p;
      };
      auto C = [&](const char* n) {
        void* p = dlsym(cry, n);
        if (!p) all = false;
        return p;
      };
      api.TLS_client_method = (void* (*)())S("TLS_client_method");
      api.SSL_CTX_new = (void* (*)(void*))S("SSL_CTX_new");
      api.SSL_CTX_free = (void (*)(void*))S("SSL_CTX_free");
      api.SSL_CTX_load_verify_locations =
          (int (*)(void*, const char*, const char*))S(
              "SSL_CTX_load_verify_locations");
      api.SSL_CTX_set_default_verify_paths =
          (int (*)(void*))S("SSL_CTX_set_default_verify_paths");
      api.SSL_CTX_set_verify =
          (void (*)(void*, int, void*))S("SSL_CTX_set_verify");
      api.SSL_new = (void* (*)(void*))S("SSL_new");
      api.SSL_free = (void (*)(void*))S("SSL_free");
      api.SSL_set_fd = (int (*)(void*, int))S("SSL_set_fd");
      api.SSL_connect = (int (*)(void*))S("SSL_connect");
      api.SSL_read = (int (*)(void*, void*, int))S("SSL_read");
      api.SSL_write = (int (*)(void*, const void*, int))S("SSL_write");
      api.SSL_shutdown = (int (*)(void*))S("SSL_shutdown");
      api.SSL_ctrl = (long (*)(void*, int, long, void*))S("SSL_ctrl");
      api.SSL_set1_host = (int (*)(void*, const char*))S("SSL_set1_host");
      api.SSL_get0_param = (void* (*)(void*))S("SSL_get0_param");
      api.X509_VERIFY_PARAM_set1_ip_asc =
          (int (*)(void*, const char*))C("X509_VERIFY_PARAM_set1_ip_asc");
      api.ERR_get_error = (unsigned long (*)())C("ERR_get_error");
      api.ERR_error_string_n =
          (void (*)(unsigned long, char*, size_t))C("ERR_error_string_n");
      api.ok = all;
    }
  });
  return api.ok ? &api : nullptr;
}

std::string tls_err(TlsApi* api, const char* what) {
  char buf[256] = {0};
  unsigned long e = api->ERR_get_error();
  if (e)
    api->ERR_error_string_n(e, buf, sizeof buf);
  else
    snprintf(buf, sizeof buf, "%s", strerror(errno));
  return std::string(what) + ": " + buf;
}

// ---- byte buffer helpers ------------------------------------------------
struct Writer {
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void i8(int8_t v) { buf.push_back((uint8_t)v); }
  void i16(int16_t v) {
    uint16_t x = htons((uint16_t)v);
    append(&x, 2);
  }
  void i32(int32_t v) {
    uint32_t x = htonl((uint32_t)v);
    append(&x, 4);
  }
  void u32(uint32_t v) {
    uint32_t x = htonl(v);
    append(&x, 4);
  }
  void i64(int64_t v) {
    uint32_t hi = htonl((uint32_t)(((uint64_t)v) >> 32));
    uint32_t lo = htonl((uint32_t)(v & 0xFFFFFFFFu));
    append(&hi, 4);
    append(&lo, 4);
  }
  void str(const std::string& s) {
    i16((int16_t)s.size());
    append(s.data(), s.size());
  }
  void nullable_str() { i16(-1); }
  void bytes(const std::vector<uint8_t>& b) {
    i32((int32_t)b.size());
    append(b.data(), b.size());
  }
  void varint(int64_t v) {  // zigzag
    uint64_t z = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
    while (z >= 0x80) {
      buf.push_back((uint8_t)(z | 0x80));
      z >>= 7;
    }
    buf.push_back((uint8_t)z);
  }
  void append(const void* p, size_t n) {
    const uint8_t* b = (const uint8_t*)p;
    buf.insert(buf.end(), b, b + n);
  }
};

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;
  bool need(size_t n) {
    if ((size_t)(end - p) < n) {
      fail = true;
      return false;
    }
    return true;
  }
  int8_t i8() {
    if (!need(1)) return 0;
    return (int8_t)*p++;
  }
  int16_t i16() {
    if (!need(2)) return 0;
    uint16_t x;
    memcpy(&x, p, 2);
    p += 2;
    return (int16_t)ntohs(x);
  }
  int32_t i32() {
    if (!need(4)) return 0;
    uint32_t x;
    memcpy(&x, p, 4);
    p += 4;
    return (int32_t)ntohl(x);
  }
  uint32_t u32() { return (uint32_t)i32(); }
  int64_t i64() {
    if (!need(8)) return 0;
    uint32_t hi, lo;
    memcpy(&hi, p, 4);
    memcpy(&lo, p + 4, 4);
    p += 8;
    return ((int64_t)ntohl(hi) << 32) | (uint32_t)ntohl(lo);
  }
  std::string str() {
    int16_t n = i16();
    if (n < 0) return "";
    if (!need((size_t)n)) return "";
    std::string s((const char*)p, n);
    p += n;
    return s;
  }
  void skip_bytes() {
    int32_t n = i32();
    if (n > 0 && need((size_t)n)) p += n;
  }
  int64_t varint() {
    uint64_t acc = 0;
    int shift = 0;
    while (need(1)) {
      uint8_t b = *p++;
      acc |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
    }
    return (int64_t)((acc >> 1) ^ (~(acc & 1) + 1));
  }
  void skip(size_t n) {
    if (need(n)) p += n;
  }
};

struct Client {
  int fd = -1;
  std::string error;
  int32_t corr = 0;
  // fetch results
  std::vector<uint8_t> rec_bytes;
  std::vector<uint64_t> rec_offsets;  // n+1
  std::vector<int64_t> rec_ts;
  std::vector<int64_t> rec_kafka_offsets;
  int64_t next_offset = 0;
  int64_t high_watermark = 0;
  // externally-decompressed codecs (e.g. zstd via the caller's Python
  // zstandard module): batches whose codec bit is set here are stashed in
  // `pending` for the caller to decompress and re-ingest, instead of
  // erroring.  Bit n = Kafka codec id n.
  uint32_t ext_codec_mask = 0;
  struct Pending {
    int64_t base_offset;
    int64_t first_ts;
    int64_t fetch_offset;
    int32_t nrec;
    int32_t last_offset_delta;
    int32_t codec;
    std::vector<uint8_t> data;  // compressed records section
  };
  std::vector<Pending> pending;

  // TLS state (null = plaintext).  All framing above this layer is
  // identical either way — rpc() and the record paths never know.
  void* ssl = nullptr;
  void* ssl_ctx = nullptr;

  bool send_all(const uint8_t* d, size_t n) {
    while (n) {
      ssize_t w;
      if (ssl) {
        w = tls_api()->SSL_write(ssl, d, (int)std::min(n, (size_t)1 << 30));
        if (w <= 0) {
          error = tls_err(tls_api(), "tls send");
          return false;
        }
      } else {
        w = ::send(fd, d, n, MSG_NOSIGNAL);
        if (w <= 0) {
          error = std::string("send: ") + strerror(errno);
          return false;
        }
      }
      d += w;
      n -= (size_t)w;
    }
    return true;
  }
  bool recv_all(uint8_t* d, size_t n) {
    while (n) {
      ssize_t r;
      if (ssl) {
        r = tls_api()->SSL_read(ssl, d, (int)std::min(n, (size_t)1 << 30));
        if (r <= 0) {
          error = tls_err(tls_api(), "tls recv");
          return false;
        }
      } else {
        r = ::recv(fd, d, n, 0);
        if (r <= 0) {
          error = std::string("recv: ") + strerror(errno);
          return false;
        }
      }
      d += r;
      n -= (size_t)r;
    }
    return true;
  }

  // frame + send a request, receive full response body (after corr id)
  bool rpc(int16_t api_key, int16_t api_version, const Writer& body,
           std::vector<uint8_t>& resp) {
    Writer req;
    req.i16(api_key);
    req.i16(api_version);
    req.i32(++corr);
    req.str("denormalized-tpu");
    req.append(body.buf.data(), body.buf.size());
    Writer framed;
    framed.i32((int32_t)req.buf.size());
    framed.append(req.buf.data(), req.buf.size());
    if (!send_all(framed.buf.data(), framed.buf.size())) return false;
    uint8_t szb[4];
    if (!recv_all(szb, 4)) return false;
    uint32_t sz = ntohl(*(uint32_t*)szb);
    if (sz < 4 || sz > (1u << 28)) {
      error = "bad response size";
      return false;
    }
    resp.resize(sz);
    if (!recv_all(resp.data(), sz)) return false;
    // strip correlation id
    resp.erase(resp.begin(), resp.begin() + 4);
    return true;
  }
};

// build a magic-2 RecordBatch from payloads
void build_record_batch(Writer& out, const uint8_t* data,
                        const uint64_t* offs, int n, int64_t now_ms) {
  Writer records;
  for (int i = 0; i < n; i++) {
    const uint8_t* v = data + offs[i];
    int64_t vlen = (int64_t)(offs[i + 1] - offs[i]);
    Writer rec;
    rec.i8(0);           // attributes
    rec.varint(0);       // timestampDelta
    rec.varint(i);       // offsetDelta
    rec.varint(-1);      // key length (null)
    rec.varint(vlen);    // value length
    rec.append(v, (size_t)vlen);
    rec.varint(0);       // headers
    records.varint((int64_t)rec.buf.size());
    records.append(rec.buf.data(), rec.buf.size());
  }
  // batch header
  Writer hdr;  // part covered by CRC starts at attributes
  hdr.i16(0);                    // attributes
  hdr.i32(n - 1);                // lastOffsetDelta
  hdr.i64(now_ms);               // firstTimestamp
  hdr.i64(now_ms);               // maxTimestamp
  hdr.i64(-1);                   // producerId
  hdr.i16(-1);                   // producerEpoch
  hdr.i32(-1);                   // baseSequence
  hdr.i32(n);                    // numRecords
  hdr.append(records.buf.data(), records.buf.size());
  uint32_t crc = crc32c(hdr.buf.data(), hdr.buf.size());

  Writer batch;
  batch.i64(0);                              // baseOffset
  batch.i32((int32_t)(hdr.buf.size() + 9));  // batchLength (from leaderEpoch)
  batch.i32(-1);                             // partitionLeaderEpoch
  batch.i8(2);                               // magic
  batch.u32(crc);
  batch.append(hdr.buf.data(), hdr.buf.size());
  out.bytes(batch.buf);
}

// inflate a gzip stream (Kafka codec 1) into out
bool gunzip(const uint8_t* src, size_t n, std::vector<uint8_t>& out) {
  z_stream zs{};
  if (inflateInit2(&zs, 15 + 16) != Z_OK) return false;  // gzip wrapper
  out.clear();
  out.resize(n * 4 + 1024);
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = (uInt)n;
  size_t written = 0;
  int rc;
  do {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = (uInt)(out.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = out.size() - zs.avail_out;
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
  } while (rc != Z_STREAM_END && zs.avail_in > 0);
  inflateEnd(&zs);
  out.resize(written);
  return rc == Z_STREAM_END;
}

// ---- snappy (Kafka codec 2) --------------------------------------------
// Raw snappy block format: uvarint uncompressed length, then a stream of
// literal/copy elements.  Kafka magic-2 batches carry raw snappy; legacy
// Java producers wrapped it in xerial framing (magic "\x82SNAPPY\x00"),
// which librdkafka also auto-detects — mirror that.

bool snappy_block(const uint8_t* p, const uint8_t* end,
                  std::vector<uint8_t>& out) {
  // uncompressed length: plain LE base-128 varint (not zigzag)
  uint64_t ulen = 0;
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    ulen |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 35) return false;
  }
  if (ulen > (1u << 30)) return false;  // 1GB sanity cap
  size_t base = out.size();
  // reserve bounded by what the input could plausibly expand to, NOT the
  // corruption-controlled ulen alone — a crafted 10-byte stream declaring
  // ulen=1GB must not allocate a gigabyte before validation rejects it
  size_t n = (size_t)(end - p);
  out.reserve(base + (size_t)std::min<uint64_t>(ulen, n * 64 + 4096));
  while (p < end) {
    uint8_t tag = *p++;
    uint32_t type = tag & 3;
    if (type == 0) {  // literal
      uint32_t len = (tag >> 2) + 1;
      if (len > 60) {
        uint32_t nb = len - 60;
        if (p + nb > end) return false;
        len = 0;
        for (uint32_t i = 0; i < nb; i++) len |= (uint32_t)p[i] << (8 * i);
        p += nb;
        len += 1;
      }
      if (p + len > end) return false;
      out.insert(out.end(), p, p + len);
      p += len;
    } else {  // copy
      uint32_t len, off;
      if (type == 1) {
        if (p >= end) return false;
        len = ((tag >> 2) & 7) + 4;
        off = ((uint32_t)(tag >> 5) << 8) | *p++;
      } else if (type == 2) {
        if (p + 2 > end) return false;
        len = (tag >> 2) + 1;
        off = (uint32_t)p[0] | ((uint32_t)p[1] << 8);
        p += 2;
      } else {
        if (p + 4 > end) return false;
        len = (tag >> 2) + 1;
        off = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
              ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
        p += 4;
      }
      size_t produced = out.size() - base;
      if (off == 0 || off > produced) return false;
      // reject before copying: output past the declared length is invalid,
      // so a corrupt stream can never make us do unbounded copy work
      if (produced + len > ulen) return false;
      // byte-by-byte: copies may overlap their own output (RLE)
      size_t src = out.size() - off;
      for (uint32_t i = 0; i < len; i++) out.push_back(out[src + i]);
    }
  }
  return out.size() - base == ulen;
}

bool snappy_decompress(const uint8_t* src, size_t n,
                       std::vector<uint8_t>& out) {
  out.clear();
  static const uint8_t XERIAL[8] = {0x82, 'S', 'N', 'A', 'P', 'P', 'Y', 0};
  if (n > 16 && memcmp(src, XERIAL, 8) == 0) {
    // xerial frame: magic + version(4) + compat(4), then [len BE][block]*
    const uint8_t* p = src + 16;
    const uint8_t* end = src + n;
    while (p + 4 <= end) {
      uint32_t len = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                     ((uint32_t)p[2] << 8) | (uint32_t)p[3];
      p += 4;
      if (p + len > end) return false;
      if (!snappy_block(p, p + len, out)) return false;
      p += len;
    }
    return p == end;
  }
  return snappy_block(src, src + n, out);
}

// ---- lz4 (Kafka codec 3) -----------------------------------------------
// LZ4 Frame format (magic 0x184D2204) wrapping LZ4 block compression.
// Checksums (xxhash) are skipped, not validated — the transport is TCP and
// the decode itself bounds-checks every copy.

bool lz4_block(const uint8_t* p, const uint8_t* end, std::vector<uint8_t>& out,
               size_t base) {
  while (p < end) {
    uint8_t token = *p++;
    uint32_t litlen = token >> 4;
    if (litlen == 15) {
      uint8_t b;
      do {
        if (p >= end) return false;
        b = *p++;
        litlen += b;
      } while (b == 255);
    }
    if (p + litlen > end) return false;
    out.insert(out.end(), p, p + litlen);
    p += litlen;
    if (p >= end) break;  // last sequence: literals only
    if (p + 2 > end) return false;
    uint32_t off = (uint32_t)p[0] | ((uint32_t)p[1] << 8);
    p += 2;
    uint32_t mlen = token & 0xF;
    if (mlen == 15) {
      uint8_t b;
      do {
        if (p >= end) return false;
        b = *p++;
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    size_t produced = out.size() - base;
    if (off == 0 || off > produced) return false;
    // cap BEFORE the copy: a corrupt matchlength extension (runs of 0xFF)
    // can encode ~1e9 in a few input bytes — reject it in O(1) instead of
    // doing a gigabyte of copy work first
    if (out.size() + mlen > (1u << 30)) return false;
    size_t src = out.size() - off;
    for (uint32_t i = 0; i < mlen; i++) out.push_back(out[src + i]);
  }
  return true;
}

bool lz4f_decompress(const uint8_t* src, size_t n,
                     std::vector<uint8_t>& out) {
  out.clear();
  const uint8_t* p = src;
  const uint8_t* end = src + n;
  if (n < 7) return false;
  uint32_t magic = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                   ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
  if (magic != 0x184D2204u) return false;
  p += 4;
  uint8_t flg = *p++;
  p++;  // BD (block max size) — we size dynamically
  if ((flg >> 6) != 1) return false;     // version
  bool content_size = flg & 0x08;
  bool block_checksum = flg & 0x10;
  bool content_checksum = flg & 0x04;
  bool dict_id = flg & 0x01;
  if (content_size) p += 8;
  if (dict_id) p += 4;
  p += 1;  // header checksum byte
  if (p > end) return false;
  while (p + 4 <= end) {
    uint32_t bsz = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                   ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
    p += 4;
    if (bsz == 0) {  // EndMark
      if (content_checksum) p += 4;
      return true;
    }
    bool stored = bsz & 0x80000000u;
    bsz &= 0x7FFFFFFFu;
    if (p + bsz > end) return false;
    if (stored) {
      out.insert(out.end(), p, p + bsz);
    } else {
      // each frame block decompresses independently against the data
      // already in `out` (blocks may reference prior blocks' output when
      // the frame is block-linked; passing base=0 allows both modes)
      if (!lz4_block(p, p + bsz, out, 0)) return false;
    }
    p += bsz;
    if (block_checksum) p += 4;
  }
  return false;  // ran out of input before EndMark
}

const char* codec_name(int codec) {
  switch (codec) {
    case 1: return "gzip";
    case 2: return "snappy";
    case 3: return "lz4";
    case 4: return "zstd";
    default: return "unknown";
  }
}

// parse one records stream (inline or decompressed) into the client's
// arenas; returns false (with c->error set) on corrupt record data
bool parse_records_stream(Client* c, Reader rr, int32_t nrec,
                          int64_t base_offset, int64_t first_ts,
                          int64_t fetch_offset) {
  for (int32_t i = 0; i < nrec && !rr.fail; i++) {
    int64_t rec_len = rr.varint();
    const uint8_t* rec_end = rr.p + rec_len;
    rr.i8();  // attributes
    int64_t ts_delta = rr.varint();
    int64_t off_delta = rr.varint();
    int64_t klen = rr.varint();
    if (klen > 0) rr.skip((size_t)klen);
    int64_t vlen = rr.varint();
    int64_t abs_off = base_offset + off_delta;
    if (abs_off >= fetch_offset && vlen >= 0 && rr.need((size_t)vlen)) {
      c->rec_bytes.insert(c->rec_bytes.end(), rr.p, rr.p + vlen);
      c->rec_offsets.push_back(c->rec_bytes.size());
      c->rec_ts.push_back(first_ts + ts_delta);
      c->rec_kafka_offsets.push_back(abs_off);
    }
    // the cursor advances past EVERY record >= fetch_offset — including
    // tombstones (vlen == -1) and pre-filter duplicates — or the consumer
    // would refetch the same batch forever
    if (abs_off >= fetch_offset && abs_off + 1 > c->next_offset)
      c->next_offset = abs_off + 1;
    if (vlen > 0) rr.skip((size_t)vlen);
    // headers
    int64_t nh = rr.varint();
    for (int64_t h = 0; h < nh && !rr.fail; h++) {
      int64_t kl = rr.varint();
      rr.skip((size_t)kl);
      int64_t vl = rr.varint();
      if (vl > 0) rr.skip((size_t)vl);
    }
    // rec_end comes from an untrusted rec_len (possibly decompressed from
    // an external codec): never let the cursor move past the buffer, or
    // Reader::need's (end - p) would underflow and every later bounds
    // check would pass on out-of-bounds memory
    if (rr.p > rec_end || rec_end > rr.end) rr.fail = true;
    else rr.p = rec_end;
  }
  if (rr.fail) {
    // same error-loudly policy as the codec branches: a record stream
    // that goes bad mid-batch (truncated/garbled after a successful
    // decompress — nothing validates content checksums) must not
    // silently drop its remaining records and advance past them.
    c->error = "corrupt record data in batch at offset " +
               std::to_string(base_offset);
    return false;
  }
  return true;
}

// parse magic-2 record batches out of a Fetch "records" blob
bool parse_record_sets(Client* c, Reader& r, int32_t total_len,
                       int64_t fetch_offset) {
  const uint8_t* blob_end = r.p + total_len;
  while (r.p + 61 <= blob_end) {  // minimal batch header size
    int64_t base_offset = r.i64();
    int32_t batch_len = r.i32();
    if (r.fail || batch_len <= 0 || r.p + batch_len > blob_end) break;
    const uint8_t* batch_end = r.p + batch_len;
    r.i32();              // partitionLeaderEpoch
    int8_t magic = r.i8();
    if (magic != 2) {
      // legacy v0/v1 message sets: error loudly — silently skipping them
      // would be silent data loss against an old producer
      c->error = "legacy message format magic=" + std::to_string(magic) +
                 " at offset " + std::to_string(base_offset) +
                 " (only magic-2 record batches are supported)";
      return false;
    }
    r.u32();              // crc (trusted; transport is TCP)
    int16_t attrs = r.i16();
    int codec = attrs & 0x7;
    std::vector<uint8_t> inflated;  // keeps decompressed records alive
    if (codec > 3 && !((c->ext_codec_mask >> codec) & 1)) {
      // zstd (or future codec) with no external decompressor registered:
      // no silent skip — surface the codec by name so the operator can
      // reconfigure the producer or the topic (the reference gets all
      // codecs from librdkafka, Cargo.toml:58)
      c->error = std::string("unsupported compression codec ") +
                 codec_name(codec) + " (" + std::to_string(codec) +
                 ") in batch at offset " + std::to_string(base_offset);
      return false;
    }
    int32_t last_offset_delta = r.i32();
    int64_t first_ts = r.i64();
    r.i64();              // maxTimestamp
    r.skip(8 + 2 + 4);    // producerId/Epoch/baseSequence
    int32_t nrec = r.i32();
    if (codec > 3) {
      // externally-decompressed codec: stash the compressed records
      // section; the caller decompresses (e.g. Python zstandard) and
      // re-ingests through kc_ingest_decompressed BEFORE reading the
      // fetch arena
      Client::Pending pend;
      pend.base_offset = base_offset;
      pend.first_ts = first_ts;
      pend.fetch_offset = fetch_offset;
      pend.nrec = nrec;
      pend.last_offset_delta = last_offset_delta;
      pend.codec = codec;
      pend.data.assign(r.p, batch_end);
      c->pending.push_back(std::move(pend));
      r.p = batch_end;
      continue;
    }
    if (!c->pending.empty()) {
      // an inline batch AFTER a stashed one would be parsed into the arena
      // BEFORE the stashed batch's records are ingested, scrambling
      // partition-offset order.  Stop the fetch here; these batches
      // refetch next round (next_offset has not advanced past them).
      r.p = blob_end;
      return true;
    }
    Reader rr = r;  // records section (inline, or decompressed)
    if (codec != 0) {
      bool ok = false;
      size_t comp_len = (size_t)(batch_end - r.p);
      if (codec == 1) ok = gunzip(r.p, comp_len, inflated);
      else if (codec == 2) ok = snappy_decompress(r.p, comp_len, inflated);
      else ok = lz4f_decompress(r.p, comp_len, inflated);
      if (!ok) {
        // corrupt compressed section: error (a skip would silently drop
        // up to last_offset_delta+1 records)
        c->error = std::string(codec_name(codec)) +
                   " decompression failed for batch at offset " +
                   std::to_string(base_offset);
        return false;
      }
      rr = Reader{inflated.data(), inflated.data() + inflated.size()};
    }
    if (!parse_records_stream(c, rr, nrec, base_offset, first_ts,
                              fetch_offset))
      return false;
    // safety net for empty/odd batches: never stall behind a consumed batch
    int64_t past = base_offset + last_offset_delta + 1;
    if (past > c->next_offset && past > fetch_offset) c->next_offset = past;
    r.p = batch_end;
  }
  r.p = blob_end;
  return true;
}


}  // namespace

extern "C" {

void* kc_connect(const char* host, int port, char* errbuf, int errlen) {
  addrinfo hints{};
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  int rc = getaddrinfo(host, portstr, &hints, &res);
  if (rc != 0) {
    snprintf(errbuf, errlen, "resolve %s: %s", host, gai_strerror(rc));
    return nullptr;
  }
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // bounded connect/recv: a blackholed peer must not freeze the reader
    // thread for the kernel's multi-minute SYN retry cycle
    timeval conn_to{5, 0};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &conn_to, sizeof conn_to);
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      timeval io_to{30, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &io_to, sizeof io_to);
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    snprintf(errbuf, errlen, "connect %s:%d failed", host, port);
    return nullptr;
  }
  Client* c = new Client();
  c->fd = fd;
  return c;
}

void kc_close(void* h) {
  Client* c = static_cast<Client*>(h);
  TlsApi* api = c->ssl ? tls_api() : nullptr;
  if (api) {
    api->SSL_shutdown(c->ssl);  // best-effort close_notify
    api->SSL_free(c->ssl);
    if (c->ssl_ctx) api->SSL_CTX_free(c->ssl_ctx);
  }
  if (c->fd >= 0) close(c->fd);
  delete c;
}

// Upgrade the connected socket to TLS (librdkafka security.protocol=SSL
// analog).  ca_path: PEM bundle (null → system default paths); verify:
// nonzero enforces certificate chain + host identity (host_for_verify
// handles both DNS names and IP-literal SANs); SNI is sent for DNS names.
// Returns 0 on success; on failure the connection is unusable.
int kc_tls_init(void* h, const char* ca_path, int verify,
                const char* host_for_verify, char* errbuf, int errlen) {
  Client* c = static_cast<Client*>(h);
  TlsApi* api = tls_api();
  if (!api) {
    snprintf(errbuf, errlen,
             "TLS unavailable: libssl/libcrypto not loadable in this "
             "environment");
    return -1;
  }
  void* ctx = api->SSL_CTX_new(api->TLS_client_method());
  if (!ctx) {
    snprintf(errbuf, errlen, "%s", tls_err(api, "SSL_CTX_new").c_str());
    return -1;
  }
  if (ca_path && *ca_path) {
    if (api->SSL_CTX_load_verify_locations(ctx, ca_path, nullptr) != 1) {
      snprintf(errbuf, errlen, "%s",
               tls_err(api, "load ssl.ca.location").c_str());
      api->SSL_CTX_free(ctx);
      return -1;
    }
  } else {
    api->SSL_CTX_set_default_verify_paths(ctx);
  }
  if (verify) api->SSL_CTX_set_verify(ctx, 1 /*SSL_VERIFY_PEER*/, nullptr);
  void* ssl = api->SSL_new(ctx);
  if (!ssl) {
    snprintf(errbuf, errlen, "%s", tls_err(api, "SSL_new").c_str());
    api->SSL_CTX_free(ctx);
    return -1;
  }
  api->SSL_set_fd(ssl, c->fd);
  bool is_ip = false;
  if (host_for_verify && *host_for_verify) {
    unsigned char tmp[16];
    is_ip = inet_pton(AF_INET, host_for_verify, tmp) == 1 ||
            inet_pton(AF_INET6, host_for_verify, tmp) == 1;
    if (!is_ip) {
      // SNI (RFC 6066 forbids IP literals in the extension)
      api->SSL_ctrl(ssl, 55 /*SSL_CTRL_SET_TLSEXT_HOSTNAME*/,
                    0 /*TLSEXT_NAMETYPE_host_name*/,
                    (void*)host_for_verify);
    }
    if (verify) {
      int hv;
      if (is_ip)
        hv = api->X509_VERIFY_PARAM_set1_ip_asc(api->SSL_get0_param(ssl),
                                                host_for_verify);
      else
        hv = api->SSL_set1_host(ssl, host_for_verify);
      if (hv != 1) {
        snprintf(errbuf, errlen, "%s",
                 tls_err(api, "set verify host").c_str());
        api->SSL_free(ssl);
        api->SSL_CTX_free(ctx);
        return -1;
      }
    }
  }
  if (api->SSL_connect(ssl) != 1) {
    snprintf(errbuf, errlen, "%s", tls_err(api, "tls handshake").c_str());
    api->SSL_free(ssl);
    api->SSL_CTX_free(ctx);
    return -1;
  }
  c->ssl = ssl;
  c->ssl_ctx = ctx;
  return 0;
}

// SASL/PLAIN (RFC 4616) over the Kafka SaslHandshake v1 + SaslAuthenticate
// v0 exchange — the librdkafka sasl.mechanism=PLAIN analog.  Runs over
// whatever transport is active (call after kc_tls_init for SASL_SSL).
int kc_sasl_plain(void* h, const char* user, const char* pass, char* errbuf,
                  int errlen) {
  Client* c = static_cast<Client*>(h);
  {
    Writer body;
    body.str("PLAIN");
    std::vector<uint8_t> resp;
    if (!c->rpc(17 /*SaslHandshake*/, 1, body, resp)) {
      snprintf(errbuf, errlen, "sasl handshake: %s", c->error.c_str());
      return -1;
    }
    Reader r{resp.data(), resp.data() + resp.size()};
    int16_t err = r.i16();
    if (err != 0) {
      // collect the broker's advertised mechanisms for the error
      std::string mechs;
      int32_t n = r.i32();
      for (int32_t i = 0; i < n && !r.fail; i++) {
        if (i) mechs += ",";
        mechs += r.str();
      }
      snprintf(errbuf, errlen,
               "broker rejected SASL mechanism PLAIN (error %d; broker "
               "supports: %s)",
               (int)err, mechs.c_str());
      return -1;
    }
  }
  {
    std::vector<uint8_t> token;
    token.push_back(0);  // authzid (empty)
    token.insert(token.end(), user, user + strlen(user));
    token.push_back(0);
    token.insert(token.end(), pass, pass + strlen(pass));
    Writer body;
    body.bytes(token);
    std::vector<uint8_t> resp;
    if (!c->rpc(36 /*SaslAuthenticate*/, 0, body, resp)) {
      snprintf(errbuf, errlen, "sasl authenticate: %s", c->error.c_str());
      return -1;
    }
    Reader r{resp.data(), resp.data() + resp.size()};
    int16_t err = r.i16();
    if (err != 0) {
      int16_t mlen = r.i16();
      std::string msg;
      if (mlen > 0 && r.need((size_t)mlen)) {
        msg.assign((const char*)r.p, (size_t)mlen);
      }
      snprintf(errbuf, errlen, "sasl authentication failed (error %d%s%s)",
               (int)err, msg.empty() ? "" : ": ", msg.c_str());
      return -1;
    }
  }
  return 0;
}

const char* kc_error(void* h) {
  return static_cast<Client*>(h)->error.c_str();
}

// Metadata v1 → partition count for topic (-1 on error)
int kc_partition_count(void* h, const char* topic) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.i32(1);  // one topic
  body.str(topic);
  std::vector<uint8_t> resp;
  if (!c->rpc(3, 1, body, resp)) return -1;
  Reader r{resp.data(), resp.data() + resp.size()};
  int32_t nbrokers = r.i32();
  for (int32_t i = 0; i < nbrokers; i++) {
    r.i32();
    r.str();
    r.i32();
    r.str();  // rack (nullable)
  }
  r.i32();  // controller id
  int32_t ntopics = r.i32();
  for (int32_t t = 0; t < ntopics; t++) {
    int16_t terr = r.i16();
    std::string name = r.str();
    r.i8();  // is_internal
    int32_t nparts = r.i32();
    if (name == topic) {
      if (terr != 0) {
        c->error = "metadata error code " + std::to_string(terr);
        return -1;
      }
      return nparts;
    }
    for (int32_t pi = 0; pi < nparts; pi++) {
      r.i16();
      r.i32();
      r.i32();
      int32_t nr = r.i32();
      for (int32_t x = 0; x < nr; x++) r.i32();
      int32_t ni = r.i32();
      for (int32_t x = 0; x < ni; x++) r.i32();
    }
  }
  c->error = "topic not in metadata";
  return -1;
}

// ListOffsets v1: ts -1=latest, -2=earliest
int64_t kc_list_offset(void* h, const char* topic, int partition, int64_t ts) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.i32(-1);  // replica
  body.i32(1);   // topics
  body.str(topic);
  body.i32(1);  // partitions
  body.i32(partition);
  body.i64(ts);
  std::vector<uint8_t> resp;
  if (!c->rpc(2, 1, body, resp)) return -1;
  Reader r{resp.data(), resp.data() + resp.size()};
  int32_t ntopics = r.i32();
  for (int32_t t = 0; t < ntopics; t++) {
    r.str();
    int32_t nparts = r.i32();
    for (int32_t p = 0; p < nparts; p++) {
      r.i32();  // partition
      int16_t err = r.i16();
      r.i64();  // timestamp
      int64_t off = r.i64();
      if (err != 0) {
        c->error = "list_offsets error " + std::to_string(err);
        return -1;
      }
      return off;
    }
  }
  c->error = "empty list_offsets response";
  return -1;
}

// Produce v3, acks=1
int kc_produce(void* h, const char* topic, int partition, const uint8_t* data,
               const uint64_t* offs, int n, int64_t now_ms) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.nullable_str();  // transactional_id
  body.i16(1);          // acks
  body.i32(10000);      // timeout
  body.i32(1);          // topics
  body.str(topic);
  body.i32(1);  // partitions
  body.i32(partition);
  build_record_batch(body, data, offs, n, now_ms);
  std::vector<uint8_t> resp;
  if (!c->rpc(0, 3, body, resp)) return -1;
  Reader r{resp.data(), resp.data() + resp.size()};
  int32_t ntopics = r.i32();
  for (int32_t t = 0; t < ntopics; t++) {
    r.str();
    int32_t nparts = r.i32();
    for (int32_t p = 0; p < nparts; p++) {
      r.i32();
      int16_t err = r.i16();
      r.i64();  // base offset
      r.i64();  // log append time
      if (err != 0) {
        c->error = "produce error " + std::to_string(err);
        return -1;
      }
    }
  }
  return 0;
}

// Fetch v4 from offset; returns record count, -1 error
int kc_fetch(void* h, const char* topic, int partition, int64_t offset,
             int max_bytes, int max_wait_ms) {
  Client* c = static_cast<Client*>(h);
  c->rec_bytes.clear();
  c->rec_offsets.assign(1, 0);
  c->rec_ts.clear();
  c->rec_kafka_offsets.clear();
  c->pending.clear();
  c->next_offset = offset;
  Writer body;
  body.i32(-1);           // replica
  body.i32(max_wait_ms);  // max wait
  body.i32(1);            // min bytes
  body.i32(max_bytes);    // max bytes
  body.i8(0);             // isolation: read_uncommitted
  body.i32(1);            // topics
  body.str(topic);
  body.i32(1);  // partitions
  body.i32(partition);
  body.i64(offset);
  body.i32(max_bytes);
  std::vector<uint8_t> resp;
  if (!c->rpc(1, 4, body, resp)) return -1;
  Reader r{resp.data(), resp.data() + resp.size()};
  r.i32();  // throttle
  int32_t ntopics = r.i32();
  for (int32_t t = 0; t < ntopics; t++) {
    r.str();
    int32_t nparts = r.i32();
    for (int32_t p = 0; p < nparts; p++) {
      r.i32();  // partition
      int16_t err = r.i16();
      c->high_watermark = r.i64();
      r.i64();  // last stable offset
      int32_t naborted = r.i32();
      for (int32_t a = 0; a < naborted; a++) {
        r.i64();
        r.i64();
      }
      int32_t blob_len = r.i32();
      if (err != 0) {
        c->error = "fetch error " + std::to_string(err);
        return -1;
      }
      if (blob_len > 0 && !parse_record_sets(c, r, blob_len, offset))
        return -1;
    }
  }
  if (r.fail) {
    c->error = "malformed fetch response";
    return -1;
  }
  return (int)c->rec_ts.size();
}

// register codecs the CALLER can decompress (bit n = Kafka codec id n)
void kc_set_external_codecs(void* h, uint32_t mask) {
  static_cast<Client*>(h)->ext_codec_mask = mask;
}

int kc_pending_count(void* h) {
  return (int)static_cast<Client*>(h)->pending.size();
}

int kc_pending_codec(void* h, int i) {
  return static_cast<Client*>(h)->pending[i].codec;
}

const uint8_t* kc_pending_data(void* h, int i, uint64_t* len) {
  Client::Pending& p = static_cast<Client*>(h)->pending[i];
  *len = p.data.size();
  return p.data.data();
}

// ingest a decompressed records section for pending batch i; returns the
// new total record count, or -1 (error set) on corrupt data
int kc_ingest_decompressed(void* h, int i, const uint8_t* data,
                           uint64_t len) {
  Client* c = static_cast<Client*>(h);
  Client::Pending& p = c->pending[i];
  Reader rr{data, data + len};
  if (!parse_records_stream(c, rr, p.nrec, p.base_offset, p.first_ts,
                            p.fetch_offset))
    return -1;
  int64_t past = p.base_offset + p.last_offset_delta + 1;
  if (past > c->next_offset && past > p.fetch_offset) c->next_offset = past;
  return (int)c->rec_ts.size();
}

const uint8_t* kc_rec_bytes(void* h, uint64_t* nbytes) {
  Client* c = static_cast<Client*>(h);
  *nbytes = c->rec_bytes.size();
  return c->rec_bytes.data();
}
const uint64_t* kc_rec_offsets(void* h) {
  return static_cast<Client*>(h)->rec_offsets.data();
}
const int64_t* kc_rec_timestamps(void* h) {
  return static_cast<Client*>(h)->rec_ts.data();
}
// absolute Kafka offset of each fetched record — exact slice-boundary
// offsets for readers that split a large fetch into bounded batches
// (gaps from compaction/control records make base+index arithmetic wrong)
const int64_t* kc_rec_kafka_offsets(void* h) {
  return static_cast<Client*>(h)->rec_kafka_offsets.data();
}
int64_t kc_next_offset(void* h) {
  return static_cast<Client*>(h)->next_offset;
}
int64_t kc_high_watermark(void* h) {
  return static_cast<Client*>(h)->high_watermark;
}

}  // extern "C"
