// Host-edge partial window aggregation — the native single-pass reducer
// behind the "partial_merge" device strategy.
//
// Why this exists: a streaming engine feeding an accelerator should ship
// the SMALLEST sufficient statistics across the host->device link, not raw
// rows.  This kernel reduces a decoded batch to per-(slide-unit, sub,
// group) partials (row count; per value column: valid count, sum, min,
// max) in one pass over the rows.  The device then folds the partials into
// its HBM-resident window ring (sliding fan-out included) — the same
// Partial/Final split the reference applies across CPU partitions
// (crates/core/src/planner/streaming_window.rs:133-153), applied across
// the host/accelerator boundary.
//
// The `sub` axis splits each slide unit in two when window length is not a
// multiple of the slide: rows with rem < L - (k-1)*S belong to all k
// overlapping windows (sub 0), the rest to only the first k-1 (sub 1).
// With L % S == 0 every row is sub 0 and SUB == 1.
//
// Accumulation is f64 on host — strictly more precise than the per-row
// f32 device scatter it replaces.

#include <cstdint>
#include <cmath>

extern "C" {

// One pass over n rows.  Arrays are dense C-order:
//   win_rel: (n) int64  — slide-unit index rebased to the stripe window;
//            rows outside [0, U) are skipped (late / overflow, the caller
//            pre-rebased against u_lo)
//   sub:     (n) uint8 or NULL — sub-bucket per row (0/1); NULL = all 0
//   gid:     (n) int32  — dense group ids in [0, G)
//   values:  (n, V) f64 — value matrix (row-major)
//   colvalid:(n, V) uint8 or NULL — per-cell validity; NULL = all valid
// Outputs (all (U * SUB * G) flat, indexed ((u*SUB)+s)*G+g):
//   row_cnt: int64  — rows per cell (count(*))
//   cnt:     (V, U*SUB*G) int64 — valid values per cell per column
//   sum:     (V, U*SUB*G) f64
//   mn:      (V, U*SUB*G) f64 (caller inits to +inf)
//   mx:      (V, U*SUB*G) f64 (caller inits to -inf)
// Returns number of rows folded (excludes skipped).
int64_t partial_window_agg(
    const int64_t* win_rel,
    const uint8_t* sub,
    const int32_t* gid,
    const double* values,
    const uint8_t* colvalid,
    int64_t n,
    int32_t V,
    int32_t U,
    int32_t SUB,
    int32_t G,
    int64_t* row_cnt,
    int64_t* cnt,
    double* sum,
    double* mn,
    double* mx) {
  const int64_t cells = (int64_t)U * SUB * G;
  int64_t folded = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t u = win_rel[i];
    if (u < 0 || u >= U) continue;
    const int32_t g = gid[i];
    if (g < 0 || g >= G) continue;
    const int32_t s = sub ? (int32_t)sub[i] : 0;
    const int64_t cell = ((u * SUB) + s) * G + g;
    ++row_cnt[cell];
    ++folded;
    for (int32_t v = 0; v < V; ++v) {
      if (colvalid && !colvalid[i * V + v]) continue;
      const double x = values[i * V + v];
      const int64_t off = (int64_t)v * cells + cell;
      ++cnt[off];
      sum[off] += x;
      // NaN propagates (parity with the device scatter path and numpy
      // fallback): a plain `x < mn` comparison would silently skip NaN
      if (x != x || x < mn[off]) mn[off] = x;
      if (x != x || x > mx[off]) mx[off] = x;
    }
  }
  return folded;
}

}  // extern "C"
