// pyassemble — C-level reassembly of shredded nested columns into python
// row values (dicts / lists / scalars, None for null).
//
// The native parsers (json_parser.cpp, avro_parser.cpp) shred nested
// payloads into typed leaf buffers + presence bytes + list offsets at
// ~4.5M rows/s; what bounded nested decode after that was the PYTHON
// reassembly — per-row dict building in the wrapper ran ~650ns/row even
// through generated dict-literal comprehensions.  This helper walks the
// same buffers with the CPython C API instead (PyDict_New +
// PyDict_SetItem against pre-built interned keys, PyLong/PyFloat straight
// off the typed buffers), the same optional-Python-path pattern as
// interner.cpp's INTERN_HAVE_PYTHON build.
//
// Must be loaded through ctypes.PyDLL (keeps the GIL — every call here
// manipulates Python objects).  The node description is parser-agnostic:
// the wrapper passes whatever jp_col_* / ap_col_* pointers the schema
// tree resolves to, so one assembler serves both formats.
//
// Node types: 0 i64 | 1 f64 | 2 bool | 3 object (PyObject** — the data
// pointer of a materialized numpy object array, e.g. decoded strings) |
// 4 struct (valid = presence, children = fields) | 5 list (offsets =
// per-entry element ranges, single child indexed per ELEMENT — packed
// scalar lists pass the list node's own element buffers as that child).

#include <Python.h>

#include <cstdint>
#include <vector>

namespace {

struct NodeView {
  int type;
  const void* data;
  const uint8_t* valid;
  const uint64_t* offsets;  // lists only
  PyObject* key;            // owned by pa_struct_rows' keys vector
  std::vector<int> kids;
};

// one value of node ni at entry index r (row, or element for nodes under
// a list); returns a NEW reference, nullptr on error
PyObject* build(const std::vector<NodeView>& nodes, int ni, uint64_t r) {
  const NodeView& nd = nodes[ni];
  if (nd.valid && !nd.valid[r]) Py_RETURN_NONE;
  switch (nd.type) {
    case 0:
      return PyLong_FromLongLong(((const int64_t*)nd.data)[r]);
    case 1:
      return PyFloat_FromDouble(((const double*)nd.data)[r]);
    case 2: {
      PyObject* o = ((const uint8_t*)nd.data)[r] ? Py_True : Py_False;
      Py_INCREF(o);
      return o;
    }
    case 3: {
      PyObject* o = ((PyObject* const*)nd.data)[r];
      Py_INCREF(o);
      return o;
    }
    case 4: {
      // presized like CPython's own BUILD_MAP — PyDict_New starts with
      // the shared empty-keys object and pays a resize on first insert
      PyObject* d = _PyDict_NewPresized((Py_ssize_t)nd.kids.size());
      if (!d) return nullptr;
      for (int k : nd.kids) {
        PyObject* v = build(nodes, k, r);
        if (!v || PyDict_SetItem(d, nodes[k].key, v) < 0) {
          Py_XDECREF(v);
          Py_DECREF(d);
          return nullptr;
        }
        Py_DECREF(v);
      }
      return d;
    }
    case 5: {
      uint64_t a = nd.offsets[r], b = nd.offsets[r + 1];
      PyObject* lst = PyList_New((Py_ssize_t)(b - a));
      if (!lst) return nullptr;
      for (uint64_t e = a; e < b; e++) {
        PyObject* v = build(nodes, nd.kids[0], e);
        if (!v) {
          Py_DECREF(lst);
          return nullptr;
        }
        PyList_SET_ITEM(lst, (Py_ssize_t)(e - a), v);  // steals
      }
      return lst;
    }
  }
  Py_RETURN_NONE;
}

}  // namespace

extern "C" {

// Assemble one nested column's python rows.  Parallel node arrays in any
// order with parents[i] -1 for the single root; data[i]/valids[i]/
// offsets[i] as the node type requires (see header comment).  Returns a
// NEW PyList of n row values, or nullptr with a python error set (ctypes
// py_object restype surfaces it).
PyObject* pa_rows(int nnodes, const int* types, const int* parents,
                  const char** names, void* const* data,
                  const uint8_t* const* valids,
                  const uint64_t* const* offsets, uint64_t n) {
  std::vector<NodeView> nodes(nnodes);
  int root = -1;
  bool ok = true;
  for (int i = 0; i < nnodes; i++) {
    NodeView& nd = nodes[i];
    nd.type = types[i];
    nd.data = data[i];
    nd.valid = valids[i];
    nd.offsets = offsets[i];
    nd.key = PyUnicode_FromString(names[i]);
    if (!nd.key) ok = false;
    if (parents[i] < 0)
      root = i;
    else
      nodes[parents[i]].kids.push_back(i);
  }
  PyObject* out = nullptr;
  if (ok && root >= 0) {
    out = PyList_New((Py_ssize_t)n);
    if (out) {
      for (uint64_t r = 0; r < n; r++) {
        PyObject* v = build(nodes, root, r);
        if (!v) {
          Py_DECREF(out);
          out = nullptr;
          break;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)r, v);  // steals
      }
    }
  } else if (ok) {
    PyErr_SetString(PyExc_ValueError, "pa_rows: no root node");
  }
  for (auto& nd : nodes) Py_XDECREF(nd.key);
  return out;
}

}  // extern "C"
