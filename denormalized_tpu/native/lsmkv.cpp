// lsmkv — log-structured KV store backing checkpoints.
//
// Native (C++) counterpart of the reference's SlateDB state backend
// (crates/core/src/state_backend/slatedb.rs:28-92: an LSM on object storage
// with async fire-and-forget put, awaited get, close) and the dormant
// RocksDB backend (state_backend/rocksdb_backend.rs).  Design:
//
//   - append-only segment files  seg-<n>.log  of records:
//       [u32 crc][u32 klen][u32 vlen][u8 tombstone][key][value]
//     crc32 covers klen..value.  Torn tails are truncated on recovery.
//   - in-memory index: key -> (segment, offset, vlen) built by replaying
//     segments in order on open.
//   - writes go to the active segment; fsync on flush()/close() (puts are
//     fire-and-forget at the API level, like the reference's spawned put).
//   - compact() rewrites live entries into a fresh segment and unlinks old
//     ones once the index is swapped.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).  All calls are
// thread-safe behind one mutex — the checkpoint path is not contended.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

uint32_t crc32(const uint8_t* data, size_t len, uint32_t crc = 0) {
  // C++11 magic static: thread-safe one-time init (plain `static bool`
  // guards race when two stores are used from different threads)
  static const Crc32Table table;
  crc = ~crc;
  for (size_t i = 0; i < len; i++)
    crc = table.t[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Entry {
  uint32_t segment;
  uint64_t offset;  // offset of the value payload in the segment file
  uint32_t vlen;
};

struct Store {
  std::string dir;
  std::map<std::string, Entry> index;
  FILE* active = nullptr;
  uint32_t active_seg = 0;
  uint64_t active_size = 0;
  std::mutex mu;

  std::string seg_path(uint32_t n) const {
    char buf[32];
    snprintf(buf, sizeof buf, "/seg-%08u.log", n);
    return dir + buf;
  }
};

bool replay_segment(Store* s, uint32_t seg) {
  std::string path = s->seg_path(seg);
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  uint64_t off = 0;
  std::vector<uint8_t> buf;
  for (;;) {
    uint8_t hdr[13];
    if (fread(hdr, 1, 13, f) != 13) break;
    uint32_t crc, klen, vlen;
    memcpy(&crc, hdr, 4);
    memcpy(&klen, hdr + 4, 4);
    memcpy(&vlen, hdr + 8, 4);
    uint8_t tomb = hdr[12];
    if (klen > (1u << 24) || vlen > (1u << 30)) break;  // corrupt header
    buf.resize(9 + klen + vlen);
    memcpy(buf.data(), hdr + 4, 9);
    if (fread(buf.data() + 9, 1, klen + vlen, f) != klen + vlen) break;
    if (crc32(buf.data(), buf.size()) != crc) break;  // torn/corrupt tail
    std::string key(reinterpret_cast<char*>(buf.data() + 9), klen);
    if (tomb) {
      s->index.erase(key);
    } else {
      s->index[key] = Entry{seg, off + 13 + klen, vlen};
    }
    off += 13 + klen + vlen;
  }
  // a torn tail is simply ignored: writers always append to a FRESH segment
  // after recovery (lsm_open bumps active_seg), so the tail is never
  // extended and CRC replay keeps skipping it
  fclose(f);
  return true;
}

int append_record(Store* s, const std::string& key, const uint8_t* val,
                  uint32_t vlen, bool tombstone) {
  uint32_t klen = (uint32_t)key.size();
  std::vector<uint8_t> rec(13 + klen + vlen);
  memcpy(rec.data() + 4, &klen, 4);
  memcpy(rec.data() + 8, &vlen, 4);
  rec[12] = tombstone ? 1 : 0;
  memcpy(rec.data() + 13, key.data(), klen);
  if (vlen) memcpy(rec.data() + 13 + klen, val, vlen);
  uint32_t crc = crc32(rec.data() + 4, rec.size() - 4);
  memcpy(rec.data(), &crc, 4);
  if (fwrite(rec.data(), 1, rec.size(), s->active) != rec.size()) return -1;
  uint64_t payload_off = s->active_size + 13 + klen;
  if (tombstone) {
    s->index.erase(key);
  } else {
    s->index[key] = Entry{s->active_seg, payload_off, vlen};
  }
  s->active_size += rec.size();
  return 0;
}

}  // namespace

extern "C" {

void* lsm_open(const char* dir) {
  mkdir(dir, 0755);
  Store* s = new Store();
  s->dir = dir;
  // discover segments
  std::vector<uint32_t> segs;
  if (DIR* d = opendir(dir)) {
    while (dirent* e = readdir(d)) {
      unsigned n;
      if (sscanf(e->d_name, "seg-%08u.log", &n) == 1) segs.push_back(n);
    }
    closedir(d);
  }
  std::sort(segs.begin(), segs.end());
  s->active_seg = segs.empty() ? 0 : segs.back();
  for (uint32_t seg : segs) replay_segment(s, seg);
  // new writers append to a fresh segment to avoid truncation races
  s->active_seg = segs.empty() ? 0 : segs.back() + 1;
  s->active_size = 0;
  s->active = fopen(s->seg_path(s->active_seg).c_str(), "ab");
  if (!s->active) {
    delete s;
    return nullptr;
  }
  return s;
}

int lsm_put(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val,
            uint32_t vlen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return append_record(s, std::string((const char*)key, klen), val, vlen,
                       false);
}

int lsm_delete(void* h, const uint8_t* key, uint32_t klen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return append_record(s, std::string((const char*)key, klen), nullptr, 0,
                       true);
}

// Returns vlen and writes a malloc'd buffer into *val (caller must
// lsm_free it); returns -1 if the key is absent.
int64_t lsm_get(void* h, const uint8_t* key, uint32_t klen, uint8_t** val) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(std::string((const char*)key, klen));
  if (it == s->index.end()) return -1;
  const Entry& e = it->second;
  uint8_t* out = (uint8_t*)malloc(e.vlen ? e.vlen : 1);
  if (e.segment == s->active_seg) fflush(s->active);
  FILE* f = fopen(s->seg_path(e.segment).c_str(), "rb");
  if (!f) {
    free(out);
    return -1;
  }
  fseeko(f, (off_t)e.offset, SEEK_SET);
  size_t got = fread(out, 1, e.vlen, f);
  fclose(f);
  if (got != e.vlen) {
    free(out);
    return -1;
  }
  *val = out;
  return (int64_t)e.vlen;
}

void lsm_free(uint8_t* p) { free(p); }

int lsm_flush(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (fflush(s->active) != 0) return -1;
  return fsync(fileno(s->active));
}

// number of live keys
uint64_t lsm_count(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->index.size();
}

// list keys as \n-joined buffer (malloc'd); for debugging/tests
int64_t lsm_keys(void* h, uint8_t** out) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string all;
  for (auto& kv : s->index) {
    all += kv.first;
    all += '\n';
  }
  uint8_t* buf = (uint8_t*)malloc(all.size() ? all.size() : 1);
  memcpy(buf, all.data(), all.size());
  *out = buf;
  return (int64_t)all.size();
}

// rewrite live entries into a fresh segment, unlink old ones
int lsm_compact(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  fflush(s->active);
  uint32_t new_seg = s->active_seg + 1;
  FILE* nf = fopen(s->seg_path(new_seg).c_str(), "ab");
  if (!nf) return -1;
  std::map<std::string, Entry> new_index;
  uint64_t new_size = 0;
  for (auto& kv : s->index) {
    const Entry& e = kv.second;
    std::vector<uint8_t> val(e.vlen);
    FILE* f = fopen(s->seg_path(e.segment).c_str(), "rb");
    if (!f) continue;
    fseeko(f, (off_t)e.offset, SEEK_SET);
    size_t got = fread(val.data(), 1, e.vlen, f);
    fclose(f);
    if (got != e.vlen) continue;
    uint32_t klen = (uint32_t)kv.first.size();
    std::vector<uint8_t> rec(13 + klen + e.vlen);
    memcpy(rec.data() + 4, &klen, 4);
    memcpy(rec.data() + 8, &e.vlen, 4);
    rec[12] = 0;
    memcpy(rec.data() + 13, kv.first.data(), klen);
    memcpy(rec.data() + 13 + klen, val.data(), e.vlen);
    uint32_t crc = crc32(rec.data() + 4, rec.size() - 4);
    memcpy(rec.data(), &crc, 4);
    fwrite(rec.data(), 1, rec.size(), nf);
    new_index[kv.first] = Entry{new_seg, new_size + 13 + klen, e.vlen};
    new_size += rec.size();
  }
  fflush(nf);
  fsync(fileno(nf));
  // swap
  uint32_t old_active = s->active_seg;
  fclose(s->active);
  s->active = nf;
  s->active_seg = new_seg;
  s->active_size = new_size;
  s->index.swap(new_index);
  // unlink all older segments
  for (uint32_t seg = 0; seg <= old_active; seg++) {
    unlink(s->seg_path(seg).c_str());
  }
  return 0;
}

void lsm_close(void* h) {
  Store* s = static_cast<Store*>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->active) {
      fflush(s->active);
      fsync(fileno(s->active));
      fclose(s->active);
    }
  }
  delete s;
}

}  // extern "C"
