"""Build-on-first-use for the native (C++) components: compiles
``<name>.cpp`` beside this file into ``<name>.so`` with g++ and loads it
with ctypes.  The rebuild trigger is a content hash of the source recorded
in a sidecar file — NOT mtimes, which a fresh git checkout resets to the
same instant for source and any stray binary, silently shipping a stale
build.  Raises on failure — callers decide whether a pure-Python fallback
exists."""

from __future__ import annotations

import ctypes
import hashlib
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).resolve().parent
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL] = {}


def load(
    name: str, extra_flags: list[str] | None = None, *, pydll: bool = False
) -> ctypes.CDLL:
    """``pydll=True`` loads through :class:`ctypes.PyDLL` (calls keep the
    GIL) — REQUIRED for libraries that touch the CPython API
    (pyassemble.cpp): a plain-CDLL handle to such a library would release
    the GIL around calls that manipulate PyObjects and crash the
    interpreter.  The cache keys on the loader kind so a PyDLL library
    can never be served a previously-cached CDLL handle or vice versa."""
    key = f"{name}|pydll" if pydll else name
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
        src = _DIR / f"{name}.cpp"
        so = _DIR / f"{name}.so"
        stamp = _DIR / f"{name}.so.srchash"
        # local quoted includes participate in the rebuild hash — a header
        # edit must rebuild every .so that inlines it; the scan follows
        # the quoted-include closure recursively
        def hash_with_includes(path: Path, seen: set) -> bytes:
            if path in seen or not path.exists():
                return b""
            seen.add(path)
            data = path.read_bytes()
            out = data
            for line in data.splitlines():
                line = line.strip().replace(b'#include"', b'#include "')
                if line.startswith(b'#include "'):
                    out += hash_with_includes(
                        _DIR / line.split(b'"')[1].decode(), seen
                    )
            return out

        want = hashlib.sha256(
            hash_with_includes(src, set())
            + repr(sorted(extra_flags or [])).encode()
        ).hexdigest()
        have = stamp.read_text().strip() if stamp.exists() else ""
        if not so.exists() or have != want:
            cmd = [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                str(src), "-o", str(so),
            ] + (extra_flags or [])
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native build of {name} failed:\n{proc.stderr[-2000:]}"
                )
            stamp.write_text(want)
        lib = (ctypes.PyDLL if pydll else ctypes.CDLL)(str(so))
        _CACHE[key] = lib
        return lib
