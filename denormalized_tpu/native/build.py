"""Build-on-first-use for the native (C++) components: compiles
``<name>.cpp`` beside this file into ``<name>.so`` with g++ and loads it
with ctypes.  The rebuild trigger is a content hash of the source recorded
in a sidecar file — NOT mtimes, which a fresh git checkout resets to the
same instant for source and any stray binary, silently shipping a stale
build.  Raises on failure — callers decide whether a pure-Python fallback
exists."""

from __future__ import annotations

import ctypes
import hashlib
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).resolve().parent
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL] = {}

#: warning surface every native build compiles under.  The gate test
#: (tests/test_native_build_gate.py) compiles with these PLUS -Werror,
#: so the committed tree is warning-clean; the production build keeps
#: them non-fatal (a future compiler inventing a new warning must not
#: take the engine down at first use).
WARN_FLAGS = ["-Wall", "-Wextra", "-Wshadow", "-Wconversion"]

#: ``sanitize=`` kinds -> compile/link flags.  ``thread`` is what
#: tests/test_native_sanitizers.py uses for the TSan hammer coverage;
#: address covers the single-thread memory-safety runs.
SANITIZE_FLAGS = {
    "thread": ["-fsanitize=thread", "-g"],
    "address": ["-fsanitize=address,undefined", "-g"],
}


def _flavor_suffix(sanitize: str | None) -> str:
    flavor = {"thread": ".tsan", "address": ".asan"}.get(sanitize or "", "")
    if sanitize and not flavor:
        raise ValueError(
            f"unknown sanitize kind {sanitize!r} "
            f"(expected one of {sorted(SANITIZE_FLAGS)})"
        )
    return flavor


def compile(
    name: str,
    extra_flags: list[str] | None = None,
    *,
    sanitize: str | None = None,
) -> Path:
    """Compile ``<name>.cpp`` (if stale) and return the .so path WITHOUT
    dlopen'ing it.  ``sanitize="thread"|"address"`` builds a
    separately-named, separately-stamped flavor (``<name>.tsan.so`` /
    ``<name>.asan.so``) with the matching ``-fsanitize=`` flags — those
    artifacts can only be dlopen'd with the sanitizer runtime preloaded
    (LD_PRELOAD=libtsan.so...), which is exactly why this step is split
    from :func:`load`: the sanitizer test harness compiles flavors here
    and loads them in a preloaded subprocess, while production loads
    stay unflavored.  Callers must hold no assumption about which thread
    builds first: the compile is serialized under the module lock."""
    flavor = _flavor_suffix(sanitize)
    with _LOCK:
        return _compile_locked(name, flavor, extra_flags, sanitize)


def _compile_locked(
    name: str, flavor: str, extra_flags, sanitize: str | None
) -> Path:
    src = _DIR / f"{name}.cpp"
    so = _DIR / f"{name}{flavor}.so"
    stamp = _DIR / f"{name}{flavor}.so.srchash"
    # local quoted includes participate in the rebuild hash — a header
    # edit must rebuild every .so that inlines it; the scan follows
    # the quoted-include closure recursively
    def hash_with_includes(path: Path, seen: set) -> bytes:
        if path in seen or not path.exists():
            return b""
        seen.add(path)
        data = path.read_bytes()
        out = data
        for line in data.splitlines():
            line = line.strip().replace(b'#include"', b'#include "')
            if line.startswith(b'#include "'):
                out += hash_with_includes(
                    _DIR / line.split(b'"')[1].decode(), seen
                )
        return out

    build_flags = (
        WARN_FLAGS
        + (SANITIZE_FLAGS[sanitize] if sanitize else [])
        + (extra_flags or [])
    )
    want = hashlib.sha256(
        hash_with_includes(src, set())
        + repr(sorted(build_flags)).encode()
    ).hexdigest()
    have = stamp.read_text().strip() if stamp.exists() else ""
    if not so.exists() or have != want:
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            str(src), "-o", str(so),
        ] + build_flags
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build of {name} failed:\n{proc.stderr[-2000:]}"
            )
        stamp.write_text(want)
    return so


def load(
    name: str,
    extra_flags: list[str] | None = None,
    *,
    pydll: bool = False,
) -> ctypes.CDLL:
    """``pydll=True`` loads through :class:`ctypes.PyDLL` (calls keep the
    GIL) — REQUIRED for libraries that touch the CPython API
    (pyassemble.cpp): a plain-CDLL handle to such a library would release
    the GIL around calls that manipulate PyObjects and crash the
    interpreter.  The cache keys on the loader kind so a PyDLL library
    can never be served a previously-cached CDLL handle or vice versa."""
    key = f"{name}|pydll" if pydll else name
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
        so = _compile_locked(name, "", extra_flags, None)
        lib = (ctypes.PyDLL if pydll else ctypes.CDLL)(str(so))
        _CACHE[key] = lib
        return lib
