// json_parser — one-pass JSON-objects → columnar buffers.
//
// Native ingest/decode path: the reference decodes Kafka JSON payloads by
// concatenating them into a JSON array and running arrow-json's reader
// (crates/core/src/formats/decoders/json.rs:11-49, native Rust/C via Arrow).
// Ours parses each payload directly into typed columnar buffers in a single
// pass — no intermediate DOM, no per-row Python objects.  Flat schemas only
// (the Python fallback handles nested structs/lists).
//
// C ABI for ctypes.  Column types: 0=int64, 1=float64, 2=bool, 3=string.
// Unknown keys are skipped (balanced for nested values); missing keys and
// JSON nulls set validity 0.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "str_dict.hpp"

namespace {

struct Col {
  std::string name;
  int type;
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<uint8_t> b;
  std::vector<uint8_t> valid;
  std::vector<uint8_t> str_bytes;
  std::vector<uint64_t> str_offsets;  // nrows+1
  StrDict dict;
};

struct Parser {
  std::vector<Col> cols;
  uint64_t nrows = 0;
  std::string error;
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == (uint8_t)c) {
      p++;
      return true;
    }
    fail = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == (uint8_t)c;
  }
};

// parse a JSON string (after the opening quote) into out; handles escapes
bool parse_string(Cursor& c, std::string& out) {
  out.clear();
  while (c.p < c.end) {
    uint8_t ch = *c.p++;
    if (ch == '"') return true;
    if (ch != '\\') {
      out.push_back((char)ch);
      continue;
    }
    if (c.p >= c.end) break;
    uint8_t esc = *c.p++;
    switch (esc) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        auto hex4 = [&](unsigned& cp) -> bool {
          if (c.end - c.p < 4) return false;
          cp = 0;
          for (int i = 0; i < 4; i++) {
            uint8_t h = *c.p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          return true;
        };
        unsigned cp;
        if (!hex4(cp)) return false;
        // surrogate pair → combined code point (json.dumps ensure_ascii
        // emits all non-BMP chars this way)
        if (cp >= 0xD800 && cp <= 0xDBFF && c.end - c.p >= 6 &&
            c.p[0] == '\\' && c.p[1] == 'u') {
          c.p += 2;
          unsigned lo;
          if (!hex4(lo)) return false;
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          } else {
            cp = 0xFFFD;  // lone high surrogate → replacement char
            // re-emit the second escape as its own char below? simplest:
            // treat `lo` as an independent BMP code point
            unsigned cp2 = (lo >= 0xD800 && lo <= 0xDFFF) ? 0xFFFD : lo;
            // emit cp now, then fall through to emit cp2
            auto emit = [&](unsigned x) {
              if (x < 0x80) out.push_back((char)x);
              else if (x < 0x800) {
                out.push_back((char)(0xC0 | (x >> 6)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else if (x < 0x10000) {
                out.push_back((char)(0xE0 | (x >> 12)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else {
                out.push_back((char)(0xF0 | (x >> 18)));
                out.push_back((char)(0x80 | ((x >> 12) & 0x3F)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              }
            };
            emit(cp);
            emit(cp2);
            break;
          }
        } else if (cp >= 0xD800 && cp <= 0xDFFF) {
          cp = 0xFFFD;  // lone surrogate
        }
        if (cp < 0x80) out.push_back((char)cp);
        else if (cp < 0x800) {
          out.push_back((char)(0xC0 | (cp >> 6)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
          out.push_back((char)(0xE0 | (cp >> 12)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else {
          out.push_back((char)(0xF0 | (cp >> 18)));
          out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default: return false;
    }
  }
  return false;
}

// copy one numeric token into a NUL-terminated buffer, advancing the
// cursor past it; returns the token length (0 = no token).  Scanning stops
// at c.end or the first non-number char, so strtoll/strtod never touch the
// (non-NUL-terminated) arena directly.  Tokens longer than the stack
// buffer spill into `big` (rare: legal JSON numbers of arbitrary
// precision) — *out points at whichever buffer holds the token.
size_t scan_number(Cursor& c, char* buf, size_t bufsize, std::string& big,
                   const char** out) {
  size_t n = 0;
  big.clear();
  while (c.p < c.end) {
    uint8_t ch = *c.p;
    bool numchar = (ch >= '0' && ch <= '9') || ch == '-' || ch == '+' ||
                   ch == '.' || ch == 'e' || ch == 'E';
    if (!numchar) break;
    if (n + 1 < bufsize) {
      buf[n] = (char)ch;
    } else {
      if (big.empty()) big.assign(buf, n);
      big.push_back((char)ch);
    }
    n++;
    c.p++;
  }
  if (!big.empty()) {
    *out = big.c_str();
    return n;
  }
  buf[n] = '\0';
  *out = buf;
  return n;
}

// skip any JSON value (for unknown keys)
bool skip_value(Cursor& c) {
  c.ws();
  if (c.p >= c.end) return false;
  uint8_t ch = *c.p;
  if (ch == '"') {
    c.p++;
    std::string tmp;
    return parse_string(c, tmp);
  }
  if (ch == '{' || ch == '[') {
    uint8_t open = ch, close = (ch == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    while (c.p < c.end) {
      uint8_t x = *c.p++;
      if (in_str) {
        if (x == '\\') { if (c.p < c.end) c.p++; }
        else if (x == '"') in_str = false;
      } else if (x == '"') in_str = true;
      else if (x == open) depth++;
      else if (x == close) {
        if (--depth == 0) return true;
      }
    }
    return false;
  }
  // number / true / false / null
  while (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
         *c.p != ' ' && *c.p != '\n' && *c.p != '\t' && *c.p != '\r')
    c.p++;
  return true;
}

}  // namespace

extern "C" {

void* jp_create(int ncols, const char** names, const int* types) {
  Parser* p = new Parser();
  p->cols.resize(ncols);
  for (int i = 0; i < ncols; i++) {
    p->cols[i].name = names[i];
    p->cols[i].type = types[i];
    p->cols[i].str_offsets.push_back(0);
  }
  return p;
}

void jp_clear(void* h) {
  Parser* p = static_cast<Parser*>(h);
  p->nrows = 0;
  p->error.clear();
  for (auto& c : p->cols) {
    c.i64.clear();
    c.f64.clear();
    c.b.clear();
    c.valid.clear();
    c.str_bytes.clear();
    c.str_offsets.assign(1, 0);
  }
}

// returns 0 on success, -1 on parse error (see jp_error)
int jp_parse(void* h, const uint8_t* data, const uint64_t* offsets,
             uint64_t nrows) {
  Parser* p = static_cast<Parser*>(h);
  const int ncols = (int)p->cols.size();
  std::string key, sval;
  std::vector<uint8_t> seen(ncols);

  for (uint64_t r = 0; r < nrows; r++) {
    Cursor c{data + offsets[r], data + offsets[r + 1]};
    std::fill(seen.begin(), seen.end(), 0);
    if (!c.eat('{')) {
      p->error = "expected '{' at row " + std::to_string(r);
      return -1;
    }
    if (!c.peek('}')) {
      for (;;) {
        if (!c.eat('"')) break;
        if (!parse_string(c, key)) { c.fail = true; break; }
        if (!c.eat(':')) break;
        // find column
        int ci = -1;
        for (int i = 0; i < ncols; i++)
          if (p->cols[i].name == key) { ci = i; break; }
        if (ci < 0) {
          if (!skip_value(c)) { c.fail = true; break; }
        } else {
          Col& col = p->cols[ci];
          if (seen[ci]) {
            // duplicate key: last-wins (match json.loads dict semantics) —
            // drop the value stored for the earlier occurrence
            col.valid.pop_back();
            switch (col.type) {
              case 0: col.i64.pop_back(); break;
              case 1: col.f64.pop_back(); break;
              case 2: col.b.pop_back(); break;
              case 3:
                col.str_offsets.pop_back();
                col.str_bytes.resize(col.str_offsets.back());
                break;
            }
          }
          seen[ci] = 1;
          c.ws();
          bool is_null = false;
          if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
            c.p += 4;
            is_null = true;
          }
          if (is_null) {
            col.valid.push_back(0);
            switch (col.type) {
              case 0: col.i64.push_back(0); break;
              case 1: col.f64.push_back(0); break;
              case 2: col.b.push_back(0); break;
              case 3: col.str_offsets.push_back(col.str_bytes.size()); break;
            }
          } else {
            switch (col.type) {
              // numeric tokens are copied into a bounded NUL-terminated
              // local buffer first: strtoll/strtod scan until NUL, and the
              // fetch arena is NOT NUL-terminated — a payload truncated
              // mid-number at the arena's end would let them read past it
              case 0: {
                char numbuf[48];
                std::string big;
                const char* tok = nullptr;
                size_t tl = scan_number(c, numbuf, sizeof numbuf, big, &tok);
                char* endp = nullptr;
                long long v = tl ? strtoll(tok, &endp, 10) : 0;
                // partial consumption (e.g. "1e5" on an int column) must
                // fail the row, not silently truncate to 1
                if (tl == 0 || endp != tok + tl) { c.fail = true; }
                col.i64.push_back(v);
                col.valid.push_back(1);
                break;
              }
              case 1: {
                char numbuf[48];
                std::string big;
                const char* tok = nullptr;
                size_t tl = scan_number(c, numbuf, sizeof numbuf, big, &tok);
                char* endp = nullptr;
                double v = tl ? strtod(tok, &endp) : 0.0;
                if (tl == 0 || endp != tok + tl) { c.fail = true; }
                col.f64.push_back(v);
                col.valid.push_back(1);
                break;
              }
              case 2: {
                c.ws();
                if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) {
                  c.p += 4;
                  col.b.push_back(1);
                } else if (c.end - c.p >= 5 && memcmp(c.p, "false", 5) == 0) {
                  c.p += 5;
                  col.b.push_back(0);
                } else {
                  c.fail = true;
                  col.b.push_back(0);
                }
                col.valid.push_back(1);
                break;
              }
              case 3: {
                if (!c.eat('"')) { c.fail = true; break; }
                if (!parse_string(c, sval)) { c.fail = true; break; }
                col.str_bytes.insert(col.str_bytes.end(), sval.begin(),
                                     sval.end());
                col.str_offsets.push_back(col.str_bytes.size());
                col.valid.push_back(1);
                break;
              }
            }
          }
        }
        if (c.fail) break;
        c.ws();
        if (c.peek(',')) { c.p++; continue; }
        break;
      }
      if (!c.fail) c.eat('}');
    } else {
      c.p++;  // consume '}'
    }
    if (c.fail) {
      p->error = "malformed JSON at row " + std::to_string(r);
      return -1;
    }
    // missing keys → null
    for (int i = 0; i < ncols; i++) {
      if (!seen[i]) {
        Col& col = p->cols[i];
        col.valid.push_back(0);
        switch (col.type) {
          case 0: col.i64.push_back(0); break;
          case 1: col.f64.push_back(0); break;
          case 2: col.b.push_back(0); break;
          case 3: col.str_offsets.push_back(col.str_bytes.size()); break;
        }
      }
    }
    p->nrows++;
  }
  return 0;
}

const char* jp_error(void* h) {
  return static_cast<Parser*>(h)->error.c_str();
}

uint64_t jp_nrows(void* h) { return static_cast<Parser*>(h)->nrows; }

const int64_t* jp_col_i64(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].i64.data();
}
const double* jp_col_f64(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].f64.data();
}
const uint8_t* jp_col_bool(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].b.data();
}
const uint8_t* jp_col_valid(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].valid.data();
}
const uint8_t* jp_col_str_bytes(void* h, int col, uint64_t* nbytes) {
  Col& c = static_cast<Parser*>(h)->cols[col];
  *nbytes = c.str_bytes.size();
  return c.str_bytes.data();
}
const uint64_t* jp_col_str_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].str_offsets.data();
}
int64_t jp_col_str_dict(void* h, int col) {
  Parser* p = static_cast<Parser*>(h);
  Col& c = p->cols[col];
  return build_str_dict(c.str_bytes, c.str_offsets, p->nrows, c.dict);
}
const int32_t* jp_col_str_dict_codes(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].dict.codes.data();
}
const uint8_t* jp_col_str_dict_bytes(void* h, int col, uint64_t* nbytes) {
  StrDict& d = static_cast<Parser*>(h)->cols[col].dict;
  *nbytes = d.bytes.size();
  return d.bytes.data();
}
const uint64_t* jp_col_str_dict_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].dict.offsets.data();
}

void jp_destroy(void* h) { delete static_cast<Parser*>(h); }

}  // extern "C"
