// json_parser — one-pass JSON-objects → columnar buffers.
//
// Native ingest/decode path: the reference decodes Kafka JSON payloads by
// concatenating them into a JSON array and running arrow-json's reader
// (crates/core/src/formats/decoders/json.rs:11-49, native Rust/C via Arrow).
// Ours parses each payload directly into typed columnar buffers in a single
// pass — no intermediate DOM, no per-row Python objects.  Flat schemas only
// (the Python fallback handles nested structs/lists).
//
// C ABI for ctypes.  Column types: 0=int64, 1=float64, 2=bool, 3=string.
// Unknown keys are skipped (balanced for nested values); missing keys and
// JSON nulls set validity 0.

#include <algorithm>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "str_dict.hpp"

namespace {

struct Col {
  std::string name;
  int type;
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<uint8_t> b;
  std::vector<uint8_t> valid;
  std::vector<uint8_t> str_bytes;
  std::vector<uint64_t> str_offsets;  // nrows+1
  StrDict dict;
};

// Adaptive row layout: streaming producers emit a fixed record shape, so
// after one general-path row parse we capture the exact inter-value byte
// runs — `{"key":`, `,"key2":`, …, the trailing `}` — including whatever
// fixed whitespace style the producer uses (serde_json compact,
// json.dumps `", "`/`": "`, …).  Subsequent rows then reduce to a few
// memcmps plus direct value parses: no per-key string materialization, no
// column-name lookup, no whitespace scanning.  Any mismatch rolls the row
// back and reparses it on the general path (which re-learns the layout),
// so this is purely a fast path — semantics are identical.
struct Layout {
  bool valid = false;
  std::vector<std::string> tok;  // tok[i]: bytes preceding value i
  std::vector<int> col;          // column index of value i (-1: skip)
  std::vector<int> missing;      // schema columns absent from the row
  std::string tail;              // bytes after the last value
  int fail_streak = 0;
};

struct Parser {
  std::vector<Col> cols;
  uint64_t nrows = 0;
  std::string error;
  Layout layout;
  int adopt_cooldown = 0;  // >0: layout adoption suppressed (see jp_parse)
  // per-row discovery scratch (value spans, matched columns), filled by
  // the general path so a successful row can become the new layout
  std::vector<size_t> d_vs, d_ve;
  std::vector<int> d_col;
  bool d_ok = false;
  // general-path per-row scratch, hoisted here so rows that stay on the
  // general path don't pay per-row heap allocations
  std::string g_key, g_sval;
  std::vector<uint8_t> g_seen;
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == (uint8_t)c) {
      p++;
      return true;
    }
    fail = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == (uint8_t)c;
  }
};

// parse a JSON string (after the opening quote) into out; handles escapes
bool parse_string(Cursor& c, std::string& out) {
  out.clear();
  while (c.p < c.end) {
    uint8_t ch = *c.p++;
    if (ch == '"') return true;
    if (ch != '\\') {
      out.push_back((char)ch);
      continue;
    }
    if (c.p >= c.end) break;
    uint8_t esc = *c.p++;
    switch (esc) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        auto hex4 = [&](unsigned& cp) -> bool {
          if (c.end - c.p < 4) return false;
          cp = 0;
          for (int i = 0; i < 4; i++) {
            uint8_t h = *c.p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          return true;
        };
        unsigned cp;
        if (!hex4(cp)) return false;
        // surrogate pair → combined code point (json.dumps ensure_ascii
        // emits all non-BMP chars this way)
        if (cp >= 0xD800 && cp <= 0xDBFF && c.end - c.p >= 6 &&
            c.p[0] == '\\' && c.p[1] == 'u') {
          c.p += 2;
          unsigned lo;
          if (!hex4(lo)) return false;
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          } else {
            cp = 0xFFFD;  // lone high surrogate → replacement char
            // re-emit the second escape as its own char below? simplest:
            // treat `lo` as an independent BMP code point
            unsigned cp2 = (lo >= 0xD800 && lo <= 0xDFFF) ? 0xFFFD : lo;
            // emit cp now, then fall through to emit cp2
            auto emit = [&](unsigned x) {
              if (x < 0x80) out.push_back((char)x);
              else if (x < 0x800) {
                out.push_back((char)(0xC0 | (x >> 6)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else if (x < 0x10000) {
                out.push_back((char)(0xE0 | (x >> 12)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else {
                out.push_back((char)(0xF0 | (x >> 18)));
                out.push_back((char)(0x80 | ((x >> 12) & 0x3F)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              }
            };
            emit(cp);
            emit(cp2);
            break;
          }
        } else if (cp >= 0xD800 && cp <= 0xDFFF) {
          cp = 0xFFFD;  // lone surrogate
        }
        if (cp < 0x80) out.push_back((char)cp);
        else if (cp < 0x800) {
          out.push_back((char)(0xC0 | (cp >> 6)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
          out.push_back((char)(0xE0 | (cp >> 12)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else {
          out.push_back((char)(0xF0 | (cp >> 18)));
          out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default: return false;
    }
  }
  return false;
}

// End of the numeric token starting at p (same charset the old
// strtol-based scanner used); std::from_chars then converts straight from
// the arena — no copy, no NUL termination needed, exactly-rounded doubles.
// The full token must be consumed or the row fails (so "1e5" on an int
// column cannot silently truncate to 1, and "inf"/"nan" — which
// from_chars would accept but JSON forbids — yield an empty token).
inline const uint8_t* num_token_end(const uint8_t* p, const uint8_t* e) {
  while (p < e) {
    uint8_t ch = *p;
    if ((ch >= '0' && ch <= '9') || ch == '-' || ch == '+' || ch == '.' ||
        ch == 'e' || ch == 'E')
      p++;
    else
      break;
  }
  return p;
}

// out-of-range tokens keep the historical strtoll/strtod semantics
// (clamp to LLONG_MIN/MAX; overflow to ±inf, underflow to ±0) instead of
// failing the batch — json.loads accepts 1e999 and 20-digit ints, so the
// parser must too.  Cold path: copies the token for NUL termination.
bool num_range_fallback_i64(const uint8_t* q, const uint8_t* te, int64_t& v) {
  std::string tok((const char*)q, (const char*)te);
  char* endp = nullptr;
  long long r = strtoll(tok.c_str(), &endp, 10);
  if (endp != tok.c_str() + tok.size()) return false;
  v = r;
  return true;
}

bool num_range_fallback_f64(const uint8_t* q, const uint8_t* te, double& v) {
  std::string tok((const char*)q, (const char*)te);
  char* endp = nullptr;
  double r = strtod(tok.c_str(), &endp);
  if (endp != tok.c_str() + tok.size()) return false;
  v = r;
  return true;
}

inline bool parse_i64_at(const uint8_t*& q, const uint8_t* e, int64_t& v) {
  const uint8_t* te = num_token_end(q, e);
  if (te == q) return false;
  auto r = std::from_chars((const char*)q, (const char*)te, v, 10);
  if (r.ec == std::errc::result_out_of_range) {
    if (!num_range_fallback_i64(q, te, v)) return false;
  } else if (r.ec != std::errc() || r.ptr != (const char*)te) {
    return false;
  }
  q = te;
  return true;
}

inline bool parse_f64_at(const uint8_t*& q, const uint8_t* e, double& v) {
  const uint8_t* te = num_token_end(q, e);
  if (te == q) return false;
  auto r = std::from_chars((const char*)q, (const char*)te, v);
  if (r.ec == std::errc::result_out_of_range) {
    if (!num_range_fallback_f64(q, te, v)) return false;
  } else if (r.ec != std::errc() || r.ptr != (const char*)te) {
    return false;
  }
  q = te;
  return true;
}

// skip any JSON value (for unknown keys)
bool skip_value(Cursor& c) {
  c.ws();
  if (c.p >= c.end) return false;
  uint8_t ch = *c.p;
  if (ch == '"') {
    c.p++;
    std::string tmp;
    return parse_string(c, tmp);
  }
  if (ch == '{' || ch == '[') {
    uint8_t open = ch, close = (ch == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    while (c.p < c.end) {
      uint8_t x = *c.p++;
      if (in_str) {
        if (x == '\\') { if (c.p < c.end) c.p++; }
        else if (x == '"') in_str = false;
      } else if (x == '"') in_str = true;
      else if (x == open) depth++;
      else if (x == close) {
        if (--depth == 0) return true;
      }
    }
    return false;
  }
  // number / true / false / null
  while (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
         *c.p != ' ' && *c.p != '\n' && *c.p != '\t' && *c.p != '\r')
    c.p++;
  return true;
}

// drop every per-row append made by a partially parsed row, restoring all
// column vectors to exactly `nr` committed rows (cheap: size bookkeeping
// only, no reallocation)
void rollback_row(Parser* p, uint64_t nr) {
  for (auto& col : p->cols) {
    col.valid.resize(nr);
    switch (col.type) {
      case 0: col.i64.resize(nr); break;
      case 1: col.f64.resize(nr); break;
      case 2: col.b.resize(nr); break;
      case 3:
        col.str_offsets.resize(nr + 1);
        col.str_bytes.resize(col.str_offsets.back());
        break;
    }
  }
}

void push_null(Col& col) {
  col.valid.push_back(0);
  switch (col.type) {
    case 0: col.i64.push_back(0); break;
    case 1: col.f64.push_back(0.0); break;
    case 2: col.b.push_back(0); break;
    case 3: col.str_offsets.push_back(col.str_bytes.size()); break;
  }
}

// layout-driven row parse; returns false on ANY deviation (caller rolls
// back and reparses on the general path).  Appends exactly one entry per
// schema column on success.
bool fast_row(Parser* p, const uint8_t* b, const uint8_t* e) {
  Layout& L = p->layout;
  const uint8_t* q = b;
  const size_t n = L.tok.size();
  for (size_t i = 0; i < n; i++) {
    const std::string& t = L.tok[i];
    if ((size_t)(e - q) < t.size() || memcmp(q, t.data(), t.size()) != 0)
      return false;
    q += t.size();
    const int ci = L.col[i];
    if (ci < 0) {
      Cursor c{q, e};
      if (!skip_value(c) || c.fail) return false;
      q = c.p;
      continue;
    }
    Col& col = p->cols[ci];
    if ((size_t)(e - q) >= 4 && memcmp(q, "null", 4) == 0) {
      q += 4;
      push_null(col);
      continue;
    }
    switch (col.type) {
      case 0: {
        int64_t v;
        if (!parse_i64_at(q, e, v)) return false;
        col.i64.push_back(v);
        break;
      }
      case 1: {
        double v;
        if (!parse_f64_at(q, e, v)) return false;
        col.f64.push_back(v);
        break;
      }
      case 2: {
        if ((size_t)(e - q) >= 4 && memcmp(q, "true", 4) == 0) {
          q += 4;
          col.b.push_back(1);
        } else if ((size_t)(e - q) >= 5 && memcmp(q, "false", 5) == 0) {
          q += 5;
          col.b.push_back(0);
        } else {
          return false;
        }
        break;
      }
      case 3: {
        if (q >= e || *q != '"') return false;
        const uint8_t* s = q + 1;
        const uint8_t* close = (const uint8_t*)memchr(s, '"', e - s);
        if (!close) return false;
        if (memchr(s, '\\', close - s) != nullptr) {
          // escape present: the first '"' may itself be escaped — use the
          // full unescaping parser for this value
          Cursor c{s, e};
          std::string sval;
          if (!parse_string(c, sval)) return false;
          col.str_bytes.insert(col.str_bytes.end(), sval.begin(),
                               sval.end());
          q = c.p;
        } else {
          col.str_bytes.insert(col.str_bytes.end(), s, close);
          q = close + 1;
        }
        col.str_offsets.push_back(col.str_bytes.size());
        break;
      }
    }
    col.valid.push_back(1);
  }
  if ((size_t)(e - q) != L.tail.size() ||
      memcmp(q, L.tail.data(), L.tail.size()) != 0)
    return false;
  for (int ci : L.missing) push_null(p->cols[ci]);
  return true;
}

// capture the layout of a row the general path just parsed successfully
void adopt_layout(Parser* p, const uint8_t* b, const uint8_t* e) {
  Layout& L = p->layout;
  L.valid = false;
  if (!p->d_ok || p->d_vs.empty()) return;  // dup keys / empty object
  const size_t n = p->d_vs.size();
  L.tok.resize(n);
  L.tok[0].assign((const char*)b, p->d_vs[0]);
  for (size_t i = 1; i < n; i++)
    L.tok[i].assign((const char*)b + p->d_ve[i - 1],
                    p->d_vs[i] - p->d_ve[i - 1]);
  L.tail.assign((const char*)b + p->d_ve[n - 1],
                (size_t)(e - b) - p->d_ve[n - 1]);
  L.col = p->d_col;
  L.missing.clear();
  std::vector<uint8_t> present(p->cols.size(), 0);
  for (int c : L.col)
    if (c >= 0) present[c] = 1;
  for (int i = 0; i < (int)p->cols.size(); i++)
    if (!present[i]) L.missing.push_back(i);
  L.valid = true;
  // NOTE: fail_streak is deliberately NOT reset here — it resets only on
  // a fast-row success.  Re-adopting after every general-path row would
  // otherwise zero the streak each time and the mixed-shape kill-switch
  // in jp_parse could never fire.
}

// the general (any-shape) row parse; fills discovery scratch for
// adopt_layout.  Returns false with p->error set on malformed input.
bool parse_row_general(Parser* p, const uint8_t* b, const uint8_t* e,
                       uint64_t r) {
  const int ncols = (int)p->cols.size();
  std::string& key = p->g_key;
  std::string& sval = p->g_sval;
  std::vector<uint8_t>& seen = p->g_seen;
  seen.assign(ncols, 0);
  p->d_vs.clear();
  p->d_ve.clear();
  p->d_col.clear();
  p->d_ok = true;

  Cursor c{b, e};
  if (!c.eat('{')) {
    p->error = "expected '{' at row " + std::to_string(r);
    return false;
  }
  if (!c.peek('}')) {
    for (;;) {
      if (!c.eat('"')) break;
      if (!parse_string(c, key)) { c.fail = true; break; }
      if (!c.eat(':')) break;
      // find column
      int ci = -1;
      for (int i = 0; i < ncols; i++)
        if (p->cols[i].name == key) { ci = i; break; }
      c.ws();
      p->d_vs.push_back((size_t)(c.p - b));
      p->d_col.push_back(ci);
      if (ci < 0) {
        if (!skip_value(c)) { c.fail = true; break; }
      } else {
        Col& col = p->cols[ci];
        if (seen[ci]) {
          // duplicate key: last-wins (match json.loads dict semantics) —
          // drop the value stored for the earlier occurrence
          p->d_ok = false;  // fast path can't reproduce dup handling
          col.valid.pop_back();
          switch (col.type) {
            case 0: col.i64.pop_back(); break;
            case 1: col.f64.pop_back(); break;
            case 2: col.b.pop_back(); break;
            case 3:
              col.str_offsets.pop_back();
              col.str_bytes.resize(col.str_offsets.back());
              break;
          }
        }
        seen[ci] = 1;
        bool is_null = false;
        if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
          c.p += 4;
          is_null = true;
        }
        if (is_null) {
          push_null(col);
        } else {
          switch (col.type) {
            case 0: {
              int64_t v;
              if (!parse_i64_at(c.p, c.end, v)) { c.fail = true; }
              col.i64.push_back(c.fail ? 0 : v);
              col.valid.push_back(1);
              break;
            }
            case 1: {
              double v;
              if (!parse_f64_at(c.p, c.end, v)) { c.fail = true; }
              col.f64.push_back(c.fail ? 0.0 : v);
              col.valid.push_back(1);
              break;
            }
            case 2: {
              c.ws();
              if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) {
                c.p += 4;
                col.b.push_back(1);
              } else if (c.end - c.p >= 5 &&
                         memcmp(c.p, "false", 5) == 0) {
                c.p += 5;
                col.b.push_back(0);
              } else {
                c.fail = true;
                col.b.push_back(0);
              }
              col.valid.push_back(1);
              break;
            }
            case 3: {
              if (!c.eat('"')) { c.fail = true; break; }
              if (!parse_string(c, sval)) { c.fail = true; break; }
              col.str_bytes.insert(col.str_bytes.end(), sval.begin(),
                                   sval.end());
              col.str_offsets.push_back(col.str_bytes.size());
              col.valid.push_back(1);
              break;
            }
          }
        }
      }
      if (c.fail) break;
      p->d_ve.push_back((size_t)(c.p - b));
      c.ws();
      if (c.peek(',')) { c.p++; continue; }
      break;
    }
    if (!c.fail) c.eat('}');
  } else {
    c.p++;  // consume '}'
  }
  if (c.fail) {
    p->error = "malformed JSON at row " + std::to_string(r);
    return false;
  }
  // missing keys → null
  for (int i = 0; i < ncols; i++)
    if (!seen[i]) push_null(p->cols[i]);
  return true;
}

}  // namespace

extern "C" {

void* jp_create(int ncols, const char** names, const int* types) {
  Parser* p = new Parser();
  p->cols.resize(ncols);
  for (int i = 0; i < ncols; i++) {
    p->cols[i].name = names[i];
    p->cols[i].type = types[i];
    p->cols[i].str_offsets.push_back(0);
  }
  return p;
}

void jp_clear(void* h) {
  Parser* p = static_cast<Parser*>(h);
  p->nrows = 0;
  p->error.clear();
  for (auto& c : p->cols) {
    c.i64.clear();
    c.f64.clear();
    c.b.clear();
    c.valid.clear();
    c.str_bytes.clear();
    c.str_offsets.assign(1, 0);
  }
}

// returns 0 on success, -1 on parse error (see jp_error)
int jp_parse(void* h, const uint8_t* data, const uint64_t* offsets,
             uint64_t nrows) {
  Parser* p = static_cast<Parser*>(h);
  for (auto& col : p->cols) {
    col.valid.reserve(col.valid.size() + nrows);
    switch (col.type) {
      case 0: col.i64.reserve(col.i64.size() + nrows); break;
      case 1: col.f64.reserve(col.f64.size() + nrows); break;
      case 2: col.b.reserve(col.b.size() + nrows); break;
      case 3:
        col.str_offsets.reserve(col.str_offsets.size() + nrows);
        break;
    }
  }
  for (uint64_t r = 0; r < nrows; r++) {
    const uint8_t* b = data + offsets[r];
    const uint8_t* e = data + offsets[r + 1];
    if (p->layout.valid) {
      if (fast_row(p, b, e)) {
        p->layout.fail_streak = 0;
        p->nrows++;
        continue;
      }
      rollback_row(p, p->nrows);
      // a producer whose shape keeps missing the layout (mixed styles,
      // varying key sets) must not pay fast-attempt + rollback + layout
      // re-adoption per row forever: after 8 straight misses, disable
      // the fast path and suppress re-adoption for a stretch of rows
      if (++p->layout.fail_streak >= 8) {
        p->layout.valid = false;
        p->layout.fail_streak = 0;
        p->adopt_cooldown = 256;
      }
    }
    if (!parse_row_general(p, b, e, r)) return -1;
    if (p->adopt_cooldown > 0)
      p->adopt_cooldown--;
    else
      adopt_layout(p, b, e);
    p->nrows++;
  }
  return 0;
}

const char* jp_error(void* h) {
  return static_cast<Parser*>(h)->error.c_str();
}

uint64_t jp_nrows(void* h) { return static_cast<Parser*>(h)->nrows; }

const int64_t* jp_col_i64(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].i64.data();
}
const double* jp_col_f64(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].f64.data();
}
const uint8_t* jp_col_bool(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].b.data();
}
const uint8_t* jp_col_valid(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].valid.data();
}
const uint8_t* jp_col_str_bytes(void* h, int col, uint64_t* nbytes) {
  Col& c = static_cast<Parser*>(h)->cols[col];
  *nbytes = c.str_bytes.size();
  return c.str_bytes.data();
}
const uint64_t* jp_col_str_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].str_offsets.data();
}
int64_t jp_col_str_dict(void* h, int col) {
  Parser* p = static_cast<Parser*>(h);
  Col& c = p->cols[col];
  return build_str_dict(c.str_bytes, c.str_offsets, p->nrows, c.dict);
}
const int32_t* jp_col_str_dict_codes(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].dict.codes.data();
}
const uint8_t* jp_col_str_dict_bytes(void* h, int col, uint64_t* nbytes) {
  StrDict& d = static_cast<Parser*>(h)->cols[col].dict;
  *nbytes = d.bytes.size();
  return d.bytes.data();
}
const uint64_t* jp_col_str_dict_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->cols[col].dict.offsets.data();
}

void jp_destroy(void* h) { delete static_cast<Parser*>(h); }

}  // extern "C"
