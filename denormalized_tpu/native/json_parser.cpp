// json_parser — one-pass JSON-objects → columnar buffers.
//
// Native ingest/decode path: the reference decodes Kafka JSON payloads by
// concatenating them into a JSON array and running arrow-json's reader
// (crates/core/src/formats/decoders/json.rs:11-49, native Rust/C via Arrow),
// which handles nested structs/lists natively.  Ours parses each payload
// directly into typed columnar buffers in a single pass — no intermediate
// DOM, no per-row Python objects — and SHREDS nested values the way a
// columnar format does:
//   - struct fields (any depth) become their leaf columns plus a per-row
//     presence byte per struct node;
//   - lists of scalars become Arrow-style (offsets, values, elem-validity)
//     triples;
//   - lists of structs / lists of lists are GENERIC list nodes: the list
//     stores per-row offsets and the single child node stores one entry
//     per ELEMENT (struct presence + descendant leaves, or another
//     (offsets, …) level for lists-of-lists) — recursion to any depth,
//     the same shredding arrow-json performs.
//
// C ABI for ctypes.  Node types: 0=int64, 1=float64, 2=bool, 3=string,
// 4=struct, 5=list-of-scalar, 6=list-of-node (child subtree per element).
// ``jp_create`` keeps the historical flat ABI (top-level scalar columns
// only); ``jp_create_tree`` takes the full schema tree.  Unknown keys are
// skipped (balanced for nested values); missing keys and JSON nulls set
// validity 0 (recursively for structs).

#include <algorithm>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <locale.h>
#include <string>
#include <vector>

#include "str_dict.hpp"

namespace {

// One schema-tree node.  Scalars store one value per ENTRY; struct nodes
// store a presence byte per entry in `valid` (1 = object present, 0 =
// null/missing) and their children hold the data; scalar-list nodes
// (type 5) store per-entry `list_offsets` with the elements packed into
// the node's own value vectors (`evalid` parallel to elements); generic
// list nodes (type 6) store per-entry `list_offsets` and their single
// child node holds one entry per element.  An "entry" is a row for
// top-level nodes and struct descendants, and an element for nodes under
// a generic list — every node appends exactly one `valid` byte per
// entry, so `valid.size()` is always a node's entry count.
struct Node {
  std::string name;
  int type;  // 0 i64 | 1 f64 | 2 bool | 3 str | 4 struct | 5 list | 6 list-of-node
  int elem_type = -1;  // type-5 list: scalar element type 0..3
  std::vector<int> kids;  // struct children / generic-list element node
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<uint8_t> b;
  std::vector<uint8_t> str_bytes;
  std::vector<uint64_t> str_offsets;  // scalar: nrows+1; list str: nelems+1
  std::vector<uint8_t> valid;         // per row (leaf/struct/list)
  std::vector<uint64_t> list_offsets;  // list: nentries+1
  std::vector<uint8_t> evalid;         // type-5 list: per element
  StrDict dict;
};

// Adaptive row layout: streaming producers emit a fixed record shape, so
// after one general-path row parse we capture the exact inter-value byte
// runs — `{"key":`, `,"key2":`, …, the trailing `}` — including whatever
// fixed whitespace style the producer uses (serde_json compact,
// json.dumps `", "`/`": "`, …).  With nesting, the "values" are the
// LAYOUT UNITS: scalar leaves at any struct depth plus entire lists; the
// bytes of the nested structure itself (`{"gps":{"lat":`) land inside the
// inter-unit token runs, so a nested fixed-shape producer gets the same
// few-memcmp fast path as a flat one.  Any mismatch rolls the row back
// and reparses it on the general path (which re-learns the layout), so
// this is purely a fast path — semantics are identical.
struct Layout {
  bool valid = false;
  std::vector<std::string> tok;  // tok[i]: bytes preceding unit i
  std::vector<int> col;          // node index of unit i (-1: skip)
  std::vector<int> present;      // struct nodes present in this shape
  std::vector<int> missing;      // nodes nulled in this shape (subtree tops)
  std::string tail;              // bytes after the last unit
  int fail_streak = 0;
};

struct Parser {
  std::vector<Node> nodes;
  std::vector<int> top;  // top-level node indices, schema order
  uint64_t nrows = 0;
  std::string error;
  Layout layout;
  int adopt_cooldown = 0;  // >0: layout adoption suppressed (see jp_parse)
  // per-row discovery scratch (unit spans, node ids, shape sets), filled
  // by the general path so a successful row can become the new layout
  std::vector<size_t> d_vs, d_ve;
  std::vector<int> d_col;
  std::vector<int> d_present, d_missing;
  bool d_ok = false;
  // general-path per-row scratch, hoisted here so rows that stay on the
  // general path don't pay per-row heap allocations
  std::string g_key, g_sval;
  std::vector<uint8_t> g_seen;  // per NODE, cleared per row
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == (uint8_t)c) {
      p++;
      return true;
    }
    fail = true;
    return false;
  }
  bool peek(char c) {
    ws();
    return p < end && *p == (uint8_t)c;
  }
};

// parse a JSON string (after the opening quote) into out; handles escapes
bool parse_string(Cursor& c, std::string& out) {
  out.clear();
  while (c.p < c.end) {
    uint8_t ch = *c.p++;
    if (ch == '"') return true;
    if (ch != '\\') {
      out.push_back((char)ch);
      continue;
    }
    if (c.p >= c.end) break;
    uint8_t esc = *c.p++;
    switch (esc) {
      case '"': out.push_back('"'); break;
      case '\\': out.push_back('\\'); break;
      case '/': out.push_back('/'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case 'u': {
        auto hex4 = [&](unsigned& cp) -> bool {
          if (c.end - c.p < 4) return false;
          cp = 0;
          for (int i = 0; i < 4; i++) {
            uint8_t h = *c.p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          return true;
        };
        unsigned cp;
        if (!hex4(cp)) return false;
        // surrogate pair → combined code point (json.dumps ensure_ascii
        // emits all non-BMP chars this way)
        if (cp >= 0xD800 && cp <= 0xDBFF && c.end - c.p >= 6 &&
            c.p[0] == '\\' && c.p[1] == 'u') {
          c.p += 2;
          unsigned lo;
          if (!hex4(lo)) return false;
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          } else {
            cp = 0xFFFD;  // lone high surrogate → replacement char
            unsigned cp2 = (lo >= 0xD800 && lo <= 0xDFFF) ? 0xFFFD : lo;
            auto emit = [&](unsigned x) {
              if (x < 0x80) out.push_back((char)x);
              else if (x < 0x800) {
                out.push_back((char)(0xC0 | (x >> 6)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else if (x < 0x10000) {
                out.push_back((char)(0xE0 | (x >> 12)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              } else {
                out.push_back((char)(0xF0 | (x >> 18)));
                out.push_back((char)(0x80 | ((x >> 12) & 0x3F)));
                out.push_back((char)(0x80 | ((x >> 6) & 0x3F)));
                out.push_back((char)(0x80 | (x & 0x3F)));
              }
            };
            emit(cp);
            emit(cp2);
            break;
          }
        } else if (cp >= 0xD800 && cp <= 0xDFFF) {
          cp = 0xFFFD;  // lone surrogate
        }
        if (cp < 0x80) out.push_back((char)cp);
        else if (cp < 0x800) {
          out.push_back((char)(0xC0 | (cp >> 6)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else if (cp < 0x10000) {
          out.push_back((char)(0xE0 | (cp >> 12)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        } else {
          out.push_back((char)(0xF0 | (cp >> 18)));
          out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
          out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
          out.push_back((char)(0x80 | (cp & 0x3F)));
        }
        break;
      }
      default: return false;
    }
  }
  return false;
}

// End of the numeric token starting at p (same charset the old
// strtol-based scanner used); std::from_chars then converts straight from
// the arena — no copy, no NUL termination needed, exactly-rounded doubles.
// The full token must be consumed or the row fails (so "1e5" on an int
// column cannot silently truncate to 1, and "inf"/"nan" — which
// from_chars would accept but JSON forbids — yield an empty token).
// The three literals json.loads DOES accept (NaN/Infinity/-Infinity;
// our own JsonRowEncoder emits Infinity for inf) are matched by spelling
// in parse_f64_at, keeping the native and Python decode paths identical.
inline const uint8_t* num_token_end(const uint8_t* p, const uint8_t* e) {
  while (p < e) {
    uint8_t ch = *p;
    if ((ch >= '0' && ch <= '9') || ch == '-' || ch == '+' || ch == '.' ||
        ch == 'e' || ch == 'E')
      p++;
    else
      break;
  }
  return p;
}

// out-of-range tokens keep the historical strtoll/strtod semantics
// (clamp to LLONG_MIN/MAX; overflow to ±inf, underflow to ±0) instead of
// failing the batch — json.loads accepts 1e999 and 20-digit ints, so the
// parser must too.  Cold path: copies the token for NUL termination.
bool num_range_fallback_i64(const uint8_t* q, const uint8_t* te, int64_t& v) {
  std::string tok((const char*)q, (const char*)te);
  char* endp = nullptr;
  long long r = strtoll(tok.c_str(), &endp, 10);
  if (endp != tok.c_str() + tok.size()) return false;
  v = r;
  return true;
}

bool num_range_fallback_f64(const uint8_t* q, const uint8_t* te, double& v) {
  // strtod_l against a cached C locale: plain strtod honors LC_NUMERIC,
  // so an embedding process that set a comma-decimal locale would reject
  // every '.'-pointed token this fallback exists to parse (from_chars is
  // locale-independent — the two branches must not diverge by locale)
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  std::string tok((const char*)q, (const char*)te);
  char* endp = nullptr;
  double r = c_loc ? strtod_l(tok.c_str(), &endp, c_loc)
                   : strtod(tok.c_str(), &endp);
  if (endp != tok.c_str() + tok.size()) return false;
  v = r;
  return true;
}

// Clinger fast path: a token with <= 15 significant digits and a net
// decimal exponent within ±22 is EXACTLY m * 10^q with m < 2^53 and
// 10^|q| exactly representable — one multiply/divide, one rounding,
// bit-identical to a correctly-rounded strtod/from_chars.  Returns
// false (caller falls back to strtod) on long mantissas, big exponents,
// or malformed tails.  This is the hot conversion on toolchains whose
// libstdc++ lacks floating-point from_chars (gcc 10, this image): the
// sensor-style payloads the engine ingests are short decimals, so the
// slow path is essentially never taken.
inline bool fast_f64(const uint8_t* p, const uint8_t* e, double& v) {
  static const double P10[] = {1.0,   1e1,  1e2,  1e3,  1e4,  1e5,
                               1e6,   1e7,  1e8,  1e9,  1e10, 1e11,
                               1e12,  1e13, 1e14, 1e15, 1e16, 1e17,
                               1e18,  1e19, 1e20, 1e21, 1e22};
  bool neg = false;
  if (p < e && *p == '-') {
    neg = true;
    p++;
  }
  uint64_t m = 0;
  int ndig = 0, frac = 0;
  bool seen_dot = false, any = false;
  for (; p < e; p++) {
    uint8_t ch = *p;
    if (ch >= '0' && ch <= '9') {
      any = true;
      if (ndig < 19) m = m * 10 + (ch - '0');
      ndig++;
      if (seen_dot) frac++;
    } else if (ch == '.' && !seen_dot) {
      seen_dot = true;
    } else {
      break;
    }
  }
  if (!any) return false;
  int exp10 = 0;
  if (p < e && (*p == 'e' || *p == 'E')) {
    p++;
    bool eneg = false;
    if (p < e && (*p == '+' || *p == '-')) {
      eneg = (*p == '-');
      p++;
    }
    if (p >= e || *p < '0' || *p > '9') return false;
    int ev = 0;
    for (; p < e && *p >= '0' && *p <= '9'; p++)
      if (ev < 100000) ev = ev * 10 + (*p - '0');
    exp10 = eneg ? -ev : ev;
  }
  if (p != e) return false;
  if (ndig > 15) return false;  // double rounding possible: strtod decides
  int q10 = exp10 - frac;
  if (q10 < -22 || q10 > 22) return false;
  double dv = (double)m;
  dv = q10 >= 0 ? dv * P10[q10] : dv / P10[-q10];
  v = neg ? -dv : dv;
  return true;
}

inline bool parse_i64_at(const uint8_t*& q, const uint8_t* e, int64_t& v) {
  const uint8_t* te = num_token_end(q, e);
  if (te == q) return false;
  auto r = std::from_chars((const char*)q, (const char*)te, v, 10);
  if (r.ec == std::errc::result_out_of_range) {
    if (!num_range_fallback_i64(q, te, v)) return false;
  } else if (r.ec != std::errc() || r.ptr != (const char*)te) {
    return false;
  }
  q = te;
  return true;
}

inline bool parse_f64_at(const uint8_t*& q, const uint8_t* e, double& v) {
  // the exact (case-sensitive) non-finite literals json.loads accepts;
  // int columns stay strict — the Python path also rejects them there
  if (e - q >= 3 && memcmp(q, "NaN", 3) == 0) {
    v = std::numeric_limits<double>::quiet_NaN();
    q += 3;
    return true;
  }
  if (e - q >= 8 && memcmp(q, "Infinity", 8) == 0) {
    v = std::numeric_limits<double>::infinity();
    q += 8;
    return true;
  }
  if (e - q >= 9 && memcmp(q, "-Infinity", 9) == 0) {
    v = -std::numeric_limits<double>::infinity();
    q += 9;
    return true;
  }
  const uint8_t* te = num_token_end(q, e);
  if (te == q) return false;
#if defined(__cpp_lib_to_chars)
  auto r = std::from_chars((const char*)q, (const char*)te, v);
  if (r.ec == std::errc::result_out_of_range) {
    if (!num_range_fallback_f64(q, te, v)) return false;
  } else if (r.ec != std::errc() || r.ptr != (const char*)te) {
    return false;
  }
#else
  // libstdc++ < 11 ships integer from_chars only.  Clinger fast path
  // first (correctly rounded for short decimals — the hot shape), then
  // strtod on a bounded copy (the range-fallback conversion), keeping
  // the same full-token consumption rule; '+'-led tokens are rejected
  // explicitly to keep from_chars strictness (JSON forbids a leading
  // plus, strtod does not).
  if (*q == '+') return false;
  if (!fast_f64(q, te, v) && !num_range_fallback_f64(q, te, v))
    return false;
#endif
  q = te;
  return true;
}

// skip any JSON value (for unknown keys)
bool skip_value(Cursor& c) {
  c.ws();
  if (c.p >= c.end) return false;
  uint8_t ch = *c.p;
  if (ch == '"') {
    c.p++;
    std::string tmp;
    return parse_string(c, tmp);
  }
  if (ch == '{' || ch == '[') {
    uint8_t open = ch, close = (ch == '{') ? '}' : ']';
    int depth = 0;
    bool in_str = false;
    while (c.p < c.end) {
      uint8_t x = *c.p++;
      if (in_str) {
        if (x == '\\') { if (c.p < c.end) c.p++; }
        else if (x == '"') in_str = false;
      } else if (x == '"') in_str = true;
      else if (x == open) depth++;
      else if (x == close) {
        if (--depth == 0) return true;
      }
    }
    return false;
  }
  // number / true / false / null
  while (c.p < c.end && *c.p != ',' && *c.p != '}' && *c.p != ']' &&
         *c.p != ' ' && *c.p != '\n' && *c.p != '\t' && *c.p != '\r')
    c.p++;
  return true;
}

inline uint64_t list_elems(const Node& nd) {
  return nd.list_offsets.empty() ? 0 : nd.list_offsets.back();
}

// resize node ni and its whole subtree down to exactly `count` entries —
// cheap size bookkeeping, no reallocation.  Used by row rollback (count =
// committed rows for top-level nodes) and by duplicate-key subtree
// removal, where a generic-list child's entry count is whatever the
// trimmed parent's offsets say.
void trim_node(Parser* p, int ni, uint64_t count) {
  Node& nd = p->nodes[ni];
  nd.valid.resize(count);
  switch (nd.type) {
    case 0: nd.i64.resize(count); break;
    case 1: nd.f64.resize(count); break;
    case 2: nd.b.resize(count); break;
    case 3:
      nd.str_offsets.resize(count + 1);
      nd.str_bytes.resize(nd.str_offsets.back());
      break;
    case 4:
      for (int k : nd.kids) trim_node(p, k, count);
      break;
    case 5: {
      nd.list_offsets.resize(count + 1);
      uint64_t ne = nd.list_offsets.back();
      nd.evalid.resize(ne);
      switch (nd.elem_type) {
        case 0: nd.i64.resize(ne); break;
        case 1: nd.f64.resize(ne); break;
        case 2: nd.b.resize(ne); break;
        case 3:
          nd.str_offsets.resize(ne + 1);
          nd.str_bytes.resize(nd.str_offsets.back());
          break;
      }
      break;
    }
    case 6:
      nd.list_offsets.resize(count + 1);
      trim_node(p, nd.kids[0], nd.list_offsets.back());
      break;
  }
}

// drop every per-row append made by a partially parsed row, restoring all
// node vectors to exactly `nr` committed rows
void rollback_row(Parser* p, uint64_t nr) {
  for (int ni : p->top) trim_node(p, ni, nr);
}

void push_null_scalar(Node& nd) {
  nd.valid.push_back(0);
  switch (nd.type) {
    case 0: nd.i64.push_back(0); break;
    case 1: nd.f64.push_back(0.0); break;
    case 2: nd.b.push_back(0); break;
    case 3: nd.str_offsets.push_back(nd.str_bytes.size()); break;
  }
}

// append one null entry to node ni and (for structs) every descendant
// (a null list leaves its child untouched — zero elements)
void push_null_recursive(Parser* p, int ni) {
  Node& nd = p->nodes[ni];
  switch (nd.type) {
    case 4:
      nd.valid.push_back(0);
      for (int k : nd.kids) push_null_recursive(p, k);
      break;
    case 5:
    case 6:
      nd.valid.push_back(0);
      nd.list_offsets.push_back(list_elems(nd));
      break;
    default:
      push_null_scalar(nd);
  }
}

// zero the per-row duplicate-key marks for a whole subtree
void clear_seen(Parser* p, int ni) {
  p->g_seen[ni] = 0;
  for (int k : p->nodes[ni].kids) clear_seen(p, k);
}

// remove the last entry from node ni and every descendant (duplicate
// keys: json.loads is last-wins, so the earlier subtree's appends must
// go).  Also clears the per-row `seen` marks for the subtree so the
// replacement occurrence re-parses descendants as first sightings (the
// caller re-marks the subtree top itself).
void pop_row_subtree(Parser* p, int ni) {
  Node& nd = p->nodes[ni];
  p->g_seen[ni] = 0;
  nd.valid.pop_back();
  switch (nd.type) {
    case 0: nd.i64.pop_back(); break;
    case 1: nd.f64.pop_back(); break;
    case 2: nd.b.pop_back(); break;
    case 3:
      nd.str_offsets.pop_back();
      nd.str_bytes.resize(nd.str_offsets.back());
      break;
    case 4:
      for (int k : nd.kids) pop_row_subtree(p, k);
      break;
    case 5: {
      nd.list_offsets.pop_back();
      uint64_t ne = nd.list_offsets.back();
      nd.evalid.resize(ne);
      switch (nd.elem_type) {
        case 0: nd.i64.resize(ne); break;
        case 1: nd.f64.resize(ne); break;
        case 2: nd.b.resize(ne); break;
        case 3:
          nd.str_offsets.resize(ne + 1);
          nd.str_bytes.resize(nd.str_offsets.back());
          break;
      }
      break;
    }
    case 6:
      nd.list_offsets.pop_back();
      trim_node(p, nd.kids[0], nd.list_offsets.back());
      clear_seen(p, nd.kids[0]);
      break;
  }
}

// parse one scalar JSON value into nd (appends value + valid=1); the
// cursor sits at the first value byte (caller already handled "null")
bool parse_scalar_value(Parser* p, Node& nd, Cursor& c) {
  switch (nd.type) {
    case 0: {
      int64_t v;
      if (!parse_i64_at(c.p, c.end, v)) { c.fail = true; return false; }
      nd.i64.push_back(v);
      break;
    }
    case 1: {
      double v;
      if (!parse_f64_at(c.p, c.end, v)) { c.fail = true; return false; }
      nd.f64.push_back(v);
      break;
    }
    case 2: {
      if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) {
        c.p += 4;
        nd.b.push_back(1);
      } else if (c.end - c.p >= 5 && memcmp(c.p, "false", 5) == 0) {
        c.p += 5;
        nd.b.push_back(0);
      } else {
        c.fail = true;
        return false;
      }
      break;
    }
    case 3: {
      if (!c.eat('"')) { c.fail = true; return false; }
      if (!parse_string(c, p->g_sval)) { c.fail = true; return false; }
      nd.str_bytes.insert(nd.str_bytes.end(), p->g_sval.begin(),
                          p->g_sval.end());
      nd.str_offsets.push_back(nd.str_bytes.size());
      break;
    }
    default:
      c.fail = true;
      return false;
  }
  nd.valid.push_back(1);
  return true;
}

// parse one scalar-list value (cursor at '['); appends elements + one
// list_offsets/valid row entry.  Shared by the general and fast paths —
// a list is a single layout unit, reparsed generically every row (its
// element count varies, so its bytes can't be layout tokens).
bool parse_list_value(Parser* /*p: callers pass it for symmetry with the
                                 other value parsers; lists need no
                                 parser-wide scratch*/,
                      Node& nd, Cursor& c, std::string& sval) {
  if (!c.eat('[')) return false;
  if (!c.peek(']')) {
    for (;;) {
      c.ws();
      if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
        c.p += 4;
        nd.evalid.push_back(0);
        switch (nd.elem_type) {
          case 0: nd.i64.push_back(0); break;
          case 1: nd.f64.push_back(0.0); break;
          case 2: nd.b.push_back(0); break;
          case 3: nd.str_offsets.push_back(nd.str_bytes.size()); break;
        }
      } else {
        switch (nd.elem_type) {
          case 0: {
            int64_t v;
            if (!parse_i64_at(c.p, c.end, v)) return false;
            nd.i64.push_back(v);
            break;
          }
          case 1: {
            double v;
            if (!parse_f64_at(c.p, c.end, v)) return false;
            nd.f64.push_back(v);
            break;
          }
          case 2: {
            if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) {
              c.p += 4;
              nd.b.push_back(1);
            } else if (c.end - c.p >= 5 && memcmp(c.p, "false", 5) == 0) {
              c.p += 5;
              nd.b.push_back(0);
            } else {
              return false;
            }
            break;
          }
          case 3: {
            if (!c.eat('"')) return false;
            if (!parse_string(c, sval)) return false;
            nd.str_bytes.insert(nd.str_bytes.end(), sval.begin(),
                                sval.end());
            nd.str_offsets.push_back(nd.str_bytes.size());
            break;
          }
        }
        nd.evalid.push_back(1);
      }
      if (c.peek(',')) { c.p++; continue; }
      break;
    }
  }
  if (!c.eat(']')) return false;
  nd.list_offsets.push_back(nd.evalid.size());
  nd.valid.push_back(1);
  return true;
}

bool parse_struct_body(Parser* p, int ni, Cursor& c, const uint8_t* b,
                       bool discover);
bool parse_value_node(Parser* p, int ni, Cursor& c);

// parse one generic-list value (type 6, cursor at '['): each element
// appends ONE entry to the child subtree — a struct element pushes its
// presence byte + descendant leaves, a list element pushes another
// offsets level, a null element pushes a recursive null — so the child's
// entry count IS the element count and the parent only records offsets.
bool parse_list_node(Parser* p, int ni, Cursor& c) {
  Node& nd = p->nodes[ni];
  const int kid = nd.kids[0];
  if (!c.eat('[')) return false;
  if (!c.peek(']')) {
    for (;;) {
      if (!parse_value_node(p, kid, c)) return false;
      if (c.peek(',')) { c.p++; continue; }
      break;
    }
  }
  if (!c.eat(']')) return false;
  nd.list_offsets.push_back(p->nodes[kid].valid.size());
  nd.valid.push_back(1);
  return true;
}

// parse any JSON value into node ni — the element parser for generic
// lists (no layout discovery: the enclosing list is already one opaque
// layout unit, reparsed generically every row)
bool parse_value_node(Parser* p, int ni, Cursor& c) {
  c.ws();
  if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
    c.p += 4;
    push_null_recursive(p, ni);
    return true;
  }
  Node& nd = p->nodes[ni];
  switch (nd.type) {
    case 4:
      if (!parse_struct_body(p, ni, c, nullptr, false)) {
        c.fail = true;
        return false;
      }
      return true;
    case 5:
      return parse_list_value(p, nd, c, p->g_sval) && !c.fail;
    case 6:
      return parse_list_node(p, ni, c);
    default:
      return parse_scalar_value(p, nd, c);
  }
}

// layout-driven row parse; returns false on ANY deviation (caller rolls
// back and reparses on the general path).  Appends exactly one entry per
// schema node on success.
bool fast_row(Parser* p, const uint8_t* b, const uint8_t* e) {
  Layout& L = p->layout;
  const uint8_t* q = b;
  const size_t n = L.tok.size();
  for (size_t i = 0; i < n; i++) {
    const std::string& t = L.tok[i];
    if ((size_t)(e - q) < t.size() || memcmp(q, t.data(), t.size()) != 0)
      return false;
    q += t.size();
    const int ci = L.col[i];
    if (ci < 0) {
      Cursor c{q, e};
      if (!skip_value(c) || c.fail) return false;
      q = c.p;
      continue;
    }
    Node& nd = p->nodes[ci];
    if ((size_t)(e - q) >= 4 && memcmp(q, "null", 4) == 0) {
      q += 4;
      push_null_recursive(p, ci);
      continue;
    }
    switch (nd.type) {
      case 0: {
        int64_t v;
        if (!parse_i64_at(q, e, v)) return false;
        nd.i64.push_back(v);
        break;
      }
      case 1: {
        double v;
        if (!parse_f64_at(q, e, v)) return false;
        nd.f64.push_back(v);
        break;
      }
      case 2: {
        if ((size_t)(e - q) >= 4 && memcmp(q, "true", 4) == 0) {
          q += 4;
          nd.b.push_back(1);
        } else if ((size_t)(e - q) >= 5 && memcmp(q, "false", 5) == 0) {
          q += 5;
          nd.b.push_back(0);
        } else {
          return false;
        }
        break;
      }
      case 3: {
        if (q >= e || *q != '"') return false;
        const uint8_t* s = q + 1;
        const uint8_t* close = (const uint8_t*)memchr(s, '"', e - s);
        if (!close) return false;
        if (memchr(s, '\\', close - s) != nullptr) {
          // escape present: the first '"' may itself be escaped — use the
          // full unescaping parser for this value
          Cursor c{s, e};
          std::string sval;
          if (!parse_string(c, sval)) return false;
          nd.str_bytes.insert(nd.str_bytes.end(), sval.begin(),
                              sval.end());
          q = c.p;
        } else {
          nd.str_bytes.insert(nd.str_bytes.end(), s, close);
          q = close + 1;
        }
        nd.str_offsets.push_back(nd.str_bytes.size());
        break;
      }
      case 5: {
        Cursor c{q, e};
        if (!parse_list_value(p, nd, c, p->g_sval) || c.fail) return false;
        q = c.p;
        continue;  // parse_list_value pushed valid itself
      }
      case 6: {
        Cursor c{q, e};
        if (!parse_list_node(p, ci, c) || c.fail) return false;
        q = c.p;
        continue;  // parse_list_node pushed valid itself
      }
      default:
        return false;  // struct nodes are never layout units
    }
    nd.valid.push_back(1);
  }
  if ((size_t)(e - q) != L.tail.size() ||
      memcmp(q, L.tail.data(), L.tail.size()) != 0)
    return false;
  for (int ni : L.present) p->nodes[ni].valid.push_back(1);
  for (int ni : L.missing) push_null_recursive(p, ni);
  return true;
}

// capture the layout of a row the general path just parsed successfully
void adopt_layout(Parser* p, const uint8_t* b, const uint8_t* e) {
  Layout& L = p->layout;
  L.valid = false;
  if (!p->d_ok || p->d_vs.empty()) return;  // dup keys / no units
  const size_t n = p->d_vs.size();
  L.tok.resize(n);
  L.tok[0].assign((const char*)b, p->d_vs[0]);
  for (size_t i = 1; i < n; i++)
    L.tok[i].assign((const char*)b + p->d_ve[i - 1],
                    p->d_vs[i] - p->d_ve[i - 1]);
  L.tail.assign((const char*)b + p->d_ve[n - 1],
                (size_t)(e - b) - p->d_ve[n - 1]);
  L.col = p->d_col;
  L.present = p->d_present;
  L.missing = p->d_missing;
  L.valid = true;
  // NOTE: fail_streak is deliberately NOT reset here — it resets only on
  // a fast-row success.  Re-adopting after every general-path row would
  // otherwise zero the streak each time and the mixed-shape kill-switch
  // in jp_parse could never fire.
}

// general-path parse of one struct BODY (cursor at '{'); ni = -1 for the
// row root (children = p->top).  With ``discover`` set (row-scope
// structs) it fills the discovery scratch for adopt_layout: unit spans
// for scalar leaves + whole lists, present/missing node sets.  Struct
// values inside generic-list elements parse with discover=false — the
// enclosing list is already one opaque layout unit — and clear their
// direct kids' seen marks on entry, because the same schema node is
// instantiated once per ELEMENT within a single row.
bool parse_struct_body(Parser* p, int ni, Cursor& c, const uint8_t* b,
                       bool discover) {
  const std::vector<int>& kids = ni < 0 ? p->top : p->nodes[ni].kids;
  std::string& key = p->g_key;
  if (!c.eat('{')) return false;
  for (int k : kids) p->g_seen[k] = 0;
  if (ni >= 0) {
    p->nodes[ni].valid.push_back(1);
    if (discover) p->d_present.push_back(ni);
  }
  if (!c.peek('}')) {
    for (;;) {
      if (!c.eat('"')) return false;
      if (!parse_string(c, key)) { c.fail = true; return false; }
      if (!c.eat(':')) return false;
      int ci = -1;
      for (int k : kids)
        if (p->nodes[k].name == key) { ci = k; break; }
      c.ws();
      if (ci < 0) {
        // unknown key: skip — and record it as a col=-1 layout unit so a
        // producer whose undeclared field VARIES byte-to-byte (uuid,
        // trace id) still gets the fast path (fast_row re-skips the
        // value generically at that position instead of memcmp-failing)
        if (discover) {
          p->d_vs.push_back((size_t)(c.p - b));
          p->d_col.push_back(-1);
        }
        if (!skip_value(c)) { c.fail = true; return false; }
        if (discover) p->d_ve.push_back((size_t)(c.p - b));
      } else {
        Node& nd = p->nodes[ci];
        if (p->g_seen[ci]) {
          // duplicate key: last-wins (match json.loads dict semantics) —
          // drop the whole subtree stored for the earlier occurrence.
          // (Stale d_present/d_missing entries from it don't matter:
          // d_ok=false suppresses layout adoption for this row.)
          if (discover) p->d_ok = false;  // fast path can't reproduce dups
          pop_row_subtree(p, ci);
        }
        p->g_seen[ci] = 1;
        bool is_null = false;
        if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
          c.p += 4;
          is_null = true;
        }
        if (is_null) {
          push_null_recursive(p, ci);
          if (discover) p->d_missing.push_back(ci);
        } else if (nd.type == 4) {
          if (!parse_struct_body(p, ci, c, b, discover)) {
            c.fail = true;
            return false;
          }
        } else if (nd.type == 5 || nd.type == 6) {
          if (discover) {
            p->d_vs.push_back((size_t)(c.p - b));
            p->d_col.push_back(ci);
          }
          bool ok = nd.type == 5
                        ? parse_list_value(p, nd, c, p->g_sval) && !c.fail
                        : parse_list_node(p, ci, c);
          if (!ok) {
            c.fail = true;
            return false;
          }
          if (discover) p->d_ve.push_back((size_t)(c.p - b));
        } else {
          if (discover) {
            p->d_vs.push_back((size_t)(c.p - b));
            p->d_col.push_back(ci);
          }
          if (!parse_scalar_value(p, nd, c)) return false;
          if (discover) p->d_ve.push_back((size_t)(c.p - b));
        }
      }
      c.ws();
      if (c.peek(',')) { c.p++; continue; }
      break;
    }
    if (!c.eat('}')) return false;
  } else {
    c.p++;  // consume '}'
  }
  // missing children → null (recursively)
  for (int k : kids)
    if (!p->g_seen[k]) {
      push_null_recursive(p, k);
      if (discover) p->d_missing.push_back(k);
    }
  return true;
}

// the general (any-shape) row parse
bool parse_row_general(Parser* p, const uint8_t* b, const uint8_t* e,
                       uint64_t r) {
  p->g_seen.assign(p->nodes.size(), 0);
  p->d_vs.clear();
  p->d_ve.clear();
  p->d_col.clear();
  p->d_present.clear();
  p->d_missing.clear();
  p->d_ok = true;

  Cursor probe{b, e};
  probe.ws();
  const bool is_object = probe.p < probe.end && *probe.p == '{';
  Cursor c{b, e};
  if (!parse_struct_body(p, -1, c, b, true)) {
    rollback_row(p, p->nrows);
    p->error = (is_object ? "malformed JSON at row "
                          : "expected '{' at row ") +
               std::to_string(r);
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

// flat ABI (top-level scalar columns only) — kept for the historical
// callers; a flat schema is just a tree whose nodes are all top-level
void* jp_create(int ncols, const char** names, const int* types) {
  Parser* p = new Parser();
  p->nodes.resize(ncols);
  for (int i = 0; i < ncols; i++) {
    p->nodes[i].name = names[i];
    p->nodes[i].type = types[i];
    p->nodes[i].str_offsets.push_back(0);
    p->top.push_back(i);
  }
  return p;
}

// full schema tree.  nodes come in any order with parent[i] either -1
// (top-level field, order significant) or the index of a struct node /
// a type-6 list node (whose single child is its element subtree).
// types: 0..3 scalar, 4 struct, 5 list-of-scalar with elem_types[i]
// 0..3, 6 generic list.
void* jp_create_tree(int nnodes, const char** names, const int* types,
                     const int* elem_types, const int* parents) {
  Parser* p = new Parser();
  p->nodes.resize(nnodes);
  for (int i = 0; i < nnodes; i++) {
    Node& nd = p->nodes[i];
    nd.name = names[i];
    nd.type = types[i];
    nd.elem_type = elem_types[i];
    nd.str_offsets.push_back(0);
    nd.list_offsets.assign((nd.type == 5 || nd.type == 6) ? 1 : 0, 0);
    if (parents[i] < 0)
      p->top.push_back(i);
    else
      p->nodes[parents[i]].kids.push_back(i);
  }
  return p;
}

void jp_clear(void* h) {
  Parser* p = static_cast<Parser*>(h);
  p->nrows = 0;
  p->error.clear();
  for (auto& nd : p->nodes) {
    nd.i64.clear();
    nd.f64.clear();
    nd.b.clear();
    nd.valid.clear();
    nd.str_bytes.clear();
    nd.str_offsets.assign(1, 0);
    nd.evalid.clear();
    if (nd.type == 5 || nd.type == 6) nd.list_offsets.assign(1, 0);
  }
}

// returns 0 on success, -1 on parse error (see jp_error)
int jp_parse(void* h, const uint8_t* data, const uint64_t* offsets,
             uint64_t nrows) {
  Parser* p = static_cast<Parser*>(h);
  for (auto& nd : p->nodes) {
    nd.valid.reserve(nd.valid.size() + nrows);
    switch (nd.type) {
      case 0: nd.i64.reserve(nd.i64.size() + nrows); break;
      case 1: nd.f64.reserve(nd.f64.size() + nrows); break;
      case 2: nd.b.reserve(nd.b.size() + nrows); break;
      case 3:
        nd.str_offsets.reserve(nd.str_offsets.size() + nrows);
        break;
      case 5:
      case 6:
        nd.list_offsets.reserve(nd.list_offsets.size() + nrows);
        break;
    }
  }
  for (uint64_t r = 0; r < nrows; r++) {
    const uint8_t* b = data + offsets[r];
    const uint8_t* e = data + offsets[r + 1];
    if (p->layout.valid) {
      if (fast_row(p, b, e)) {
        p->layout.fail_streak = 0;
        p->nrows++;
        continue;
      }
      rollback_row(p, p->nrows);
      // a producer whose shape keeps missing the layout (mixed styles,
      // varying key sets) must not pay fast-attempt + rollback + layout
      // re-adoption per row forever: after 8 straight misses, disable
      // the fast path and suppress re-adoption for a stretch of rows
      if (++p->layout.fail_streak >= 8) {
        p->layout.valid = false;
        p->layout.fail_streak = 0;
        p->adopt_cooldown = 256;
      }
    }
    if (!parse_row_general(p, b, e, r)) return -1;
    if (p->adopt_cooldown > 0)
      p->adopt_cooldown--;
    else
      adopt_layout(p, b, e);
    p->nrows++;
  }
  return 0;
}

const char* jp_error(void* h) {
  return static_cast<Parser*>(h)->error.c_str();
}

uint64_t jp_nrows(void* h) { return static_cast<Parser*>(h)->nrows; }

const int64_t* jp_col_i64(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].i64.data();
}
const double* jp_col_f64(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].f64.data();
}
const uint8_t* jp_col_bool(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].b.data();
}
const uint8_t* jp_col_valid(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].valid.data();
}
const uint8_t* jp_col_str_bytes(void* h, int col, uint64_t* nbytes) {
  Node& c = static_cast<Parser*>(h)->nodes[col];
  *nbytes = c.str_bytes.size();
  return c.str_bytes.data();
}
const uint64_t* jp_col_str_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].str_offsets.data();
}
// list node accessors: per-row offsets (nrows+1), element validity, and
// element count; element VALUES come through the scalar getters above
// (a list node stores its elements in its own value vectors)
const uint64_t* jp_col_list_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].list_offsets.data();
}
const uint8_t* jp_col_list_evalid(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].evalid.data();
}
uint64_t jp_col_list_nelems(void* h, int col) {
  return list_elems(static_cast<Parser*>(h)->nodes[col]);
}
int64_t jp_col_str_dict(void* h, int col) {
  Parser* p = static_cast<Parser*>(h);
  Node& c = p->nodes[col];
  // entry count: packed scalar-list elements live in the list node's own
  // vectors; every other node (including string nodes under a generic
  // list) pushes one valid byte per entry
  uint64_t n = c.type == 5 ? list_elems(c) : c.valid.size();
  return build_str_dict(c.str_bytes, c.str_offsets, n, c.dict);
}
const int32_t* jp_col_str_dict_codes(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].dict.codes.data();
}
const uint8_t* jp_col_str_dict_bytes(void* h, int col, uint64_t* nbytes) {
  StrDict& d = static_cast<Parser*>(h)->nodes[col].dict;
  *nbytes = d.bytes.size();
  return d.bytes.data();
}
const uint64_t* jp_col_str_dict_offsets(void* h, int col) {
  return static_cast<Parser*>(h)->nodes[col].dict.offsets.data();
}

void jp_destroy(void* h) { delete static_cast<Parser*>(h); }

}  // extern "C"
