// native_test — self-contained exercises of the C++ components, built with
// -fsanitize=address,undefined by tests/test_native_sanitizers.py.  The
// reference ships no sanitizer coverage at all (SURVEY.md §5: "race
// detection/sanitizers: none"); this is our answer for the native runtime.
//
// Exercises: LSM store (put/get/delete/recovery/compaction), string
// interner (growth, duplicates, width changes), JSON parser (escapes,
// nulls, duplicates, malformed rows).

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// single-TU build: include the component sources directly
#include "avro_parser.cpp"
#include "interner.cpp"
#include "json_parser.cpp"
#include "kafka_client.cpp"
#include "lsmkv.cpp"

static void test_lsm(const char* dir) {
  void* s = lsm_open(dir);
  assert(s);
  for (int i = 0; i < 2000; i++) {
    char k[32], v[64];
    int kl = snprintf(k, sizeof k, "key-%d", i % 500);
    int vl = snprintf(v, sizeof v, "value-%d-%d", i, i * 7);
    assert(lsm_put(s, (const uint8_t*)k, kl, (const uint8_t*)v, vl) == 0);
  }
  for (int i = 0; i < 100; i += 2) {
    char k[32];
    int kl = snprintf(k, sizeof k, "key-%d", i);
    lsm_delete(s, (const uint8_t*)k, kl);
  }
  assert(lsm_count(s) == 450);
  uint8_t* out = nullptr;
  int64_t n = lsm_get(s, (const uint8_t*)"key-1", 5, &out);
  assert(n > 0);
  lsm_free(out);
  assert(lsm_get(s, (const uint8_t*)"key-0", 5, &out) == -1);
  lsm_flush(s);
  lsm_close(s);
  // reopen (recovery) + compaction
  s = lsm_open(dir);
  assert(lsm_count(s) == 450);
  assert(lsm_compact(s) == 0);
  assert(lsm_count(s) == 450);
  n = lsm_get(s, (const uint8_t*)"key-499", 7, &out);
  assert(n > 0);
  lsm_free(out);
  lsm_close(s);
  printf("lsm ok\n");
}

static void test_interner() {
  void* h = intern_create();
  const uint32_t w = 12;
  std::vector<uint8_t> buf;
  std::vector<int32_t> ids;
  const int N = 50000;
  buf.resize((size_t)N * w, 0);
  ids.resize(N);
  for (int i = 0; i < N; i++) {
    char tmp[16];
    int len = snprintf(tmp, sizeof tmp, "k%d", i % 7000);
    memcpy(buf.data() + (size_t)i * w, tmp, (size_t)len);
  }
  intern_many(h, buf.data(), N, w, ids.data());
  assert(intern_count(h) == 7000);
  // stability: same keys → same ids
  std::vector<int32_t> ids2(N);
  intern_many(h, buf.data(), N, w, ids2.data());
  assert(memcmp(ids.data(), ids2.data(), N * 4) == 0);
  // width change re-lookup
  const uint32_t w2 = 20;
  std::vector<uint8_t> buf2((size_t)N * w2, 0);
  for (int i = 0; i < N; i++) {
    char tmp[16];
    int len = snprintf(tmp, sizeof tmp, "k%d", i % 7000);
    memcpy(buf2.data() + (size_t)i * w2, tmp, (size_t)len);
  }
  std::vector<int32_t> ids3(N);
  intern_many(h, buf2.data(), N, w2, ids3.data());
  assert(memcmp(ids.data(), ids3.data(), N * 4) == 0);
  uint8_t key[64];
  uint32_t kl = intern_key(h, ids[0], key, sizeof key);
  assert(kl == 2 && memcmp(key, "k0", 2) == 0);
  intern_destroy(h);
  printf("interner ok\n");
}

static void test_json() {
  const char* names[3] = {"a", "s", "f"};
  int types[3] = {0, 3, 1};
  void* p = jp_create(3, names, types);
  std::string rows;
  std::vector<uint64_t> offs{0};
  auto add = [&](const char* r) {
    rows += r;
    offs.push_back(rows.size());
  };
  add("{\"a\": 42, \"s\": \"he\\u00e9llo\", \"f\": -1.5e3}");
  add("{\"s\": null, \"a\": -7, \"extra\": {\"x\": [1, 2, {}]}, \"f\": 0.25}");
  add("{\"a\": 1, \"a\": 2, \"s\": \"dup\", \"f\": 1}");
  add("{}");
  int rc = jp_parse(p, (const uint8_t*)rows.data(), offs.data(),
                    offs.size() - 1);
  assert(rc == 0);
  assert(jp_nrows(p) == 4);
  const int64_t* av = jp_col_i64(p, 0);
  assert(av[0] == 42 && av[1] == -7 && av[2] == 2);
  const uint8_t* valid = jp_col_valid(p, 1);
  assert(valid[0] == 1 && valid[1] == 0 && valid[3] == 0);
  uint64_t nb;
  jp_col_str_bytes(p, 1, &nb);
  assert(nb > 0);
  // malformed input reports an error (fresh parser)
  jp_clear(p);
  std::string bad = "{\"a\": nope}";
  uint64_t boffs[2] = {0, bad.size()};
  assert(jp_parse(p, (const uint8_t*)bad.data(), boffs, 1) == -1);
  assert(strlen(jp_error(p)) > 0);
  // payload truncated MID-NUMBER at the exact end of the arena: the number
  // scan must stop at the boundary (ASan redzones on the heap-exact buffer
  // catch any strtoll/strtod overread) and the row must error cleanly
  for (const char* t : {"{\"a\": 123", "{\"f\": -1.5e", "{\"a\": "}) {
    jp_clear(p);
    std::string tr = t;
    std::vector<uint8_t> exact(tr.begin(), tr.end());
    uint64_t toffs[2] = {0, tr.size()};
    assert(jp_parse(p, exact.data(), toffs, 1) == -1);
  }
  // partial-consumption tokens must fail the row, not silently truncate
  // ("1e5" on an int column would otherwise store 1)
  for (const char* t : {"{\"a\": 1e5}", "{\"a\": 12.5}", "{\"f\": 1.2.3}"}) {
    jp_clear(p);
    std::string tr = t;
    std::vector<uint8_t> exact(tr.begin(), tr.end());
    uint64_t toffs[2] = {0, tr.size()};
    assert(jp_parse(p, exact.data(), toffs, 1) == -1);
  }
  // a long-but-legal numeric token (>47 chars) still parses — arbitrary
  // precision decimals are valid JSON
  {
    jp_clear(p);
    std::string lng =
        "{\"a\": 7, \"s\": \"x\", \"f\": 1" + std::string(60, '0') + ".5}";
    std::vector<uint8_t> exact(lng.begin(), lng.end());
    uint64_t loffs[2] = {0, lng.size()};
    assert(jp_parse(p, exact.data(), loffs, 1) == 0);
    assert(jp_col_f64(p, 2)[0] == 1e60);
  }
  jp_destroy(p);
  printf("json ok\n");
}

static void test_json_fast_layout() {
  // the adaptive-layout fast path: identical-shape rows adopt a layout
  // after the first general-path parse; deviating rows roll back and
  // reparse.  Heap-exact buffers put ASan redzones right at every row
  // boundary, so any fast-path overread (memcmp/memchr/num scan) traps.
  const char* names[3] = {"a", "s", "f"};
  int types[3] = {0, 3, 1};
  void* p = jp_create(3, names, types);
  std::string rows;
  std::vector<uint64_t> offs{0};
  auto add = [&](const std::string& r) {
    rows += r;
    offs.push_back(rows.size());
  };
  // 32 identical-shape rows (fast path from row 1 on)
  for (int i = 0; i < 32; i++)
    add("{\"a\":" + std::to_string(i) + ",\"s\":\"k" + std::to_string(i) +
        "\",\"f\":" + std::to_string(i) + ".5}");
  // deviations mid-stream: reorder, escape in string, null value,
  // missing key, unknown key, json.dumps spacing — each must fall back
  // (rollback) and reparse correctly, then re-adopt
  add("{\"s\":\"re\",\"a\":900,\"f\":1.0}");
  add("{\"a\":901,\"s\":\"q\\\"x\\\\y\",\"f\":2.0}");
  add("{\"a\":null,\"s\":\"n\",\"f\":3.0}");
  add("{\"a\":903,\"f\":4.0}");
  add("{\"a\":904,\"s\":\"u\",\"zz\":[1,{\"q\":2}],\"f\":5.0}");
  add("{\"a\": 905, \"s\": \"sp\", \"f\": 6.0}");
  // back to the fast shape
  for (int i = 0; i < 8; i++)
    add("{\"a\":" + std::to_string(1000 + i) + ",\"s\":\"t\",\"f\":0.25}");
  {
    std::vector<uint8_t> exact(rows.begin(), rows.end());
    int rc = jp_parse(p, exact.data(), offs.data(), offs.size() - 1);
    assert(rc == 0);
    assert(jp_nrows(p) == 32 + 6 + 8);
    const int64_t* av = jp_col_i64(p, 0);
    const uint8_t* valid = jp_col_valid(p, 0);
    for (int i = 0; i < 32; i++) assert(av[i] == i);
    assert(av[32] == 900 && av[33] == 901);
    assert(valid[34] == 0);            // null a
    assert(av[35] == 903 && av[36] == 904 && av[37] == 905);
    for (int i = 0; i < 8; i++) assert(av[38 + i] == 1000 + i);
    const uint8_t* svalid = jp_col_valid(p, 1);
    assert(svalid[35] == 0);           // missing s
    const double* fv = jp_col_f64(p, 2);
    assert(fv[33] == 2.0 && fv[45] == 0.25);
  }
  // truncated rows WITH an armed layout: fast path must stop at the row
  // boundary, roll back, and the general path reports the error
  for (const char* t :
       {"{\"a\":7,\"s\":\"x\",\"f\":1.", "{\"a\":7,\"s\":\"x", "{\"a\":7,"}) {
    jp_clear(p);
    // re-arm the layout on the fast shape first
    std::string warm = "{\"a\":1,\"s\":\"w\",\"f\":2.0}";
    std::string tr = t;
    std::string both = warm + tr;
    std::vector<uint8_t> exact(both.begin(), both.end());
    uint64_t toffs[3] = {0, warm.size(), both.size()};
    assert(jp_parse(p, exact.data(), toffs, 2) == -1);
    assert(strlen(jp_error(p)) > 0);
  }
  jp_destroy(p);
  printf("json fast layout ok\n");
}

static void test_json_tree() {
  // the shredded node-tree ABI: nested structs to depth 2, a list of
  // strings, null/missing/duplicate/unknown-key handling, and the
  // adaptive layout over nested shapes.  Heap-exact buffers put ASan
  // redzones at every row boundary.
  //   0 id(str)  1 imu(struct)  2 ts(i64, p=1)  3 gps(struct, p=1)
  //   4 lat(f64, p=3)  5 spd(f64, p=3)  6 tags(list<str>)
  const char* names[7] = {"id", "imu", "ts", "gps", "lat", "spd", "tags"};
  int types[7] = {3, 4, 0, 4, 1, 1, 5};
  int etypes[7] = {-1, -1, -1, -1, -1, -1, 3};
  int parents[7] = {-1, -1, 1, 1, 3, 3, -1};
  void* p = jp_create_tree(7, names, types, etypes, parents);
  std::string rows;
  std::vector<uint64_t> offs{0};
  auto add = [&](const std::string& r) {
    rows += r;
    offs.push_back(rows.size());
  };
  // fixed nested shape — layout adoption must cover leaves inside structs
  for (int i = 0; i < 16; i++)
    add("{\"id\":\"d" + std::to_string(i) + "\",\"imu\":{\"ts\":" +
        std::to_string(i) + ",\"gps\":{\"lat\":1.5,\"spd\":2.5}},\"tags\":"
        "[\"a\",\"b\"]}");
  add("{\"id\":\"x\",\"imu\":null,\"tags\":[]}");               // null struct
  add("{\"id\":\"y\",\"imu\":{\"gps\":null},\"tags\":null}");   // inner null
  add("{\"id\":\"z\",\"imu\":{\"ts\":7,\"gps\":{\"lat\":9.5,\"spd\":8.5},"
      "\"junk\":{\"a\":[1]}},\"tags\":[\"q\",null]}");          // unknown key
  add("{\"imu\":{\"ts\":1,\"gps\":{\"lat\":0.0,\"spd\":0.0}},"
      "\"imu\":{\"ts\":99,\"gps\":{\"lat\":7.5,\"spd\":6.5}},"
      "\"id\":\"dup\",\"tags\":[\"w\"]}");                      // dup struct
  {
    std::vector<uint8_t> exact(rows.begin(), rows.end());
    assert(jp_parse(p, exact.data(), offs.data(), offs.size() - 1) == 0);
    uint64_t n = jp_nrows(p);
    assert(n == 20);
    const int64_t* ts = jp_col_i64(p, 2);
    const uint8_t* tsv = jp_col_valid(p, 2);
    for (int i = 0; i < 16; i++) assert(ts[i] == i && tsv[i] == 1);
    assert(tsv[16] == 0 && tsv[17] == 0);  // null imu / missing ts
    const uint8_t* imup = jp_col_valid(p, 1);
    const uint8_t* gpsp = jp_col_valid(p, 3);
    assert(imup[16] == 0 && gpsp[16] == 0);
    assert(imup[17] == 1 && gpsp[17] == 0);
    assert(ts[18] == 7 && ts[19] == 99);  // dup: last wins
    const double* lat = jp_col_f64(p, 4);
    assert(lat[18] == 9.5 && lat[19] == 7.5);
    const uint64_t* lo = jp_col_list_offsets(p, 6);
    assert(lo[16] - lo[0] == 32);          // 16 rows x 2 elems
    assert(lo[17] == lo[16]);              // []
    assert(lo[18] == lo[17]);              // null list
    assert(lo[19] - lo[18] == 2);          // ["q", null]
    const uint8_t* ev = jp_col_list_evalid(p, 6);
    assert(ev[lo[18]] == 1 && ev[lo[18] + 1] == 0);
    const uint8_t* lv = jp_col_valid(p, 6);
    assert(lv[16] == 1 && lv[17] == 0);
    assert(jp_col_list_nelems(p, 6) == lo[20]);
  }
  // truncation inside a nested value with an armed layout
  for (const char* t :
       {"{\"id\":\"t\",\"imu\":{\"ts\":1,\"gps\":{\"lat\":1.5,",
        "{\"id\":\"t\",\"imu\":{\"ts\":1", "{\"id\":\"t\",\"tags\":[\"a\""}) {
    jp_clear(p);
    std::string warm =
        "{\"id\":\"w\",\"imu\":{\"ts\":0,\"gps\":{\"lat\":1.5,\"spd\":2.5}},"
        "\"tags\":[\"a\",\"b\"]}";
    std::string both = warm + t;
    std::vector<uint8_t> exact(both.begin(), both.end());
    uint64_t toffs[3] = {0, warm.size(), both.size()};
    assert(jp_parse(p, exact.data(), toffs, 2) == -1);
    assert(strlen(jp_error(p)) > 0);
  }
  jp_destroy(p);
  printf("json tree ok\n");
}

static void test_json_generic_lists() {
  // type-6 generic lists (PR 2): list-of-struct and list-of-list with
  // null elements, missing/duplicate keys inside elements, layout
  // adoption over the opaque list units, and mid-list truncation
  // rollback.  Heap-exact buffers put ASan redzones at the row ends.
  //   0 id(i64)  1 evts(list<struct>)  2 item(struct,p=1)  3 k(i64,p=2)
  //   4 s(str,p=2)  5 m(list<list<i64>>)  6 inner(list<i64>,p=5)
  const char* names[7] = {"id", "evts", "item", "k", "s", "m", "inner"};
  int types[7] = {0, 6, 4, 0, 3, 6, 5};
  int etypes[7] = {-1, -1, -1, -1, -1, -1, 0};
  int parents[7] = {-1, -1, 1, 2, 2, -1, 5};
  void* p = jp_create_tree(7, names, types, etypes, parents);
  std::string rows;
  std::vector<uint64_t> offs{0};
  auto add = [&](const std::string& r) {
    rows += r;
    offs.push_back(rows.size());
  };
  for (int i = 0; i < 12; i++)  // fixed shape: layout adoption
    add("{\"id\":" + std::to_string(i) +
        ",\"evts\":[{\"k\":1,\"s\":\"a\"},{\"k\":2,\"s\":\"b\"}],"
        "\"m\":[[1,2],[3]]}");
  add("{\"id\":100,\"evts\":[],\"m\":[]}");
  add("{\"id\":101,\"evts\":null,\"m\":null}");
  add("{\"id\":102,\"evts\":[null,{\"s\":\"y\",\"zz\":7}],"
      "\"m\":[null,[4,null]]}");  // null elem, missing k, unknown key
  add("{\"id\":103,\"evts\":[{\"k\":5,\"k\":6}],\"m\":[[]]}");  // dup in elem
  {
    std::vector<uint8_t> exact(rows.begin(), rows.end());
    assert(jp_parse(p, exact.data(), offs.data(), offs.size() - 1) == 0);
    assert(jp_nrows(p) == 16);
    const uint64_t* eo = jp_col_list_offsets(p, 1);
    assert(eo[12] == 24 && eo[13] == 24);   // 12 x 2 elems, then []
    assert(eo[14] == 24);                   // null list: no elems
    assert(eo[15] - eo[14] == 2 && eo[16] - eo[15] == 1);
    const uint8_t* ep = jp_col_valid(p, 2);  // element struct presence
    assert(ep[24] == 0 && ep[25] == 1);      // [null, {...}]
    const int64_t* kv = jp_col_i64(p, 3);
    const uint8_t* kvv = jp_col_valid(p, 3);
    assert(kvv[25] == 0);                    // missing k -> null leaf
    assert(kv[26] == 6 && kvv[26] == 1);     // dup key: last wins
    const uint8_t* lv = jp_col_valid(p, 1);
    assert(lv[12] == 1 && lv[13] == 0 && lv[14] == 1);
    // list-of-list: outer offsets index INNER list entries
    const uint64_t* mo = jp_col_list_offsets(p, 5);
    const uint64_t* io = jp_col_list_offsets(p, 6);
    const uint8_t* iv = jp_col_valid(p, 6);
    assert(mo[12] == 24);                    // 12 x 2 inner lists
    assert(mo[15] - mo[14] == 2);            // [null, [4, null]]
    assert(iv[mo[14]] == 0 && iv[mo[14] + 1] == 1);
    uint64_t in0 = mo[14] + 1;               // the [4, null] inner entry
    assert(io[in0 + 1] - io[in0] == 2);
    const uint8_t* iev = jp_col_list_evalid(p, 6);
    assert(iev[io[in0]] == 1 && iev[io[in0] + 1] == 0);
    assert(jp_col_i64(p, 6)[io[in0]] == 4);
  }
  // truncation mid-element with an armed layout: rollback must trim the
  // whole nested subtree (trim_node through offsets), caught by ASan if
  // any vector is left inconsistent
  for (const char* t :
       {"{\"id\":1,\"evts\":[{\"k\":1,\"s\":\"a\"},{\"k\":",
        "{\"id\":1,\"m\":[[1,", "{\"id\":1,\"evts\":[null,"}) {
    jp_clear(p);
    std::string warm =
        "{\"id\":0,\"evts\":[{\"k\":1,\"s\":\"a\"},{\"k\":2,\"s\":\"b\"}],"
        "\"m\":[[1,2],[3]]}";
    std::string both = warm + t;
    std::vector<uint8_t> exact(both.begin(), both.end());
    uint64_t toffs[3] = {0, warm.size(), both.size()};
    assert(jp_parse(p, exact.data(), toffs, 2) == -1);
    assert(jp_nrows(p) == 1);  // the warm row survived the rollback
  }
  jp_destroy(p);
  printf("json generic lists ok\n");
}

static void zz(std::vector<uint8_t>& out, int64_t v) {
  uint64_t z = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
  while (z >= 0x80) {
    out.push_back((uint8_t)(z | 0x80));
    z >>= 7;
  }
  out.push_back((uint8_t)z);
}

static void test_avro() {
  // schema: long ts, nullable double v, string name, bool ok
  int types[4] = {0, 1, 3, 2};
  int nulls[4] = {0, 1, 0, 0};
  void* p = ap_create(4, types, nulls);
  std::vector<uint8_t> arena;
  std::vector<uint64_t> offs{0};
  auto rec = [&](int64_t ts, bool has_v, double v, const char* s, bool ok) {
    zz(arena, ts);
    zz(arena, has_v ? 1 : 0);
    if (has_v) {
      const uint8_t* b = (const uint8_t*)&v;
      arena.insert(arena.end(), b, b + 8);
    }
    zz(arena, (int64_t)strlen(s));
    arena.insert(arena.end(), (const uint8_t*)s, (const uint8_t*)s + strlen(s));
    arena.push_back(ok ? 1 : 0);
    offs.push_back(arena.size());
  };
  rec(1700000000000LL, true, 2.5, "alpha", true);
  rec(-42, false, 0, "", false);
  rec(7, true, -1.25, "日本", true);
  assert(ap_parse(p, arena.data(), offs.data(), 3) == 0);
  assert(ap_nrows(p) == 3);
  const int64_t* ts = ap_col_i64(p, 0);
  assert(ts[0] == 1700000000000LL && ts[1] == -42 && ts[2] == 7);
  const uint8_t* valid = ap_col_valid(p, 1);
  assert(valid[0] == 1 && valid[1] == 0 && valid[2] == 1);
  const double* v = ap_col_f64(p, 1);
  assert(v[0] == 2.5 && v[2] == -1.25);
  const uint8_t* okc = ap_col_bool(p, 3);
  assert(okc[0] == 1 && okc[1] == 0 && okc[2] == 1);
  // trailing garbage after the last field must fail the parse
  ap_clear(p);
  std::vector<uint8_t> bad(arena.begin(), arena.begin() + (long)offs[1]);
  bad.push_back(0xAB);
  uint64_t boffs[2] = {0, bad.size()};
  assert(ap_parse(p, bad.data(), boffs, 1) == -1);
  // sanitizer fuzz: truncations + single-byte corruptions of a valid arena
  for (uint64_t n = 0; n <= offs[1]; n++) {
    ap_clear(p);
    uint64_t toffs[2] = {0, n};
    std::vector<uint8_t> exact(arena.begin(), arena.begin() + (long)n);
    ap_parse(p, exact.data(), toffs, 1);
  }
  for (size_t i = 0; i < offs[1]; i++)
    for (uint8_t x : {uint8_t{0xFF}, uint8_t{0x80}, uint8_t{0x01}}) {
      ap_clear(p);
      std::vector<uint8_t> m(arena.begin(), arena.begin() + (long)offs[1]);
      m[i] ^= x;
      uint64_t moffs[2] = {0, m.size()};
      ap_parse(p, m.data(), moffs, 1);
    }
  ap_destroy(p);
  printf("avro ok\n");
}

static void test_avro_tree() {
  // the schema-tree ABI (PR 2): nested records, arrays of records,
  // arrays of arrays, nullable at every level; block-encoded arrays
  // with negative counts; truncation rollback; count-bomb rejection.
  //   0 id(i64)  1 imu(rec,nullable)  2 ts(i64,p=1)  3 gps(rec,p=1,nul)
  //   4 lat(f64,p=3)  5 readings(list,p=-1)  6 elem(rec,p=5)
  //   7 k(i64,p=6)  8 m(list)  9 inner(list,p=8)  10 x(i64,p=9)
  int types[11] = {0, 5, 0, 5, 1, 6, 5, 0, 6, 6, 0};
  int nulls[11] = {0, 1, 0, 1, 0, 0, 0, 1, 0, 1, 0};
  int parents[11] = {-1, -1, 1, 1, 3, -1, 5, 6, -1, 8, 9};
  void* p = ap_create_tree(11, types, nulls, parents);
  std::vector<uint8_t> arena;
  std::vector<uint64_t> offs{0};
  auto rec = [&](int64_t id, bool imu_null, bool gps_null, int nread,
                 int ninner) {
    zz(arena, id);
    zz(arena, imu_null ? 0 : 1);  // imu union branch
    if (!imu_null) {
      zz(arena, 42);              // ts
      zz(arena, gps_null ? 0 : 1);
      if (!gps_null) {
        double lat = 1.5;
        const uint8_t* b = (const uint8_t*)&lat;
        arena.insert(arena.end(), b, b + 8);
      }
    }
    if (nread) {
      zz(arena, nread);
      for (int i = 0; i < nread; i++) {
        zz(arena, i % 2);          // k union branch: alternate null
        if (i % 2) zz(arena, 7);
      }
    }
    zz(arena, 0);                  // readings terminator
    if (ninner) {
      zz(arena, -ninner);          // negative block count + byte size
      zz(arena, 1);                // (size not validated, items decoded)
      for (int i = 0; i < ninner; i++) {
        zz(arena, 1);              // inner union branch: present
        zz(arena, 2);              // one element
        zz(arena, (int64_t)i);
        zz(arena, (int64_t)-i);
        zz(arena, 0);              // inner terminator
      }
    }
    zz(arena, 0);                  // m terminator
    offs.push_back(arena.size());
  };
  rec(1, false, false, 2, 2);
  rec(2, true, false, 0, 0);
  rec(3, false, true, 3, 1);
  {
    std::vector<uint8_t> exact(arena);
    assert(ap_parse(p, exact.data(), offs.data(), 3) == 0);
    assert(ap_nrows(p) == 3);
    const uint8_t* imup = ap_col_valid(p, 1);
    assert(imup[0] == 1 && imup[1] == 0 && imup[2] == 1);
    const uint8_t* gpsp = ap_col_valid(p, 3);
    assert(gpsp[0] == 1 && gpsp[1] == 0 && gpsp[2] == 0);
    assert(ap_col_f64(p, 4)[0] == 1.5);
    const uint64_t* ro = ap_col_list_offsets(p, 5);
    assert(ro[1] == 2 && ro[2] == 2 && ro[3] == 5);
    const uint8_t* kp = ap_col_valid(p, 7);
    assert(kp[0] == 0 && kp[1] == 1);  // alternating null ks
    assert(ap_col_i64(p, 7)[1] == 7);
    const uint64_t* mo = ap_col_list_offsets(p, 8);
    assert(mo[1] == 2 && mo[3] == 3);  // 2 + 0 + 1 inner lists
    const uint64_t* io = ap_col_list_offsets(p, 9);
    assert(io[1] == 2 && ap_col_i64(p, 10)[0] == 0);
    assert(ap_col_i64(p, 10)[1] == 0);  // -0 zigzag
  }
  // truncations at every byte boundary of the arena: rollback must keep
  // every node subtree consistent (ASan catches stale sizes)
  for (size_t cut = 0; cut < offs[1]; cut++) {
    ap_clear(p);
    std::vector<uint8_t> exact(arena.begin(), arena.begin() + cut);
    uint64_t toffs[2] = {0, cut};
    assert(ap_parse(p, exact.data(), toffs, 1) == -1);
    assert(ap_nrows(p) == 0);
  }
  // array count bomb: tiny payload declaring 2^30 items must fail, not
  // allocate
  {
    ap_clear(p);
    std::vector<uint8_t> bomb;
    zz(bomb, 9);       // id
    zz(bomb, 0);       // imu null
    zz(bomb, 1 << 30); // readings count
    uint64_t boffs[2] = {0, bomb.size()};
    std::vector<uint8_t> exact(bomb);
    assert(ap_parse(p, exact.data(), boffs, 1) == -1);
  }
  ap_destroy(p);
  // repeated-block bomb (review-found): array<empty record> elements
  // consume ZERO wire bytes, so the per-block remaining-bytes cap admits
  // 65536 items per ~3-byte block forever — the cumulative per-record
  // element budget must stop it after the first block
  {
    int types2[2] = {6, 5};
    int nulls2[2] = {0, 0};
    int parents2[2] = {-1, 0};
    void* p2 = ap_create_tree(2, types2, nulls2, parents2);
    std::vector<uint8_t> bomb;
    for (int b = 0; b < 200; b++) zz(bomb, 65536);
    zz(bomb, 0);
    uint64_t boffs[2] = {0, bomb.size()};
    std::vector<uint8_t> exact(bomb);
    assert(ap_parse(p2, exact.data(), boffs, 1) == -1);
    // a small array of empty records stays legal
    ap_clear(p2);
    std::vector<uint8_t> ok;
    zz(ok, 3);
    zz(ok, 0);
    uint64_t ooffs[2] = {0, ok.size()};
    std::vector<uint8_t> exact2(ok);
    assert(ap_parse(p2, exact2.data(), ooffs, 1) == 0);
    assert(ap_col_list_offsets(p2, 0)[1] == 3);
    ap_destroy(p2);
  }
  printf("avro tree ok\n");
}

static void test_codecs() {
  // valid raw-snappy: "hellohellohello!" via literal + overlapping copy
  std::string want = "hellohellohello!";
  std::vector<uint8_t> sn;
  sn.push_back((uint8_t)want.size());     // uvarint len (16)
  sn.push_back((5 - 1) << 2);             // literal "hello"
  sn.insert(sn.end(), want.begin(), want.begin() + 5);
  sn.push_back(((10 - 4) << 2) | 1);      // type-1 copy off=5 len=10
  sn.push_back(5);
  sn.push_back((1 - 1) << 2);             // literal "!"
  sn.push_back('!');
  std::vector<uint8_t> out;
  assert(snappy_decompress(sn.data(), sn.size(), out));
  assert(std::string(out.begin(), out.end()) == want);

  // valid lz4 frame: one block, literals + match(off=2,len=8) + literals
  std::string lw = "ababababab-tail";
  std::vector<uint8_t> blk;
  blk.push_back((2 << 4) | (8 - 4));      // lit 2, match 8
  blk.push_back('a');
  blk.push_back('b');
  blk.push_back(2);                       // offset LE16 = 2
  blk.push_back(0);
  blk.push_back(5 << 4);                  // last sequence: 5 literals
  const char* tail = "-tail";
  blk.insert(blk.end(), tail, tail + 5);
  std::vector<uint8_t> fr;
  uint32_t magic = 0x184D2204u;
  for (int i = 0; i < 4; i++) fr.push_back((uint8_t)(magic >> (8 * i)));
  fr.push_back(0x40);  // FLG v1
  fr.push_back(0x40);  // BD
  fr.push_back(0x00);  // header checksum (not validated)
  uint32_t bsz = (uint32_t)blk.size();
  for (int i = 0; i < 4; i++) fr.push_back((uint8_t)(bsz >> (8 * i)));
  fr.insert(fr.end(), blk.begin(), blk.end());
  for (int i = 0; i < 4; i++) fr.push_back(0);  // EndMark
  out.clear();
  assert(lz4f_decompress(fr.data(), fr.size(), out));
  assert(std::string(out.begin(), out.end()) == lw);

  // sanitizer fuzz: every truncation and every single-byte corruption of
  // the valid streams must return cleanly (true or false), never read or
  // write out of bounds — this is untrusted broker data
  auto hammer = [&](const std::vector<uint8_t>& v,
                    bool (*fn)(const uint8_t*, size_t,
                               std::vector<uint8_t>&)) {
    std::vector<uint8_t> o;
    for (size_t n = 0; n <= v.size(); n++) fn(v.data(), n, o);
    std::vector<uint8_t> m;
    for (size_t i = 0; i < v.size(); i++)
      for (uint8_t x : {uint8_t{0xFF}, uint8_t{0x80}, uint8_t{0x01}, uint8_t{0x00}}) {
        m = v;
        m[i] ^= x;
        fn(m.data(), m.size(), o);
      }
  };
  hammer(sn, snappy_decompress);
  hammer(fr, lz4f_decompress);
  // xerial-framed snappy, same hammering
  std::vector<uint8_t> xr = {0x82, 'S', 'N', 'A', 'P', 'P', 'Y', 0,
                             0, 0, 0, 1, 0, 0, 0, 1};
  uint32_t bl = (uint32_t)sn.size();
  for (int i = 3; i >= 0; i--) xr.push_back((uint8_t)(bl >> (8 * i)));
  xr.insert(xr.end(), sn.begin(), sn.end());
  out.clear();
  assert(snappy_decompress(xr.data(), xr.size(), out));
  assert(std::string(out.begin(), out.end()) == want);
  hammer(xr, snappy_decompress);
  printf("codecs ok\n");
}

// ---- threaded hammers ----------------------------------------------------
// The engine calls these components from prefetch worker threads with the
// GIL released — the sanitizer build that matters most here is
// -fsanitize=thread (tests/test_native_sanitizers.py builds all of this
// under TSan and under ASan/UBSan; the hammers also run in the plain
// build as ordinary correctness tests).

static void test_lsm_hammer(const char* dir) {
  // one store, 4 threads of put/get/flush on overlapping key sets: the
  // store's internal mutex is the contract (state/checkpoint snapshots
  // and LSM maintenance can touch the global store from several threads)
  std::string d = std::string(dir) + "-hammer";
  void* s = lsm_open(d.c_str());
  assert(s);
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) {
    ts.emplace_back([s, t] {
      char k[32], v[64];
      for (int i = 0; i < 3000; i++) {
        int kl;
        if (i % 3 == 0)  // cross-thread contended keys
          kl = snprintf(k, sizeof k, "shared-%d", i % 50);
        else  // per-thread keys (the common partition-isolated shape)
          kl = snprintf(k, sizeof k, "h%d-%d", t, i % 250);
        int vl = snprintf(v, sizeof v, "val-%d-%d-%d", t, i, i * 31);
        assert(lsm_put(s, (const uint8_t*)k, (uint32_t)kl,
                       (const uint8_t*)v, (uint32_t)vl) == 0);
        if (i % 7 == 0) {
          uint8_t* out = nullptr;
          int64_t n = lsm_get(s, (const uint8_t*)k, (uint32_t)kl, &out);
          assert(n > 0);  // nothing ever deletes these keys
          lsm_free(out);
        }
        if (i % 500 == 499) lsm_flush(s);
      }
    });
  }
  for (auto& th : ts) th.join();
  // the final key population is deterministic even though values race
  assert(lsm_count(s) == 50 + 4 * 250);
  lsm_close(s);
  s = lsm_open(d.c_str());  // recovery after concurrent writes
  assert(lsm_count(s) == 50 + 4 * 250);
  lsm_close(s);
  printf("lsm hammer ok\n");
}

// -- loopback mini-broker: just enough Produce v3 / Fetch v4 to drive the
// real client wire paths from concurrent threads without a Kafka --------
static bool h_recv_all(int fd, uint8_t* d, size_t n) {
  while (n) {
    ssize_t r = ::recv(fd, d, n, 0);
    if (r <= 0) return false;
    d += r;
    n -= (size_t)r;
  }
  return true;
}

static bool h_send_all(int fd, const uint8_t* d, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, d, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    d += w;
    n -= (size_t)w;
  }
  return true;
}

static void hammer_payloads(int nrec, std::string& data,
                            std::vector<uint64_t>& offs) {
  data.clear();
  offs.assign(1, 0);
  for (int i = 0; i < nrec; i++) {
    char buf[32];
    int n = snprintf(buf, sizeof buf, "hammer-%d", i);
    data.append(buf, (size_t)n);
    offs.push_back(data.size());
  }
}

static void hammer_broker_conn(int fd, int nrec) {
  std::string data;
  std::vector<uint64_t> offs;
  hammer_payloads(nrec, data, offs);
  for (;;) {
    uint8_t szb[4];
    if (!h_recv_all(fd, szb, 4)) break;
    uint32_t sz_n;  // memcpy, not a type-punned cast: szb is 1-aligned
    memcpy(&sz_n, szb, 4);
    uint32_t sz = ntohl(sz_n);
    if (sz < 8 || sz > (1u << 24)) break;
    std::vector<uint8_t> req(sz);
    if (!h_recv_all(fd, req.data(), sz)) break;
    uint16_t api_n;
    memcpy(&api_n, req.data(), 2);
    int16_t api = (int16_t)ntohs(api_n);
    uint32_t corr_n;
    memcpy(&corr_n, req.data() + 4, 4);
    Writer body;
    if (api == 0) {  // Produce v3: echo success for topic/partition 0
      body.i32(1);
      body.str("hammer");
      body.i32(1);
      body.i32(0);   // partition
      body.i16(0);   // err
      body.i64(0);   // base offset
      body.i64(-1);  // log append time
    } else {  // Fetch v4: one batch of nrec records from offset 0
      body.i32(0);  // throttle
      body.i32(1);
      body.str("hammer");
      body.i32(1);
      body.i32(0);          // partition
      body.i16(0);          // err
      body.i64(nrec);       // high watermark
      body.i64(nrec);       // last stable offset
      body.i32(0);          // aborted txns
      build_record_batch(body, (const uint8_t*)data.data(), offs.data(),
                         nrec, 1700000000000LL);  // writes i32 len + blob
    }
    Writer resp;
    resp.i32((int32_t)(body.buf.size() + 4));
    resp.append(&corr_n, 4);  // echo correlation id verbatim
    resp.append(body.buf.data(), body.buf.size());
    if (!h_send_all(fd, resp.buf.data(), resp.buf.size())) break;
  }
  close(fd);
}

static void test_kafka_hammer() {
  const int NREC = 5, ITERS = 40, NTHREADS = 4;
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  assert(lfd >= 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  assert(bind(lfd, (sockaddr*)&addr, sizeof addr) == 0);
  assert(listen(lfd, 8) == 0);
  socklen_t alen = sizeof addr;
  assert(getsockname(lfd, (sockaddr*)&addr, &alen) == 0);
  int port = (int)ntohs(addr.sin_port);

  std::atomic<bool> stop{false};
  std::vector<std::thread> conns;
  std::mutex conns_mu;
  std::thread server([&] {
    for (;;) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd < 0) return;  // listen fd closed: shutdown
      std::lock_guard<std::mutex> g(conns_mu);
      if (stop.load()) {
        close(cfd);
        return;
      }
      conns.emplace_back(hammer_broker_conn, cfd, NREC);
    }
  });

  // concurrent init of the dlopen'd TLS surface (std::call_once path —
  // the hand-rolled flag it replaced was a real data race)
  std::atomic<void*> tls_seen{nullptr};
  std::vector<std::thread> tls_threads;
  for (int t = 0; t < NTHREADS; t++) {
    tls_threads.emplace_back([&] {
      void* p = (void*)tls_api();
      void* prev = tls_seen.exchange(p);
      assert(prev == nullptr || prev == p);  // one consistent answer
    });
  }
  for (auto& th : tls_threads) th.join();

  // 4 client objects (the engine's per-partition-reader ownership model)
  // produce+fetch concurrently against the mini-broker: shared process
  // state (crc table, codec statics, TLS api) must be race-free
  std::string data;
  std::vector<uint64_t> offs;
  hammer_payloads(NREC, data, offs);
  std::vector<std::thread> clients;
  for (int t = 0; t < NTHREADS; t++) {
    clients.emplace_back([&, t] {
      char err[256];
      void* h = kc_connect("127.0.0.1", port, err, sizeof err);
      assert(h);
      for (int k = 0; k < ITERS; k++) {
        assert(kc_produce(h, "hammer", 0, (const uint8_t*)data.data(),
                          offs.data(), NREC, 1700000000000LL) == 0);
        int n = kc_fetch(h, "hammer", 0, 0, 1 << 20, 100);
        assert(n == NREC);
        uint64_t nb = 0;
        const uint8_t* rb = kc_rec_bytes(h, &nb);
        const uint64_t* ro = kc_rec_offsets(h);
        assert(nb == data.size());
        for (int i = 0; i < NREC; i++) {
          assert(ro[i + 1] - ro[i] == offs[i + 1] - offs[i]);
          assert(memcmp(rb + ro[i], data.data() + offs[i],
                        (size_t)(offs[i + 1] - offs[i])) == 0);
        }
        assert(kc_next_offset(h) == NREC);
        assert(kc_high_watermark(h) == NREC);
      }
      kc_close(h);
      (void)t;
    });
  }
  for (auto& th : clients) th.join();

  stop.store(true);
  // close(lfd) alone does NOT unblock a thread parked in accept() on
  // Linux — wake it with a throwaway connection, which it will close
  // and exit on (stop is set)
  int wake = socket(AF_INET, SOCK_STREAM, 0);
  if (wake >= 0) {
    connect(wake, (sockaddr*)&addr, sizeof addr);
    close(wake);
  }
  server.join();
  close(lfd);
  {
    std::lock_guard<std::mutex> g(conns_mu);
    for (auto& th : conns) th.join();
  }
  printf("kafka hammer ok\n");
}

static void test_interner_hammer() {
  // one interner per thread (the engine's ownership model: interners are
  // operator-local) — this still hammers the shared allocator under
  // contention, where TSan would catch any accidental global state
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) {
    ts.emplace_back([t] {
      void* h = intern_create();
      const uint32_t w = 12;
      const int N = 20000;
      std::vector<uint8_t> buf((size_t)N * w, 0);
      std::vector<int32_t> ids(N);
      for (int i = 0; i < N; i++) {
        char tmp[16];
        int len = snprintf(tmp, sizeof tmp, "t%d-%d", t, i % 3000);
        memcpy(buf.data() + (size_t)i * w, tmp, (size_t)len);
      }
      intern_many(h, buf.data(), N, w, ids.data());
      assert(intern_count(h) == 3000);
      intern_destroy(h);
    });
  }
  for (auto& th : ts) th.join();
  printf("interner hammer ok\n");
}

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp/native_test_lsm";
  test_lsm(dir);
  test_interner();
  test_json();
  test_json_fast_layout();
  test_json_tree();
  test_json_generic_lists();
  test_avro();
  test_avro_tree();
  test_codecs();
  test_lsm_hammer(dir);
  test_kafka_hammer();
  test_interner_hammer();
  printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
