// avro_parser — one-pass Avro-binary → columnar decoder.
//
// The reference's Avro decode is native (Rust apache-avro through
// DataFusion's avro_to_arrow, crates/core/src/formats/decoders/avro.rs:11-54);
// this is our native equivalent, built like json_parser.cpp: the caller
// hands an arena of concatenated record payloads + offsets (typically the
// Kafka fetch arena, zero-copy) and reads back columnar buffers.
//
// Avro records are positional — no key matching, just the schema's field
// order — so the schema TREE drives the byte walk directly: [nullable-union
// branch varint] then the value per the node type.  Node types:
//   0 = int/long/timestamp-millis (zigzag varint → i64)
//   1 = double (8B IEEE LE → f64)
//   2 = boolean (1 byte)
//   3 = string/bytes (length varint + raw)
//   4 = float (4B IEEE LE, widened to f64 storage)
//   5 = record (struct): presence byte per entry, children positional
//   6 = array (list): block-encoded per the spec (series of counts, 0
//       terminates, negative count + block byte size); the single child
//       node stores one entry per ELEMENT, so nested records and nested
//       arrays shred recursively — the same node layout json_parser.cpp
//       uses for its generic lists.
// Nullable nodes are the ["null", T] union (branch 0 = null, branch 1 =
// value) — the only union shape the native path admits; anything else
// (maps, enums, fixed, general unions) routes to the Python decoder.
//
// ``ap_create`` keeps the historical flat ABI (top-level scalar columns
// only); ``ap_create_tree`` takes the full schema tree.  C ABI for
// ctypes; one parser object per schema; not thread-safe.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include "str_dict.hpp"
#include <vector>

namespace {

// One schema-tree node.  An "entry" is a row for top-level nodes and
// record descendants, and an element for nodes under an array — every
// node appends exactly one `valid` byte per entry, so `valid.size()` is
// always a node's entry count (the invariant rollback relies on).
struct ANode {
  int type;      // 0 i64 | 1 f64 | 2 bool | 3 str | 4 f32 | 5 struct | 6 list
  int nullable;  // ["null", T] union branch varint precedes the value
  std::vector<int> kids;  // record children (field order) / array element
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<uint8_t> b;
  std::vector<uint8_t> valid;
  std::vector<uint8_t> str_bytes;
  std::vector<uint64_t> str_offsets;   // nentries+1
  std::vector<uint64_t> list_offsets;  // list: nentries+1
  StrDict dict;
};

struct AvroParser {
  std::vector<ANode> nodes;
  std::vector<int> top;  // top-level field nodes, schema order
  std::string error;
  uint64_t nrows = 0;
  // cumulative array-element budget for the record being parsed: the
  // per-block cap below bounds one block against remaining BYTES, but
  // zero-byte items (empty-record elements, and nested arrays of them)
  // make unlimited blocks free — this caps total decoded elements per
  // record at a small multiple of its wire size (mirrored by the Python
  // decoder's _decode_blocks budget)
  uint64_t elem_budget = 0;
};

struct Cur {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;
};

// Avro long: zigzag base-128 varint (spec "binary encoding")
int64_t read_varint(Cur& c) {
  uint64_t acc = 0;
  int shift = 0;
  while (c.p < c.end) {
    uint8_t b = *c.p++;
    acc |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80))
      return (int64_t)((acc >> 1) ^ (~(acc & 1) + 1));
    shift += 7;
    if (shift > 63) break;
  }
  c.fail = true;
  return 0;
}

inline uint64_t list_elems(const ANode& nd) {
  return nd.list_offsets.empty() ? 0 : nd.list_offsets.back();
}

void push_null_scalar(ANode& nd) {
  nd.valid.push_back(0);
  switch (nd.type) {
    case 0: nd.i64.push_back(0); break;
    case 1:
    case 4: nd.f64.push_back(0); break;  // float shares the f64 store
    case 2: nd.b.push_back(0); break;
    case 3: nd.str_offsets.push_back(nd.str_bytes.size()); break;
  }
}

// append one null entry to node ni and (for records) every descendant
// (a null array leaves its child untouched — zero elements)
void push_null_recursive(AvroParser* p, int ni) {
  ANode& nd = p->nodes[ni];
  switch (nd.type) {
    case 5:
      nd.valid.push_back(0);
      for (int k : nd.kids) push_null_recursive(p, k);
      break;
    case 6:
      nd.valid.push_back(0);
      nd.list_offsets.push_back(list_elems(nd));
      break;
    default:
      push_null_scalar(nd);
  }
}

// resize node ni and its whole subtree down to exactly `count` entries
// (row rollback: size bookkeeping only, no reallocation)
void trim_node(AvroParser* p, int ni, uint64_t count) {
  ANode& nd = p->nodes[ni];
  nd.valid.resize(count);
  switch (nd.type) {
    case 0: nd.i64.resize(count); break;
    case 1:
    case 4: nd.f64.resize(count); break;
    case 2: nd.b.resize(count); break;
    case 3:
      nd.str_offsets.resize(count + 1);
      nd.str_bytes.resize(nd.str_offsets.back());
      break;
    case 5:
      for (int k : nd.kids) trim_node(p, k, count);
      break;
    case 6:
      nd.list_offsets.resize(count + 1);
      trim_node(p, nd.kids[0], nd.list_offsets.back());
      break;
  }
}

bool parse_value(AvroParser* p, int ni, Cur& c);

// block-encoded array (spec §complex types): series of item counts until
// a 0 count; a negative count is followed by the block's byte size (we
// decode items either way).  Counts are capped against the bytes actually
// remaining — without the cap a 5-byte payload declaring 2^30 null items
// would allocate gigabytes off one malicious Kafka message (the same
// bound the Python decoder's _decode_blocks enforces).
bool parse_array(AvroParser* p, int ni, Cur& c) {
  ANode& nd = p->nodes[ni];
  const int kid = nd.kids[0];
  for (;;) {
    int64_t count = read_varint(c);
    if (c.fail) return false;
    if (count == 0) break;
    if (count < 0) {
      count = -count;
      read_varint(c);  // block byte size — items are decoded anyway
      if (c.fail) return false;
    }
    int64_t remaining = (int64_t)(c.end - c.p);
    int64_t cap = 2 * (remaining + 1);
    if (count > (cap > 65536 ? cap : 65536)) return false;
    if ((uint64_t)count > p->elem_budget) return false;  // cumulative bomb
    p->elem_budget -= (uint64_t)count;
    for (int64_t i = 0; i < count; i++)
      if (!parse_value(p, kid, c)) return false;
  }
  nd.list_offsets.push_back(p->nodes[kid].valid.size());
  nd.valid.push_back(1);
  return true;
}

// parse one value into node ni (appends exactly one entry to its subtree)
bool parse_value(AvroParser* p, int ni, Cur& c) {
  ANode& nd = p->nodes[ni];
  if (nd.nullable) {
    int64_t branch = read_varint(c);
    if (c.fail) return false;
    if (branch == 0) {
      push_null_recursive(p, ni);
      return true;
    }
    if (branch != 1) return false;  // only ["null", T]
  }
  switch (nd.type) {
    case 0: {
      int64_t v = read_varint(c);
      if (c.fail) return false;
      nd.i64.push_back(v);
      nd.valid.push_back(1);
      return true;
    }
    case 1: {  // double: 8-byte IEEE LE
      if (c.p + 8 > c.end) return false;
      double v;
      memcpy(&v, c.p, 8);
      c.p += 8;
      nd.f64.push_back(v);
      nd.valid.push_back(1);
      return true;
    }
    case 4: {  // float: 4-byte IEEE LE, widened to f64 storage
      if (c.p + 4 > c.end) return false;
      float v;
      memcpy(&v, c.p, 4);
      c.p += 4;
      nd.f64.push_back((double)v);
      nd.valid.push_back(1);
      return true;
    }
    case 2: {
      if (c.p >= c.end) return false;
      nd.b.push_back(*c.p++ ? 1 : 0);
      nd.valid.push_back(1);
      return true;
    }
    case 3: {
      int64_t n = read_varint(c);
      if (c.fail || n < 0 || c.p + n > c.end) return false;
      nd.str_bytes.insert(nd.str_bytes.end(), c.p, c.p + n);
      c.p += n;
      nd.str_offsets.push_back(nd.str_bytes.size());
      nd.valid.push_back(1);
      return true;
    }
    case 5: {  // record: children in declared order
      nd.valid.push_back(1);
      for (int k : nd.kids)
        if (!parse_value(p, k, c)) return false;
      return true;
    }
    case 6:
      return parse_array(p, ni, c);
    default:
      return false;
  }
}

bool parse_record_root(AvroParser* p, Cur& c) {
  for (int ni : p->top)
    if (!parse_value(p, ni, c)) return false;
  // trailing bytes after the last field = corrupt/mismatched schema
  return c.p == c.end;
}

void rollback_row(AvroParser* p, uint64_t nr) {
  for (int ni : p->top) trim_node(p, ni, nr);
}

}  // namespace

extern "C" {

// flat ABI (top-level scalar columns only) — kept for the historical
// callers; types[i]: 0 i64(varint) | 1 f64(8B) | 4 f32(4B stored as f64)
// | 2 bool | 3 string/bytes; nullables[i]: 1 = ["null", T] union-prefixed
void* ap_create(int ncols, const int* types, const int* nullables) {
  AvroParser* p = new AvroParser();
  p->nodes.resize(ncols);
  for (int i = 0; i < ncols; i++) {
    p->nodes[i].type = types[i];
    p->nodes[i].nullable = nullables[i];
    p->nodes[i].str_offsets.assign(1, 0);
    p->top.push_back(i);
  }
  return p;
}

// full schema tree.  nodes come in any order with parent[i] either -1
// (top-level field, order significant) or the index of a record node /
// an array node (whose single child is its element subtree).
void* ap_create_tree(int nnodes, const int* types, const int* nullables,
                     const int* parents) {
  AvroParser* p = new AvroParser();
  p->nodes.resize(nnodes);
  for (int i = 0; i < nnodes; i++) {
    ANode& nd = p->nodes[i];
    nd.type = types[i];
    nd.nullable = nullables[i];
    nd.str_offsets.assign(1, 0);
    nd.list_offsets.assign(nd.type == 6 ? 1 : 0, 0);
    if (parents[i] < 0)
      p->top.push_back(i);
    else
      p->nodes[parents[i]].kids.push_back(i);
  }
  return p;
}

void ap_destroy(void* h) { delete static_cast<AvroParser*>(h); }

void ap_clear(void* h) {
  AvroParser* p = static_cast<AvroParser*>(h);
  p->nrows = 0;
  p->error.clear();
  for (auto& nd : p->nodes) {
    nd.i64.clear();
    nd.f64.clear();
    nd.b.clear();
    nd.valid.clear();
    nd.str_bytes.clear();
    nd.str_offsets.assign(1, 0);
    if (nd.type == 6) nd.list_offsets.assign(1, 0);
  }
}

const char* ap_error(void* h) {
  return static_cast<AvroParser*>(h)->error.c_str();
}

uint64_t ap_nrows(void* h) { return static_cast<AvroParser*>(h)->nrows; }

// parse n records from the arena; offsets has n+1 entries
int ap_parse(void* h, const void* data, const uint64_t* offsets, uint64_t n) {
  AvroParser* p = static_cast<AvroParser*>(h);
  const uint8_t* base = (const uint8_t*)data;
  for (uint64_t i = 0; i < n; i++) {
    Cur c{base + offsets[i], base + offsets[i + 1]};
    uint64_t rec_len = offsets[i + 1] - offsets[i];
    uint64_t budget = 4 * rec_len;
    p->elem_budget = budget > 65536 ? budget : 65536;
    uint64_t row = p->nrows;
    if (!parse_record_root(p, c)) {
      rollback_row(p, row);
      char msg[96];
      snprintf(msg, sizeof msg,
               "malformed Avro record at index %llu (offset %llu)",
               (unsigned long long)i, (unsigned long long)offsets[i]);
      p->error = msg;
      return -1;
    }
    p->nrows++;
  }
  return 0;
}

const int64_t* ap_col_i64(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].i64.data();
}
const double* ap_col_f64(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].f64.data();
}
const uint8_t* ap_col_bool(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].b.data();
}
const uint8_t* ap_col_valid(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].valid.data();
}
const uint64_t* ap_col_str_offsets(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].str_offsets.data();
}
const uint8_t* ap_col_str_bytes(void* h, int ci, uint64_t* nbytes) {
  ANode& c = static_cast<AvroParser*>(h)->nodes[ci];
  *nbytes = c.str_bytes.size();
  return c.str_bytes.data();
}
// list node accessors: per-entry offsets (nentries+1) and element count;
// element values live in the child node (one entry per element), reached
// through the scalar getters above with the child's node index.
// ap_col_list_evalid exists to satisfy the shared ctypes configuration —
// Avro lists are always child-node based (element validity is the
// child's own valid vector), so it returns that child vector.
const uint64_t* ap_col_list_offsets(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].list_offsets.data();
}
const uint8_t* ap_col_list_evalid(void* h, int ci) {
  AvroParser* p = static_cast<AvroParser*>(h);
  ANode& nd = p->nodes[ci];
  if (nd.kids.empty()) return nullptr;
  return p->nodes[nd.kids[0]].valid.data();
}
uint64_t ap_col_list_nelems(void* h, int ci) {
  return list_elems(static_cast<AvroParser*>(h)->nodes[ci]);
}
int64_t ap_col_str_dict(void* h, int ci) {
  AvroParser* p = static_cast<AvroParser*>(h);
  ANode& c = p->nodes[ci];
  // entry count == valid.size() for every node (rows at top level,
  // elements under an array)
  return build_str_dict(c.str_bytes, c.str_offsets, c.valid.size(), c.dict);
}
const int32_t* ap_col_str_dict_codes(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].dict.codes.data();
}
const uint8_t* ap_col_str_dict_bytes(void* h, int ci, uint64_t* nbytes) {
  StrDict& d = static_cast<AvroParser*>(h)->nodes[ci].dict;
  *nbytes = d.bytes.size();
  return d.bytes.data();
}
const uint64_t* ap_col_str_dict_offsets(void* h, int ci) {
  return static_cast<AvroParser*>(h)->nodes[ci].dict.offsets.data();
}

}  // extern "C"
