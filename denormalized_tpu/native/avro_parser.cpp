// avro_parser — one-pass Avro-binary → columnar decoder.
//
// The reference's Avro decode is native (Rust apache-avro through
// DataFusion's avro_to_arrow, crates/core/src/formats/decoders/avro.rs:11-54);
// this is our native equivalent, built like json_parser.cpp: the caller
// hands an arena of concatenated record payloads + offsets (typically the
// Kafka fetch arena, zero-copy) and reads back columnar buffers.
//
// Avro records are positional — no key matching, just the schema's field
// order: [nullable-union branch varint] then the value per the base type.
// Supported base types (codes): 0 = int/long/timestamp-millis (zigzag
// varint → i64), 1 = float/double (IEEE LE → f64), 2 = boolean (1 byte),
// 3 = string/bytes (length varint + raw).  Nullable fields are the
// ["null", T] union (branch 0 = null, branch 1 = value) — the only union
// shape the engine schema layer admits.
//
// C ABI for ctypes; one parser object per schema; not thread-safe.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include "str_dict.hpp"
#include <vector>

namespace {

struct AvroCol {
  int type;  // 0 i64, 1 f64, 2 bool, 3 string
  int nullable;
  std::vector<int64_t> i64;
  std::vector<double> f64;
  std::vector<uint8_t> b;
  std::vector<uint8_t> valid;
  std::vector<uint8_t> str_bytes;
  std::vector<uint64_t> str_offsets;  // n+1
  StrDict dict;
  void clear() {
    i64.clear();
    f64.clear();
    b.clear();
    valid.clear();
    str_bytes.clear();
    str_offsets.assign(1, 0);
  }
  void push_null() {
    valid.push_back(0);
    switch (type) {
      case 0: i64.push_back(0); break;
      case 1:
      case 4: f64.push_back(0); break;  // float shares the f64 store
      case 2: b.push_back(0); break;
      case 3: str_offsets.push_back(str_bytes.size()); break;
    }
  }
};

struct AvroParser {
  std::vector<AvroCol> cols;
  std::string error;
  uint64_t nrows = 0;
};

struct Cur {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;
};

// Avro long: zigzag base-128 varint (spec "binary encoding")
int64_t read_varint(Cur& c) {
  uint64_t acc = 0;
  int shift = 0;
  while (c.p < c.end) {
    uint8_t b = *c.p++;
    acc |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80))
      return (int64_t)((acc >> 1) ^ (~(acc & 1) + 1));
    shift += 7;
    if (shift > 63) break;
  }
  c.fail = true;
  return 0;
}

bool parse_record(AvroParser* p, Cur& c) {
  for (auto& col : p->cols) {
    if (col.nullable) {
      int64_t branch = read_varint(c);
      if (c.fail) return false;
      if (branch == 0) {
        col.push_null();
        continue;
      }
      if (branch != 1) return false;  // only ["null", T]
    }
    switch (col.type) {
      case 0: {
        int64_t v = read_varint(c);
        if (c.fail) return false;
        col.i64.push_back(v);
        col.valid.push_back(1);
        break;
      }
      case 1: {  // double: 8-byte IEEE LE
        if (c.p + 8 > c.end) return false;
        double v;
        memcpy(&v, c.p, 8);
        c.p += 8;
        col.f64.push_back(v);
        col.valid.push_back(1);
        break;
      }
      case 4: {  // float: 4-byte IEEE LE, widened to f64 storage
        if (c.p + 4 > c.end) return false;
        float v;
        memcpy(&v, c.p, 4);
        c.p += 4;
        col.f64.push_back((double)v);
        col.valid.push_back(1);
        break;
      }
      case 2: {
        if (c.p >= c.end) return false;
        col.b.push_back(*c.p++ ? 1 : 0);
        col.valid.push_back(1);
        break;
      }
      case 3: {
        int64_t n = read_varint(c);
        if (c.fail || n < 0 || c.p + n > c.end) return false;
        col.str_bytes.insert(col.str_bytes.end(), c.p, c.p + n);
        c.p += n;
        col.str_offsets.push_back(col.str_bytes.size());
        col.valid.push_back(1);
        break;
      }
      default:
        return false;
    }
  }
  // trailing bytes after the last field = corrupt/mismatched schema
  return c.p == c.end;
}

void rollback_row(AvroParser* p, size_t row) {
  // drop any partial values parse_record pushed for the failed row
  for (auto& col : p->cols) {
    if (col.valid.size() > row) {
      col.valid.resize(row);
      if (col.i64.size() > row) col.i64.resize(row);
      if (col.f64.size() > row) col.f64.resize(row);
      if (col.b.size() > row) col.b.resize(row);
      if (col.str_offsets.size() > row + 1) {
        col.str_offsets.resize(row + 1);
        col.str_bytes.resize(col.str_offsets.back());
      }
    }
  }
}

}  // namespace

extern "C" {

// types[i]: 0 i64(varint) | 1 f64(8B) | 4 f32(4B stored as f64) | 2 bool |
// 3 string/bytes; nullables[i]: 1 = ["null", T] union-prefixed
void* ap_create(int ncols, const int* types, const int* nullables) {
  AvroParser* p = new AvroParser();
  p->cols.resize(ncols);
  for (int i = 0; i < ncols; i++) {
    p->cols[i].type = types[i];
    p->cols[i].nullable = nullables[i];
    p->cols[i].str_offsets.assign(1, 0);
  }
  return p;
}

void ap_destroy(void* h) { delete static_cast<AvroParser*>(h); }

void ap_clear(void* h) {
  AvroParser* p = static_cast<AvroParser*>(h);
  p->nrows = 0;
  p->error.clear();
  for (auto& c : p->cols) c.clear();
}

const char* ap_error(void* h) {
  return static_cast<AvroParser*>(h)->error.c_str();
}

uint64_t ap_nrows(void* h) { return static_cast<AvroParser*>(h)->nrows; }

// parse n records from the arena; offsets has n+1 entries
int ap_parse(void* h, const void* data, const uint64_t* offsets, uint64_t n) {
  AvroParser* p = static_cast<AvroParser*>(h);
  const uint8_t* base = (const uint8_t*)data;
  for (uint64_t i = 0; i < n; i++) {
    Cur c{base + offsets[i], base + offsets[i + 1]};
    size_t row = (size_t)p->nrows;
    if (!parse_record(p, c)) {
      rollback_row(p, row);
      char msg[96];
      snprintf(msg, sizeof msg,
               "malformed Avro record at index %llu (offset %llu)",
               (unsigned long long)i, (unsigned long long)offsets[i]);
      p->error = msg;
      return -1;
    }
    p->nrows++;
  }
  return 0;
}

const int64_t* ap_col_i64(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].i64.data();
}
const double* ap_col_f64(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].f64.data();
}
const uint8_t* ap_col_bool(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].b.data();
}
const uint8_t* ap_col_valid(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].valid.data();
}
const uint64_t* ap_col_str_offsets(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].str_offsets.data();
}
const uint8_t* ap_col_str_bytes(void* h, int ci, uint64_t* nbytes) {
  AvroCol& c = static_cast<AvroParser*>(h)->cols[ci];
  *nbytes = c.str_bytes.size();
  return c.str_bytes.data();
}
int64_t ap_col_str_dict(void* h, int ci) {
  AvroParser* p = static_cast<AvroParser*>(h);
  AvroCol& c = p->cols[ci];
  return build_str_dict(c.str_bytes, c.str_offsets, p->nrows, c.dict);
}
const int32_t* ap_col_str_dict_codes(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].dict.codes.data();
}
const uint8_t* ap_col_str_dict_bytes(void* h, int ci, uint64_t* nbytes) {
  StrDict& d = static_cast<AvroParser*>(h)->cols[ci].dict;
  *nbytes = d.bytes.size();
  return d.bytes.data();
}
const uint64_t* ap_col_str_dict_offsets(void* h, int ci) {
  return static_cast<AvroParser*>(h)->cols[ci].dict.offsets.data();
}

}  // extern "C"
