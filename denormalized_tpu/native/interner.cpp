// interner — fixed-width-bytes string interning: values → dense int32 ids.
//
// Native hot path for group-key interning (the GroupValues-equivalent; see
// ops/interner.py).  Python converts an object column to a fixed-width
// numpy 'S' array (vectorized, ~10M rows/s) and hands the raw buffer here;
// we hash each width-w slot into an open-addressing table that persists
// across batches, so steady-state interning is one hash+memcmp per row with
// no Python object traffic at all.
//
// The table stores (offset into an append-only arena, id).  C ABI for
// ctypes.

#ifdef INTERN_HAVE_PYTHON
// must precede the standard headers per CPython's include rules
#include <Python.h>
#endif

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Interner {
  std::vector<uint8_t> arena;     // concatenated fixed-width keys (by id)
  std::vector<uint32_t> arena_w;  // width of each id's key
  // open addressing table of (id+1), 0 = empty
  std::vector<uint32_t> table;
  uint64_t mask = 0;
  uint64_t count = 0;

  void grow() {
    size_t ncap = table.empty() ? 1024 : table.size() * 2;
    std::vector<uint32_t> nt(ncap, 0);
    uint64_t nmask = ncap - 1;
    // rehash existing ids
    uint64_t off = 0;
    for (uint64_t id = 0; id < count; id++) {
      uint32_t w = arena_w[id];
      uint64_t h = hash(arena.data() + off, w);
      uint64_t slot = h & nmask;
      while (nt[slot]) slot = (slot + 1) & nmask;
      nt[slot] = (uint32_t)(id + 1);
      off += w;
    }
    table.swap(nt);
    mask = nmask;
  }

  static uint64_t hash(const uint8_t* p, uint32_t w) {
    // 8-byte-chunk multiply-mix (keys are fixed-width UTF-32 slots, often
    // 40+ bytes — per-byte FNV costs one multiply per byte; this costs one
    // per 8 bytes)
    uint64_t h = 1469598103934665603ull ^ w;
    while (w >= 8) {
      uint64_t k;
      memcpy(&k, p, 8);
      h = (h ^ k) * 0x9E3779B97F4A7C15ull;
      h ^= h >> 29;
      p += 8;
      w -= 8;
    }
    if (w) {
      uint64_t k = 0;
      memcpy(&k, p, w);
      h = (h ^ k) * 0x9E3779B97F4A7C15ull;
      h ^= h >> 29;
    }
    return h;
  }

};

}  // namespace

namespace {

// internal-linkage like everything else non-ABI here: the handle type
// crosses the C ABI only as void*, and keeping it in the anonymous
// namespace (its Interner field already is) avoids -Wsubobject-linkage
// in the single-TU sanitizer build
struct CInterner {
  Interner in;
  std::vector<uint64_t> offsets;  // arena offset per id
#ifdef INTERN_HAVE_PYTHON
  // pointer-identity lookaside: PyObject* → id.  Group keys repeat the
  // SAME string objects heavily (dictionary-style sources, reused pools),
  // and str is immutable — so a pointer hit skips the UTF-8 fetch, content
  // hash, and arena memcmp entirely.  Cached objects are INCREF-pinned so
  // the pointer can never be reused for a different string.
  std::vector<uint64_t> pkeys;  // ptr, 0 = empty
  std::vector<uint32_t> pids;   // id + 1
  uint64_t pmask = 0;
  uint64_t pcount = 0;
#endif
};

}  // namespace

extern "C" {

void* intern_create() {
  CInterner* c = new CInterner();
  c->in.grow();
  return c;
}

void intern_destroy(void* h) { delete static_cast<CInterner*>(h); }

uint64_t intern_count(void* h) { return static_cast<CInterner*>(h)->in.count; }

namespace {

// intern one key (len already padding-stripped) → dense id
inline int32_t intern_one(CInterner* c, const uint8_t* key, uint32_t len) {
  Interner& in = c->in;
  uint64_t hv = Interner::hash(key, len);
  uint64_t slot = hv & in.mask;
  for (;;) {
    uint32_t e = in.table[slot];
    if (!e) {
      // new key
      if ((in.count + 1) * 4 >= in.table.size() * 3) {
        in.grow();
        slot = hv & in.mask;
        while (in.table[slot]) slot = (slot + 1) & in.mask;
      }
      uint64_t off = in.arena.size();
      in.arena.insert(in.arena.end(), key, key + len);
      in.arena_w.push_back(len);
      c->offsets.push_back(off);
      in.table[slot] = (uint32_t)(in.count + 1);
      int32_t id = (int32_t)in.count;
      in.count++;
      return id;
    }
    uint64_t id = e - 1;
    uint32_t klen = in.arena_w[id];
    if (klen == len &&
        memcmp(in.arena.data() + c->offsets[id], key, len) == 0)
      return (int32_t)id;
    slot = (slot + 1) & in.mask;
  }
}

}  // namespace

// Intern n fixed-width keys (width w, buffer n*w bytes) → out_ids[n].
// Trailing bytes of shorter strings must be zero-padded (numpy 'S' does
// this).  Keys of DIFFERENT widths across calls are distinct unless their
// padded bytes match after width normalization — callers keep one interner
// per column and always pass the column's current max width; previously
// seen keys are re-looked-up by re-padding, so the arena stores the
// ORIGINAL width and comparison strips trailing zeros.
void intern_many(void* h, const uint8_t* data, uint64_t n, uint32_t w,
                 int32_t* out_ids) {
  CInterner* c = static_cast<CInterner*>(h);
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* key = data + i * w;
    // effective length: strip zero padding so width changes don't split keys
    uint32_t len = w;
    while (len > 0 && key[len - 1] == 0) len--;
    out_ids[i] = intern_one(c, key, len);
  }
}

// Intern n variable-length keys given as one contiguous UTF-8 buffer plus
// u64 offsets (n+1 entries) — the Arrow string-column layout, so a
// StringColumn interns straight off its own buffers with NO Python str
// materialization.  valid may be NULL (all valid); invalid slots intern
// the dedicated 0xFF NULL key (impossible in valid UTF-8 — same sentinel
// as the PyObject path's None handling, so mixed-lane columns agree).
// Trailing NULs strip like every other lane.
void intern_offsets(void* h, const uint8_t* bytes, const uint64_t* offsets,
                    const uint8_t* valid, uint64_t n, int32_t* out_ids) {
  CInterner* c = static_cast<CInterner*>(h);
  static const uint8_t kNullKey[1] = {0xFF};
  for (uint64_t i = 0; i < n; i++) {
    if (valid != nullptr && !valid[i]) {
      out_ids[i] = intern_one(c, kNullKey, 1);
      continue;
    }
    const uint8_t* key = bytes + offsets[i];
    uint32_t len = (uint32_t)(offsets[i + 1] - offsets[i]);
    while (len > 0 && key[len - 1] == 0) len--;
    out_ids[i] = intern_one(c, key, len);
  }
}

#ifdef INTERN_HAVE_PYTHON
// Direct PyObject path: hash each numpy-object-array slot's string content
// (CPython-cached UTF-8) with NO fixed-width conversion and NO new Python
// objects — the hot path for high-cardinality group keys.  Must be called
// through ctypes.PyDLL (the GIL stays held).  Keys stored as UTF-8, so a
// column interner must use EITHER this path or intern_many, never both.
namespace {

constexpr uint64_t kPtrCacheCap = 1u << 20;  // bound pinned objects

inline void pcache_grow(CInterner* c) {
  size_t ncap = c->pkeys.empty() ? 4096 : c->pkeys.size() * 2;
  std::vector<uint64_t> nk(ncap, 0);
  std::vector<uint32_t> ni(ncap, 0);
  uint64_t nmask = ncap - 1;
  for (size_t i = 0; i < c->pkeys.size(); i++) {
    if (!c->pkeys[i]) continue;
    uint64_t slot = (c->pkeys[i] * 0x9E3779B97F4A7C15ull >> 17) & nmask;
    while (nk[slot]) slot = (slot + 1) & nmask;
    nk[slot] = c->pkeys[i];
    ni[slot] = c->pids[i];
  }
  c->pkeys.swap(nk);
  c->pids.swap(ni);
  c->pmask = nmask;
}

}  // namespace

int intern_pyobjects(void* h, PyObject** objs, uint64_t n, int32_t* out_ids) {
  CInterner* c = static_cast<CInterner*>(h);
  if (c->pkeys.empty()) pcache_grow(c);
  for (uint64_t i = 0; i < n; i++) {
    PyObject* o = objs[i];
    // pointer lookaside first
    uint64_t ptr = (uint64_t)(uintptr_t)o;
    uint64_t slot = (ptr * 0x9E3779B97F4A7C15ull >> 17) & c->pmask;
    bool hit = false;
    while (c->pkeys[slot]) {
      if (c->pkeys[slot] == ptr) {
        out_ids[i] = (int32_t)(c->pids[slot] - 1);
        hit = true;
        break;
      }
      slot = (slot + 1) & c->pmask;
    }
    if (hit) continue;
    Py_ssize_t len = 0;
    const char* s = nullptr;
    PyObject* tmp = nullptr;
    if (o == Py_None) {
      // NULL keys get a dedicated 1-byte key (0xFF — impossible in valid
      // UTF-8), so null groups never collide with the string 'None' and
      // the reverse lookup can reconstruct real None
      static const char kNullKey[1] = {(char)0xFF};
      out_ids[i] = intern_one(c, (const uint8_t*)kNullKey, 1);
      continue;
    }
    if (PyUnicode_Check(o)) {
      s = PyUnicode_AsUTF8AndSize(o, &len);
      if (s == nullptr) {
        // lone surrogates etc.: match the engine-wide errors='replace'
        // policy instead of aborting the stream
        PyErr_Clear();
        tmp = PyUnicode_AsEncodedString(o, "utf-8", "replace");
        if (tmp) {
          char* bs = nullptr;
          if (PyBytes_AsStringAndSize(tmp, &bs, &len) == 0) s = bs;
        }
      }
    } else {
      // non-string key (None, numbers in an object column): match the
      // fallback path's str() normalization
      PyObject* as_str = PyObject_Str(o);
      if (as_str) {
        s = PyUnicode_AsUTF8AndSize(as_str, &len);
        tmp = as_str;
      }
    }
    if (s == nullptr) {
      Py_XDECREF(tmp);
      return -1;  // propagate: caller raises the pending Python error
    }
    uint32_t l = (uint32_t)len;
    while (l > 0 && s[l - 1] == 0) l--;  // same padding-strip semantics
    int32_t id = intern_one(c, (const uint8_t*)s, l);
    out_ids[i] = id;
    Py_XDECREF(tmp);
    // Cache only plain strs that show evidence of POOLING: a per-row str
    // freshly minted by a decoder is held by nothing but the batch array
    // (refcount 1 + the borrowed array slot), so pinning it would retain
    // dead objects forever for zero hits.  Reused/pooled keys (the case
    // the cache exists for) carry extra references.
    if (tmp == nullptr && Py_REFCNT(o) >= 2 && c->pcount < kPtrCacheCap) {
      if ((c->pcount + 1) * 4 >= c->pkeys.size() * 3) pcache_grow(c);
      uint64_t s2 = (ptr * 0x9E3779B97F4A7C15ull >> 17) & c->pmask;
      while (c->pkeys[s2]) s2 = (s2 + 1) & c->pmask;
      c->pkeys[s2] = ptr;
      c->pids[s2] = (uint32_t)(id + 1);
      c->pcount++;
      Py_INCREF(o);
    }
  }
  return 0;
}

// release the pointer cache's pins — MUST be called through ctypes.PyDLL
// (needs the GIL) before intern_destroy
void intern_py_release(void* h) {
  CInterner* c = static_cast<CInterner*>(h);
  for (size_t i = 0; i < c->pkeys.size(); i++)
    if (c->pkeys[i]) Py_DECREF((PyObject*)(uintptr_t)c->pkeys[i]);
  c->pkeys.clear();
  c->pids.clear();
  c->pmask = 0;
  c->pcount = 0;
}
#endif  // INTERN_HAVE_PYTHON

// bulk reverse lookup: copy the arena slice and offsets for ids in
// [start, end) — one call per batch instead of one per key
int64_t intern_keys_range(void* h, uint64_t start, uint64_t end,
                          uint8_t** bytes_out, uint64_t** offsets_out) {
  CInterner* c = static_cast<CInterner*>(h);
  if (start > end || end > c->in.count) return -1;
  uint64_t n = end - start;
  uint64_t base = c->offsets.empty() || start >= c->offsets.size()
                      ? c->in.arena.size()
                      : c->offsets[start];
  uint64_t total = (end == c->in.count ? c->in.arena.size()
                                       : c->offsets[end]) -
                   base;
  uint8_t* bytes = (uint8_t*)malloc(total ? total : 1);
  uint64_t* offs = (uint64_t*)malloc((n + 1) * sizeof(uint64_t));
  memcpy(bytes, c->in.arena.data() + base, total);
  for (uint64_t i = 0; i < n; i++) offs[i] = c->offsets[start + i] - base;
  offs[n] = total;
  *bytes_out = bytes;
  *offsets_out = offs;
  return (int64_t)n;
}

void intern_free(void* p) { free(p); }

// copy key bytes for one id (for reverse lookup); returns length
uint32_t intern_key(void* h, uint64_t id, uint8_t* out, uint32_t cap) {
  CInterner* c = static_cast<CInterner*>(h);
  if (id >= c->in.count) return 0;
  uint32_t w = c->in.arena_w[id];
  uint32_t n = w < cap ? w : cap;
  memcpy(out, c->in.arena.data() + c->offsets[id], n);
  return w;
}

}  // extern "C"
