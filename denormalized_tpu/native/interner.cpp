// interner — fixed-width-bytes string interning: values → dense int32 ids.
//
// Native hot path for group-key interning (the GroupValues-equivalent; see
// ops/interner.py).  Python converts an object column to a fixed-width
// numpy 'S' array (vectorized, ~10M rows/s) and hands the raw buffer here;
// we hash each width-w slot into an open-addressing table that persists
// across batches, so steady-state interning is one hash+memcmp per row with
// no Python object traffic at all.
//
// The table stores (offset into an append-only arena, id).  C ABI for
// ctypes.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Interner {
  std::vector<uint8_t> arena;     // concatenated fixed-width keys (by id)
  std::vector<uint32_t> arena_w;  // width of each id's key
  // open addressing table of (id+1), 0 = empty
  std::vector<uint32_t> table;
  uint64_t mask = 0;
  uint64_t count = 0;

  void grow() {
    size_t ncap = table.empty() ? 1024 : table.size() * 2;
    std::vector<uint32_t> nt(ncap, 0);
    uint64_t nmask = ncap - 1;
    // rehash existing ids
    uint64_t off = 0;
    for (uint64_t id = 0; id < count; id++) {
      uint32_t w = arena_w[id];
      uint64_t h = hash(arena.data() + off, w);
      uint64_t slot = h & nmask;
      while (nt[slot]) slot = (slot + 1) & nmask;
      nt[slot] = (uint32_t)(id + 1);
      off += w;
    }
    table.swap(nt);
    mask = nmask;
  }

  static uint64_t hash(const uint8_t* p, uint32_t w) {
    // 8-byte-chunk multiply-mix (keys are fixed-width UTF-32 slots, often
    // 40+ bytes — per-byte FNV costs one multiply per byte; this costs one
    // per 8 bytes)
    uint64_t h = 1469598103934665603ull ^ w;
    while (w >= 8) {
      uint64_t k;
      memcpy(&k, p, 8);
      h = (h ^ k) * 0x9E3779B97F4A7C15ull;
      h ^= h >> 29;
      p += 8;
      w -= 8;
    }
    if (w) {
      uint64_t k = 0;
      memcpy(&k, p, w);
      h = (h ^ k) * 0x9E3779B97F4A7C15ull;
      h ^= h >> 29;
    }
    return h;
  }

};

}  // namespace

extern "C" {

struct CInterner {
  Interner in;
  std::vector<uint64_t> offsets;  // arena offset per id
};

void* intern_create() {
  CInterner* c = new CInterner();
  c->in.grow();
  return c;
}

void intern_destroy(void* h) { delete static_cast<CInterner*>(h); }

uint64_t intern_count(void* h) { return static_cast<CInterner*>(h)->in.count; }

// Intern n fixed-width keys (width w, buffer n*w bytes) → out_ids[n].
// Trailing bytes of shorter strings must be zero-padded (numpy 'S' does
// this).  Keys of DIFFERENT widths across calls are distinct unless their
// padded bytes match after width normalization — callers keep one interner
// per column and always pass the column's current max width; previously
// seen keys are re-looked-up by re-padding, so the arena stores the
// ORIGINAL width and comparison strips trailing zeros.
void intern_many(void* h, const uint8_t* data, uint64_t n, uint32_t w,
                 int32_t* out_ids) {
  CInterner* c = static_cast<CInterner*>(h);
  Interner& in = c->in;
  for (uint64_t i = 0; i < n; i++) {
    const uint8_t* key = data + i * w;
    // effective length: strip zero padding so width changes don't split keys
    uint32_t len = w;
    while (len > 0 && key[len - 1] == 0) len--;
    uint64_t hv = Interner::hash(key, len);
    uint64_t slot = hv & in.mask;
    for (;;) {
      uint32_t e = in.table[slot];
      if (!e) {
        // new key
        if ((in.count + 1) * 4 >= in.table.size() * 3) {
          in.grow();
          slot = hv & in.mask;
          while (in.table[slot]) slot = (slot + 1) & in.mask;
        }
        uint64_t off = in.arena.size();
        in.arena.insert(in.arena.end(), key, key + len);
        in.arena_w.push_back(len);
        c->offsets.push_back(off);
        in.table[slot] = (uint32_t)(in.count + 1);
        out_ids[i] = (int32_t)in.count;
        in.count++;
        break;
      }
      uint64_t id = e - 1;
      uint32_t klen = in.arena_w[id];
      if (klen == len &&
          memcmp(in.arena.data() + c->offsets[id], key, len) == 0) {
        out_ids[i] = (int32_t)id;
        break;
      }
      slot = (slot + 1) & in.mask;
    }
  }
}

// bulk reverse lookup: copy the arena slice and offsets for ids in
// [start, end) — one call per batch instead of one per key
int64_t intern_keys_range(void* h, uint64_t start, uint64_t end,
                          uint8_t** bytes_out, uint64_t** offsets_out) {
  CInterner* c = static_cast<CInterner*>(h);
  if (start > end || end > c->in.count) return -1;
  uint64_t n = end - start;
  uint64_t base = c->offsets.empty() || start >= c->offsets.size()
                      ? c->in.arena.size()
                      : c->offsets[start];
  uint64_t total = (end == c->in.count ? c->in.arena.size()
                                       : c->offsets[end]) -
                   base;
  uint8_t* bytes = (uint8_t*)malloc(total ? total : 1);
  uint64_t* offs = (uint64_t*)malloc((n + 1) * sizeof(uint64_t));
  memcpy(bytes, c->in.arena.data() + base, total);
  for (uint64_t i = 0; i < n; i++) offs[i] = c->offsets[start + i] - base;
  offs[n] = total;
  *bytes_out = bytes;
  *offsets_out = offs;
  return (int64_t)n;
}

void intern_free(void* p) { free(p); }

// copy key bytes for one id (for reverse lookup); returns length
uint32_t intern_key(void* h, uint64_t id, uint8_t* out, uint32_t cap) {
  CInterner* c = static_cast<CInterner*>(h);
  if (id >= c->in.count) return 0;
  uint32_t w = c->in.arena_w[id];
  uint32_t n = w < cap ? w : cap;
  memcpy(out, c->in.arena.data() + c->offsets[id], n);
  return w;
}

}  // extern "C"
