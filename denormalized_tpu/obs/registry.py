"""Typed metric instruments and the registry that owns them.

Design constraints (the tentpole's contract):

- **Pre-bound handles.**  Operators bind instruments once at
  construction (``registry.counter(name, **labels)``); the hot path then
  does one attribute add — no dict lookups, no label formatting, no
  allocation.
- **No-op when disabled.**  A disabled registry hands out process-wide
  null singletons whose methods are empty (and which are *falsy*, so
  call sites can skip even the ``time.perf_counter()`` bracketing with
  ``if handle:``).  ``tests/test_obs.py`` pins that the disabled-path
  call allocates nothing.
- **Single-writer mutation.**  Instruments carry NO locks: every bound
  handle has exactly one writer (an operator on the consumer thread, a
  prefetch worker for its own partition, the fault plan under its own
  lock).  Export readers tolerate the benign raciness of reading a
  counter mid-increment; what they can never see is a torn value, since
  every field is a single Python object reference.  This is what keeps
  ``observe()`` at ~1µs on the 49M rows/s hot path.

Histograms use exponential buckets declared in the catalog and track
exact ``sum``/``count``/``min``/``max`` alongside, so a soak can report
both interpolated percentiles and the true peak (a sampled gauge would
miss the max between samples).
"""

from __future__ import annotations

import threading
from bisect import bisect_right

from denormalized_tpu.obs.catalog import declaration
from denormalized_tpu.obs.readers import quantile_from_buckets


class Counter:
    """Monotone counter.  One writer per bound handle."""

    __slots__ = ("name", "labels", "_v")
    kind = "counter"

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._v = 0

    def add(self, n: int = 1) -> None:
        self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Last-written value.  One writer per bound handle."""

    __slots__ = ("name", "labels", "_v")
    kind = "gauge"

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self._v = 0.0

    def set(self, v) -> None:
        self._v = v

    @property
    def value(self):
        return self._v


class GaugeFn:
    """Pull-style gauge: ``fn()`` is evaluated at export time.  This is
    how the pre-existing ad-hoc counters (``decode_fallback_rows``, ...)
    migrate onto the registry without restructuring their ownership —
    the authoritative count stays where it lives, the registry reads it."""

    __slots__ = ("name", "labels", "fn")
    kind = "gauge"

    def __init__(self, name: str, labels: tuple, fn):
        self.name = name
        self.labels = labels
        self.fn = fn

    @property
    def value(self):
        try:
            return float(self.fn())
        except Exception:  # dnzlint: allow(broad-except) an export-time read of a torn-down source (closed pump, dead reader) must degrade to 0, never take the exposition endpoint down with it
            return 0.0


class Histogram:
    """Exponential-bucket histogram with exact sum/count/min/max.

    ``observe`` is the hot-path call: one bisect over ~20 floats plus
    five attribute stores.  Quantiles interpolate linearly inside the
    winning bucket (clamped by the exact min/max), which is the standard
    Prometheus-style estimate — good to a bucket factor, exact at the
    tails we report (max is tracked exactly)."""

    __slots__ = (
        "name", "labels", "bounds", "counts", "sum", "count", "vmin", "vmax"
    )
    kind = "histogram"

    def __init__(self, name: str, labels: tuple, bounds: list[float]):
        self.name = name
        self.labels = labels
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.vmin: float | None = None
        self.vmax: float | None = None

    def observe(self, v: float) -> None:
        self.counts[bisect_right(self.bounds, v)] += 1
        self.sum += v
        self.count += 1
        if self.vmax is None or v > self.vmax:
            self.vmax = v
        if self.vmin is None or v < self.vmin:
            self.vmin = v

    @property
    def value(self):
        return self.sum

    def quantile(self, q: float) -> float | None:
        """Interpolated q-quantile (0..1) from the bucket counts, or
        None when empty."""
        return quantile_from_buckets(
            self.bounds, self.counts, self.count, q,
            vmin=self.vmin, vmax=self.vmax,
        )


class _NullInstrument:
    """Shared no-op handle for every kind when metrics are disabled.
    Falsy so call sites can skip timing brackets entirely:

        if self._obs_ms:            # False on the disabled path
            t0 = time.perf_counter()
    """

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def add(self, n: int = 1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass

    @property
    def value(self):
        return 0

    def quantile(self, q):
        return None


NULL = _NullInstrument()


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Owns every bound instrument of one process (normally the
    module-global default in ``denormalized_tpu.obs``).

    Binding is keyed ``(name, sorted labels)``: re-binding the same
    series returns the SAME instrument, so a restarted operator keeps
    accumulating into its series instead of shadowing it.  A
    ``gauge_fn`` re-bind replaces the callback (the new incarnation's
    closure is the live one)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._instruments: dict[tuple, object] = {}

    # -- binding --------------------------------------------------------
    def _bind(self, want_kind: str, name: str, labels: dict, factory):
        if not self.enabled:
            return NULL
        kind, _help, bounds = declaration(name)
        if kind != want_kind:
            raise TypeError(
                f"instrument {name!r} is declared as a {kind}, bound as "
                f"a {want_kind}"
            )
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = factory(name, key[1], bounds)
                self._instruments[key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._bind(
            "counter", name, labels, lambda n, lk, _b: Counter(n, lk)
        )

    def gauge(self, name: str, **labels) -> Gauge:
        return self._bind(
            "gauge", name, labels, lambda n, lk, _b: Gauge(n, lk)
        )

    def histogram(self, name: str, **labels) -> Histogram:
        return self._bind(
            "histogram", name, labels,
            lambda n, lk, b: Histogram(n, lk, b),
        )

    def gauge_fn(self, name: str, fn, **labels) -> GaugeFn:
        inst = self._bind(
            "gauge", name, labels, lambda n, lk, _b: GaugeFn(n, lk, fn)
        )
        if isinstance(inst, GaugeFn):
            inst.fn = fn  # re-bind replaces the callback (see class doc)
        return inst

    # -- reading --------------------------------------------------------
    def instruments(self) -> list:
        with self._lock:
            return list(self._instruments.values())

    def snapshot(self) -> dict:
        """One JSON-able point-in-time view: series name (with rendered
        labels) -> scalar for counters/gauges, stats dict for
        histograms.  Histograms carry their raw bucket layout so
        multi-process consumers (the soak parent) can merge counts and
        re-derive quantiles over the union."""
        out: dict[str, object] = {}
        for inst in self.instruments():
            key = series_name(inst.name, inst.labels)
            if isinstance(inst, Histogram):
                out[key] = {
                    "count": inst.count,
                    "sum": inst.sum,
                    "min": inst.vmin,
                    "max": inst.vmax,
                    "bounds": inst.bounds,
                    "bucket_counts": list(inst.counts),
                    "p50": inst.quantile(0.50),
                    "p95": inst.quantile(0.95),
                    "p99": inst.quantile(0.99),
                }
            else:
                out[key] = inst.value
        return out


def series_name(name: str, labels: tuple) -> str:
    if not labels:
        return name
    body = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{body}}}"
