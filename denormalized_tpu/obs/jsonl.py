"""Periodic JSONL snapshots of the registry — the soak/bench telemetry
stream.

One line per interval::

    {"event": "obs", "t": <epoch s>, "metrics": {<series>: <value|stats>}}

Counters/gauges snapshot as scalars; histograms as stats dicts carrying
their raw bucket layout (``bounds`` + ``bucket_counts``) so a
multi-process consumer — the soak parent reading every killed segment's
stream — can merge counts across processes and re-derive percentiles
over the union (:func:`merge_histogram`, the read-side counterpart).

The writer is a daemon thread flushing line-buffered, so a SIGKILLed
child still leaves its last completed snapshot behind (same contract as
the soak's chaos events).  ``stop()`` writes one final snapshot for
clean exits.
"""

from __future__ import annotations

import json
import threading
import time

from denormalized_tpu.obs.readers import (  # noqa: F401 (re-exported)
    counter_timeline,
    last_stats,
    merge_histogram,
    read_stream,
)
from denormalized_tpu.obs.registry import MetricsRegistry


class JsonlSnapshotter:
    def __init__(
        self,
        path: str,
        registry: MetricsRegistry,
        interval_s: float = 1.0,
    ):
        self._path = path
        self._registry = registry
        self._interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-jsonl"
        )

    def start(self) -> "JsonlSnapshotter":
        self._thread.start()
        return self

    def _write_once(self, f) -> None:
        snap = self._registry.snapshot()
        f.write(json.dumps({
            "event": "obs", "t": time.time(), "metrics": snap,
        }) + "\n")

    def _run(self) -> None:
        with open(self._path, "a", buffering=1) as f:
            while not self._stop.wait(self._interval_s):
                self._write_once(f)
            self._write_once(f)  # final snapshot on clean stop

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# -- read side (consumers: tools/soak.py, bench.py) -----------------------


# The read-side helpers (read_stream / last_stats / merge_histogram /
# counter_timeline) live in :mod:`denormalized_tpu.obs.readers` — a
# stdlib-only module the soak PARENT loads by file path to stay jax-free
# — and are re-exported here for in-process consumers (bench.py).
