"""denormalized_tpu.obs — engine-wide observability.

The metrics half of the reference's ``BaselineMetrics``/``tracing``
story, built production-grade: typed instruments (Counter, Gauge,
Histogram with exponential buckets) declared once in
:mod:`~denormalized_tpu.obs.catalog`, bound to pre-resolved handles at
operator construction, exported three ways —

- a Prometheus text-exposition endpoint on a stdlib HTTP server
  (``EngineConfig(prometheus_port=...)``, opt-in);
- periodic JSONL snapshots for soaks and benches
  (``EngineConfig(metrics_jsonl_path=...)``);
- a ring-buffered span recorder dumping Chrome trace-event JSON
  loadable in Perfetto (``EngineConfig(trace_path=...)``).

Hot-path contract: a bound handle's ``add``/``observe`` is one
attribute update (plus a ~20-element bisect for histograms); with
metrics disabled the handle is a falsy shared null object whose methods
are no-ops and allocate nothing.  Instruments are single-writer by
construction (one handle per operator/worker); export readers tolerate
mid-increment reads.

Use module-level binders everywhere in the engine (the dnzlint DNZ-M001
pass statically checks the name literals against the catalog)::

    from denormalized_tpu import obs
    self._rows_in = obs.counter("dnz_op_rows_in_total", op="window")
    ...
    self._rows_in.add(batch.num_rows)
"""

from __future__ import annotations

import contextlib
import threading

from denormalized_tpu.obs import spans as spans
from denormalized_tpu.obs.catalog import INSTRUMENTS
from denormalized_tpu.obs.registry import (
    MetricsRegistry,
    NULL,
    series_name,
)
from denormalized_tpu.obs.spans import (
    SpanRecorder,
    disable_span_recording,
    enable_span_recording,
)

__all__ = [
    "INSTRUMENTS", "MetricsRegistry", "NULL", "SpanRecorder",
    "counter", "gauge", "gauge_fn", "histogram", "enabled",
    "set_enabled", "registry", "use_registry", "series_name",
    "current_registry", "disabled_registry", "bound_registry",
    "enable_span_recording", "disable_span_recording", "spans",
    "start_exporters",
]

_REGISTRY = MetricsRegistry(enabled=True)

#: shared always-disabled registry: the per-query binding target for
#: executions with ``metrics_enabled=False`` (every bind returns NULL)
_DISABLED = MetricsRegistry(enabled=False)

# per-thread registry-binding stack (see bound_registry): executors push
# the registry a query resolved so every instrument bound while building
# and driving THAT query lands there — two concurrent queries with
# different metrics_enabled settings no longer fight over one global flag
_TLS = threading.local()


def registry() -> MetricsRegistry:
    """The process-default registry (what binds outside any query)."""
    return _REGISTRY


def current_registry() -> MetricsRegistry:
    """The registry module-level binders resolve against RIGHT NOW: the
    innermost :func:`bound_registry` on this thread, else the process
    default."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else _REGISTRY


def disabled_registry() -> MetricsRegistry:
    """The shared always-disabled registry (hands out falsy NULLs)."""
    return _DISABLED


@contextlib.contextmanager
def bound_registry(reg: MetricsRegistry):
    """Route this thread's module-level binders to ``reg`` for the
    duration.  Used by the executor to scope registry binding per query
    execution; long-lived components that bind instruments from their
    OWN threads (prefetch workers) capture ``current_registry()`` at
    construction and re-enter it on their thread, so a supervised
    rebuild mid-stream still binds to its query's registry.

    Exits remove THIS context's entry even when interleaved generators
    unwind out of order (a paused ``stream()`` holding an entry must not
    be popped by a sibling's exit)."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(reg)
    try:
        yield reg
    finally:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is reg:
                del stack[i]
                break


def use_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process registry (tests, bench isolation); returns the
    previous one so callers can restore it."""
    global _REGISTRY
    prev, _REGISTRY = _REGISTRY, reg
    return prev


def set_enabled(on: bool) -> None:
    """Flip metrics for instruments bound FROM NOW ON against the
    process-default registry (binding decides null vs live once, so the
    hot path never re-checks).  Per-query enablement is scoped by the
    executor via :func:`bound_registry` — this flag only governs binds
    outside any execution."""
    _REGISTRY.enabled = bool(on)


def enabled() -> bool:
    return current_registry().enabled


def counter(name: str, **labels):
    return current_registry().counter(name, **labels)


def gauge(name: str, **labels):
    return current_registry().gauge(name, **labels)


def histogram(name: str, **labels):
    return current_registry().histogram(name, **labels)


def gauge_fn(name: str, fn, **labels):
    return current_registry().gauge_fn(name, fn, **labels)


# -- per-execution exporters (started by the executor, opt-in) ------------


class Exporters:
    """Running exporters of one query execution; ``stop()`` is
    idempotent and flushes/dumps everything."""

    def __init__(self, prometheus=None, jsonl=None, trace_path=None,
                 installed_recorder=False):
        self.prometheus = prometheus
        self.jsonl = jsonl
        self._trace_path = trace_path
        self._installed_recorder = installed_recorder
        self._stopped = False

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self.jsonl is not None:
            self.jsonl.stop()
        if self.prometheus is not None:
            self.prometheus.stop()
        if self._trace_path is not None:
            rec = spans.recorder()
            if rec is not None:
                rec.dump(self._trace_path)
        if self._installed_recorder:
            # uninstall what WE installed: later queries must not keep
            # paying per-span record cost (or leak this run's events
            # into their traces); a user-installed recorder is left alone
            disable_span_recording()


def start_exporters(config, registry=None) -> Exporters | None:
    """Start whatever the config opted into; None when nothing is.
    Read with getattr so a caller-supplied config object predating these
    knobs (tests building bare namespaces) never breaks execution.
    ``registry`` scopes the exporters to one query's resolved registry
    (the executor passes it); default is the current binding."""
    port = getattr(config, "prometheus_port", None)
    jsonl_path = getattr(config, "metrics_jsonl_path", None)
    trace_path = getattr(config, "trace_path", None)
    trace_events = getattr(config, "trace_events", 0)
    if port is None and jsonl_path is None and trace_path is None:
        return None
    if registry is None:
        registry = current_registry()
    server = None
    if port is not None:
        from denormalized_tpu.obs.prometheus import PrometheusServer

        server = PrometheusServer(registry, port=port).start()
    snap = None
    if jsonl_path is not None:
        from denormalized_tpu.obs.jsonl import JsonlSnapshotter

        snap = JsonlSnapshotter(
            jsonl_path, registry,
            interval_s=getattr(config, "metrics_jsonl_interval_s", 1.0),
        ).start()
    installed = False
    if trace_path is not None and spans.recorder() is None:
        enable_span_recording(int(trace_events) or 65536)
        installed = True
    return Exporters(
        prometheus=server, jsonl=snap, trace_path=trace_path,
        installed_recorder=installed,
    )
