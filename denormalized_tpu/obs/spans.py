"""Ring-buffered structured span recorder → Chrome trace-event JSON.

Replaces the log-line spans of ``runtime/tracing.py`` as the machine
half of tracing: every :func:`~denormalized_tpu.runtime.tracing.span`
records a complete ("ph": "X") event here when a recorder is installed,
and fault injections land as instant ("ph": "i") events on the same
stream, so one dump shows the whole pipeline — batch processing, window
emits, checkpoint snapshots, prefetch restarts, injected faults — on a
per-thread timeline loadable in Perfetto (ui.perfetto.dev) or
chrome://tracing.

The ring is a preallocated slot list written lock-free per event under
the GIL (index reservation is a single ``itertools.count`` step, which
is atomic); the newest ``capacity`` events win.  Timestamps are
microseconds on the perf_counter clock, normalized so the earliest
retained event sits at t=0.
"""

from __future__ import annotations

import itertools
import json
import threading
import time


class SpanRecorder:
    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._slots: list = [None] * capacity
        self._next = itertools.count()
        self._t0 = time.perf_counter()

    # -- write side (hot-ish: per span, never per row) -------------------
    def record(
        self,
        name: str,
        t0_s: float,
        dur_s: float,
        args: dict | None = None,
        error: str | None = None,
    ) -> None:
        """One complete span: ``t0_s`` from ``time.perf_counter()``."""
        if error is not None:
            args = dict(args or ())
            args["error"] = error
        idx = next(self._next)
        self._slots[idx % self.capacity] = (
            idx, "X", name, t0_s, dur_s, threading.get_ident(), args or None,
        )

    def instant(self, name: str, args: dict | None = None) -> None:
        """One instant event (fault injections, restarts)."""
        idx = next(self._next)
        self._slots[idx % self.capacity] = (
            idx, "i", name, time.perf_counter(), 0.0,
            threading.get_ident(), args or None,
        )

    def flow(self, name: str, flow_id: int, phase: str,
             args: dict | None = None) -> None:
        """One flow event: ``phase`` is ``"s"`` (start), ``"t"`` (step)
        or ``"f"`` (finish).  Events sharing ``(name, flow_id)`` render
        as connected arrows in Perfetto — how sampled record lineage
        (obs/doctor/lineage.py) draws ingest → operator → emission
        chains on the same stream as the engine's spans."""
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        idx = next(self._next)
        self._slots[idx % self.capacity] = (
            idx, phase, name, time.perf_counter(), float(flow_id),
            threading.get_ident(), args or None,
        )

    # -- read side -------------------------------------------------------
    def events(self) -> list[tuple]:
        """Retained events, oldest first (slots carry their sequence
        number, so ring order reconstructs without a shared counter
        read racing the writers)."""
        return sorted(
            (s for s in self._slots if s is not None), key=lambda e: e[0]
        )

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        events = self.events()
        base = min((e[3] for e in events), default=self._t0)
        out = []
        for _idx, ph, name, t0, dur, tid, args in events:
            ev = {
                "ph": ph,
                "name": name,
                "pid": 1,
                "tid": tid,
                "ts": round((t0 - base) * 1e6, 1),
                "cat": name.split(".", 1)[0],
            }
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 1)
            if ph == "i":
                ev["s"] = "t"  # thread-scoped instant
            if ph in ("s", "t", "f"):
                # flow events reuse the dur slot as the flow id; "e"
                # binds the finish arrow to the enclosing slice's end
                ev["id"] = int(dur)
                if ph == "f":
                    ev["bp"] = "e"
            if args:
                ev["args"] = args
            if args and "error" in args:
                ev["cname"] = "terrible"  # red in the trace viewer
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


# -- process-global recorder (mirrors the tracing/fault globals) ----------

_RECORDER: SpanRecorder | None = None


def enable_span_recording(capacity: int = 65536) -> SpanRecorder:
    """Install (or replace) the process recorder; spans and fault
    events start landing in it immediately."""
    global _RECORDER
    _RECORDER = SpanRecorder(capacity)
    return _RECORDER


def disable_span_recording() -> None:
    global _RECORDER
    _RECORDER = None


def recorder() -> SpanRecorder | None:
    return _RECORDER
