"""Prometheus text-exposition rendering + the opt-in scrape endpoint.

Rendering follows the text exposition format 0.0.4: one ``# HELP`` /
``# TYPE`` pair per metric family, histograms expanded to cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``.  Every instrument
declared in the catalog is rendered — declared-but-unbound families
emit their HELP/TYPE header with no samples, so a scrape always shows
the full registered surface (the acceptance contract: a scrape during a
running query returns all registered instruments).

The endpoint is a stdlib ``ThreadingHTTPServer`` on a daemon thread,
opt-in via ``EngineConfig(prometheus_port=...)`` (0 = ephemeral port,
read it back from ``PrometheusServer.port``).  No dependencies — the
container has no prometheus_client, and the engine does not need one.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from denormalized_tpu.obs.catalog import INSTRUMENTS
from denormalized_tpu.obs.registry import Histogram, MetricsRegistry


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _labels_str(labels: tuple, extra: tuple = ()) -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels] + [
        f'{k}="{_escape_label(v)}"' for k, v in extra
    ]
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v) -> str:
    if v is None:
        return "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(registry: MetricsRegistry) -> str:
    """The full text exposition for one registry."""
    by_name: dict[str, list] = {name: [] for name in INSTRUMENTS}
    for inst in registry.instruments():
        by_name.setdefault(inst.name, []).append(inst)
    lines: list[str] = []
    for name, (kind, help_str, *_rest) in INSTRUMENTS.items():
        lines.append(f"# HELP {name} {help_str}")
        lines.append(f"# TYPE {name} {kind}")
        for inst in by_name.get(name, []):
            if isinstance(inst, Histogram):
                acc = 0
                for i, bound in enumerate(inst.bounds):
                    acc += inst.counts[i]
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_str(inst.labels, (('le', _fmt(bound)),))}"
                        f" {acc}"
                    )
                lines.append(
                    f"{name}_bucket"
                    f"{_labels_str(inst.labels, (('le', '+Inf'),))}"
                    f" {inst.count}"
                )
                lines.append(
                    f"{name}_sum{_labels_str(inst.labels)} {_fmt(inst.sum)}"
                )
                lines.append(
                    f"{name}_count{_labels_str(inst.labels)} {inst.count}"
                )
            else:
                lines.append(
                    f"{name}{_labels_str(inst.labels)} {_fmt(inst.value)}"
                )
    return "\n".join(lines) + "\n"


class PrometheusServer:
    """Scrape endpoint serving ``render(registry)`` at ``/metrics``
    (and ``/`` for convenience) on a daemon thread."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        self._registry = registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                body = render(server._registry).encode()
                self.send_response(200)
                self.send_header("Content-Type", server.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the engine's stderr

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            daemon=True,
            name=f"obs-prometheus-{self.port}",
        )

    def start(self) -> "PrometheusServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
