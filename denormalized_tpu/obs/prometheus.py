"""Prometheus text-exposition rendering + the opt-in scrape endpoint.

Rendering follows the text exposition format 0.0.4: one ``# HELP`` /
``# TYPE`` pair per metric family, histograms expanded to cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``.  Every instrument
declared in the catalog is rendered — declared-but-unbound families
emit their HELP/TYPE header with no samples, so a scrape always shows
the full registered surface (the acceptance contract: a scrape during a
running query returns all registered instruments).

The endpoint is a stdlib ``ThreadingHTTPServer`` on a daemon thread,
opt-in via ``EngineConfig(prometheus_port=...)`` (0 = ephemeral port,
read it back from ``PrometheusServer.port``).  No dependencies — the
container has no prometheus_client, and the engine does not need one.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from denormalized_tpu.obs.catalog import INSTRUMENTS
from denormalized_tpu.obs.registry import Histogram, MetricsRegistry


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _labels_str(labels: tuple, extra: tuple = ()) -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels] + [
        f'{k}="{_escape_label(v)}"' for k, v in extra
    ]
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v) -> str:
    if v is None:
        return "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(registry: MetricsRegistry) -> str:
    """The full text exposition for one registry."""
    by_name: dict[str, list] = {name: [] for name in INSTRUMENTS}
    for inst in registry.instruments():
        by_name.setdefault(inst.name, []).append(inst)
    lines: list[str] = []
    for name, (kind, help_str, *_rest) in INSTRUMENTS.items():
        lines.append(f"# HELP {name} {help_str}")
        lines.append(f"# TYPE {name} {kind}")
        for inst in by_name.get(name, []):
            if isinstance(inst, Histogram):
                acc = 0
                for i, bound in enumerate(inst.bounds):
                    acc += inst.counts[i]
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_str(inst.labels, (('le', _fmt(bound)),))}"
                        f" {acc}"
                    )
                lines.append(
                    f"{name}_bucket"
                    f"{_labels_str(inst.labels, (('le', '+Inf'),))}"
                    f" {inst.count}"
                )
                lines.append(
                    f"{name}_sum{_labels_str(inst.labels)} {_fmt(inst.sum)}"
                )
                lines.append(
                    f"{name}_count{_labels_str(inst.labels)} {inst.count}"
                )
            else:
                lines.append(
                    f"{name}{_labels_str(inst.labels)} {_fmt(inst.value)}"
                )
    return "\n".join(lines) + "\n"


class PrometheusServer:
    """Scrape endpoint serving ``render(registry)`` at ``/metrics``
    (and ``/`` for convenience) on a daemon thread — plus the pipeline
    doctor's introspection surface (``/healthz``, ``/queries``,
    ``/queries/<id>/plan|lineage|profile`` — see obs/doctor/http.py).

    Resilience contract (pinned by the concurrent-teardown test): a
    scrape racing operator/exporter teardown never gets a 5xx or a
    hung socket — the doctor router is total, and the exposition
    renderer reads single-writer instruments without locks."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        self._registry = registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, status, ctype, body):
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass  # client went away mid-write: their problem

            def _handle(self, method):
                from denormalized_tpu.obs.doctor import http as doctor_http

                if self.path.split("?")[0] in ("/", "/metrics"):
                    if method != "GET":
                        self.send_error(405)
                        return
                    self._respond(
                        200, server.CONTENT_TYPE,
                        render(server._registry).encode(),
                    )
                    return
                routed = doctor_http.route(self.path, method)
                if routed is None:
                    self.send_error(404)
                    return
                self._respond(*routed)

            def do_GET(self):  # noqa: N802 (http.server API)
                self._handle("GET")

            def do_POST(self):  # noqa: N802 (http.server API)
                self._handle("POST")

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the engine's stderr

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            daemon=True,
            name=f"obs-prometheus-{self.port}",
        )

    def start(self) -> "PrometheusServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
