"""The plan registry: every executing query, introspectable live.

``register_query`` is called by the executor right after the physical
plan is built: it assigns the SAME deterministic DFS node ids the
checkpointer uses (``state.checkpoint.assign_node_ids`` — so a dashboard
series, a checkpoint key, and a doctor suspect all name one node the
same way), stamps each operator with its id, attaches the lineage
tracker when sampling is configured, and files a :class:`QueryHandle`
under a process-global registry the HTTP surface reads.

``QueryHandle.snapshot()`` is the one data model every consumer renders:
``/queries/<id>/plan``, ``df.explain_analyze()``, and the ranked
bottleneck attribution all come from it.  On ``finish()`` the final
snapshot is frozen and the operator-tree reference is DROPPED — the
registry keeps a bounded ring of finished queries for post-run lookups
without pinning window state or prefetch buffers in memory (the same
no-graph-pinning rule the PR-6 gauge_fn weakref established).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from denormalized_tpu.obs.doctor.attribution import ATTRIBUTION_RULE, rank

_LOCK = threading.Lock()
_RUNNING: dict[str, "QueryHandle"] = {}
_RECENT: deque = deque(maxlen=16)
_IDS = itertools.count(1)


class QueryHandle:
    """Introspection handle of one query execution."""

    def __init__(self, query_id: str, root, node_ids: dict[int, str],
                 config=None, registry=None, lineage=None, shared=None):
        self.query_id = query_id
        self.root = root
        self._node_ids = node_ids  # id(op) -> node_id
        self.config = config
        self.registry = registry
        self.lineage = lineage
        # multi-query sharing (runtime/multi_query.py): when this query
        # is one of N subscribers folding from a shared operator tree,
        # ``shared`` carries {"group_size", "member", "weight", "label",
        # "group"} and every shared node's busy time / input wait /
        # state bytes are reported SCALED by weight (1/N) so per-query
        # cost stays truthful — the attribution rule documented in
        # docs/multi_query.md.  None = exclusive tree (the normal path).
        self.shared = shared
        self.profiler = None
        # serializes profiler start/stop: the HTTP surface is a
        # ThreadingHTTPServer, so two concurrent /profile/start requests
        # must not both pass the running check and orphan a sampler
        self._profiler_lock = threading.Lock()
        self.started_unix = time.time()
        self._started_mono = time.monotonic()
        self._finished_mono: float | None = None
        self._final_snapshot: dict | None = None
        self._final_state: dict | None = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._finished_mono is None

    def wall_s(self) -> float:
        end = (
            self._finished_mono
            if self._finished_mono is not None
            else time.monotonic()
        )
        return max(1e-9, end - self._started_mono)

    def finish(self) -> None:
        """Freeze the final snapshot, stop a still-running profiler, and
        drop the operator tree (see module docstring)."""
        if self._finished_mono is not None:
            return
        # finished must be VISIBLE before the profiler claim: a
        # concurrent start_profiler then either sees it (and refuses) or
        # already installed its sampler, which the claim below stops
        self._finished_mono = time.monotonic()
        self.stop_profiler()
        self._final_snapshot = self._snapshot_live()
        from denormalized_tpu.obs.doctor import statedoc

        try:
            self._final_state = statedoc.state_snapshot(self)
        except Exception:  # dnzlint: allow(broad-except) freezing the final /state view races operator teardown by design — a finished query without a state snapshot is degraded, not broken
            self._final_state = None
        self.root = None
        self._node_ids = {}
        with _LOCK:
            _RUNNING.pop(self.query_id, None)
            _RECENT.append(self)

    # -- profiler ----------------------------------------------------------
    def start_profiler(self, hz: float | None = None):
        """Start (or return) the query's sampler; None when the query
        already finished.  The finished re-check happens UNDER the lock:
        finish() marks finished before its stop_profiler claim, so a
        start racing the query's end either loses the check here or its
        fresh sampler is claimed-and-stopped by finish — never a leaked
        100 Hz thread taxing later queries."""
        from denormalized_tpu.obs.doctor.profiler import SamplingProfiler

        with self._profiler_lock:
            if not self.running:
                return None
            if self.profiler is not None and self.profiler.running:
                return self.profiler
            if hz is None:
                hz = getattr(self.config, "profiler_hz", 100.0)
            self.profiler = SamplingProfiler(hz=hz).start()
            return self.profiler

    def _profiler_snapshot(self) -> dict:
        """Status dict for /queries/<id> — claim the reference under
        the lock (same idiom as stop_profiler) so a snapshot racing
        start/stop sees one coherent sampler, then read off the claimed
        local."""
        with self._profiler_lock:
            prof = self.profiler
        return {
            "running": bool(prof and prof.running),
            "samples": getattr(prof, "samples_taken", 0),
        }

    def stop_profiler(self) -> int:
        # claim the reference under the lock, join OUTSIDE it (stop()
        # joins the sampler thread; blocking under a held lock is the
        # DNZ-L002 class).  A concurrent double-stop is harmless —
        # SamplingProfiler.stop is idempotent.
        with self._profiler_lock:
            prof = self.profiler
        if prof is None:
            return 0
        return prof.stop()

    # -- the data model ----------------------------------------------------
    def _walk(self):
        """(op, node_id, parent_node_id) over the live tree."""
        if self.root is None:
            return
        stack = [(self.root, None)]
        while stack:
            op, parent = stack.pop()
            nid = self._node_ids.get(id(op))
            yield op, nid, parent
            for c in getattr(op, "children", ()):
                stack.append((c, nid))

    def _node_stats(self, op, node_id, parent, wall_s) -> dict:
        """One node's live stats.  Every read is a plain attribute load
        off the single-writer operator — defensive defaults, no locks —
        so a snapshot racing operator teardown degrades, never raises."""
        busy_ms = float(getattr(op, "_dr_busy_ms", 0.0))
        wait_ms = float(getattr(op, "_dr_input_wait_s", 0.0)) * 1e3
        rows_in = int(getattr(op, "_dr_rows_in", 0))
        n = {
            "node_id": node_id,
            "label": _safe_label(op),
            "parent": parent,
            "children": [
                self._node_ids.get(id(c))
                for c in getattr(op, "children", ())
            ],
            "rows_in": rows_in,
            "batches": int(getattr(op, "_dr_batches", 0)),
            "busy_ms": round(busy_ms, 3),
            "busy_frac": round(busy_ms / (wall_s * 1e3), 4),
            "input_wait_ms": round(wait_ms, 3),
            "input_wait_frac": round(wait_ms / (wall_s * 1e3), 4),
            "rows_per_s": round(rows_in / wall_s, 1),
        }
        # source nodes: rows OUT of the reader + prefetch backpressure
        pump = getattr(op, "_pump", None)
        if pump is not None:
            try:
                workers = pump.workers
                n["queue_depth"] = sum(
                    max(0, w.enq_rowful - w.deq_rowful) for w in workers
                )
                n["queue_depth_limit"] = pump.depth * len(workers)
            except Exception:  # dnzlint: allow(broad-except) a live scrape racing pump teardown reads half-dead workers — degrade to no queue numbers, never 500 the introspection surface
                pass
        metrics = {}
        try:
            metrics = op.metrics() or {}
        except Exception:  # dnzlint: allow(broad-except) op.metrics() touching torn-down readers mid-scrape must degrade to {}, not take the endpoint down
            metrics = {}
        if "rows_out" in metrics:
            n["rows_out"] = metrics["rows_out"]
            n["rows_per_s"] = round(metrics["rows_out"] / wall_s, 1)
        # stateful operators carry an event-time watermark
        wm = getattr(op, "_watermark_ms", None)
        if wm is None:
            wm = getattr(op, "_watermark", None)
        if isinstance(wm, (int, float)):
            n["watermark_lag_ms"] = round(time.time() * 1000.0 - wm, 1)
        # state observatory columns (stateful operators only)
        try:
            sinfo = op._cached_state_info()
        except Exception:  # dnzlint: allow(broad-except) accounting races operator teardown (single-writer, lock-free) — degrade to no state columns, never 500 the plan endpoint
            sinfo = None
        if sinfo:
            n["state_bytes"] = int(sinfo.get("state_bytes") or 0)
            n["state_keys"] = int(sinfo.get("live_keys") or 0)
            n["state_slots"] = [
                int(sinfo.get("slot_live") or 0),
                int(sinfo.get("slot_capacity") or 0),
            ]
            if sinfo.get("oldest_event_lag_ms") is not None:
                n["state_oldest_lag_ms"] = sinfo["oldest_event_lag_ms"]
            try:
                from denormalized_tpu.obs.statewatch import side_live_keys

                skews = [
                    w.skew_factor(side_live_keys(sinfo, s))
                    for s, w, _r in op._state_watch_views() if w
                ]
                skews = [s for s in skews if s is not None]
                if skews:
                    n["state_skew"] = max(skews)
            except Exception:  # dnzlint: allow(broad-except) sketch reads race the operator thread like the accounting above — skew is an optional column
                pass
        if metrics:
            n["metrics"] = {
                k: v for k, v in metrics.items()
                if isinstance(v, (int, float))
            }
        if self.shared is not None:
            # shared-operator attribution: this tree serves group_size
            # queries at once, so THIS query's truthful cost share of
            # every node is its weight fraction of the measured totals.
            # With a weight_fn (the slice operator's per-subscriber cost
            # ledger) that fraction is MEASURED — a subsumption member
            # paying an expensive residual re-filter shows its real
            # share; without one, the even 1/group_size split applies.
            w = float(self.shared.get("weight", 1.0))
            fn = self.shared.get("weight_fn")
            if fn is not None:
                try:
                    w = float(fn())
                except Exception:  # dnzlint: allow(broad-except) the ledger read races the operator thread like every accounting read above — fall back to the even split
                    pass
            for k in ("busy_ms", "busy_frac", "input_wait_ms",
                      "input_wait_frac"):
                n[k] = round(n[k] * w, 4)
            if "state_bytes" in n:
                n["state_bytes"] = int(n["state_bytes"] * w)
            n["shared"] = {
                "subscribers": self.shared.get("group_size"),
                "fraction": round(w, 6),
            }
        return n

    def _snapshot_live(self) -> dict:
        wall_s = self.wall_s()
        nodes = [
            self._node_stats(op, nid, parent, wall_s)
            for op, nid, parent in self._walk()
        ]
        # render in DFS-preorder (node ids are "<i>_<Class>")
        nodes.sort(key=lambda n: _node_ord(n["node_id"]))
        suspects = rank(nodes, wall_s * 1e3)
        snap = {
            "query_id": self.query_id,
            "state": "running" if self.running else "finished",
            "started_unix": self.started_unix,
            "wall_s": round(wall_s, 3),
            "nodes": nodes,
            "attribution": {
                "rule": ATTRIBUTION_RULE,
                "suspects": suspects,
                "bottleneck": suspects[0]["node_id"] if suspects else None,
            },
            "profiler": self._profiler_snapshot(),
        }
        if self.lineage is not None:
            snap["lineage_samples"] = self.lineage.sampled_total
        if self.shared is not None:
            # the weight_fn callable is snapshot machinery, not payload
            # (the JSON route serializes this dict verbatim)
            snap["shared"] = {
                k: v for k, v in self.shared.items() if k != "weight_fn"
            }
        return snap

    def snapshot(self) -> dict:
        if self._final_snapshot is not None:
            return self._final_snapshot
        return self._snapshot_live()

    def state_snapshot(self) -> dict:
        """The state observatory's /state payload (live, or the frozen
        final view for a finished query)."""
        if self._final_state is not None:
            return self._final_state
        from denormalized_tpu.obs.doctor import statedoc

        return statedoc.state_snapshot(self)

    # -- rendering ---------------------------------------------------------
    def render(self) -> str:
        """The annotated plan tree + named bottleneck, from the current
        (or frozen final) snapshot."""
        snap = self.snapshot()
        by_id = {n["node_id"]: n for n in snap["nodes"]}
        roots = [n for n in snap["nodes"] if n["parent"] is None]
        lines: list[str] = [
            f"== {snap['query_id']} ({snap['state']}, "
            f"wall {snap['wall_s']}s) =="
        ]

        def emit(n: dict, depth: int) -> None:
            ann = [
                f"rows/s={n['rows_per_s']:,.0f}",
                f"busy={n['busy_ms']:.1f}ms ({n['busy_frac'] * 100:.1f}%)",
                f"wait={n['input_wait_ms']:.1f}ms",
            ]
            if "queue_depth" in n:
                ann.append(
                    f"qdepth={n['queue_depth']}/{n['queue_depth_limit']}"
                )
            if "watermark_lag_ms" in n:
                ann.append(f"wm_lag={n['watermark_lag_ms']:.0f}ms")
            if "state_bytes" in n:
                ann.append(
                    f"state={_fmt_bytes(n['state_bytes'])}/"
                    f"{n['state_keys']}keys"
                )
                if n.get("state_skew") is not None and n["state_skew"] >= 2:
                    ann.append(f"skew={n['state_skew']:.1f}")
            lines.append(
                "  " * depth + f"{n['node_id']}  [{', '.join(ann)}]"
            )
            for c in n["children"]:
                if c in by_id:
                    emit(by_id[c], depth + 1)

        for r in roots:
            emit(r, 0)
        sus = snap["attribution"]["suspects"]
        if sus:
            top = sus[0]
            lines.append(
                f"bottleneck: {top['node_id']} — "
                f"{top['share_of_wall'] * 100:.1f}% of wall "
                f"({top['basis']}: busy {top['busy_ms']:.1f}ms + "
                f"attributed {top['attributed_wait_ms']:.1f}ms)"
            )
            for i, s in enumerate(sus[1:4], start=2):
                lines.append(
                    f"  {i}. {s['node_id']} "
                    f"{s['share_of_wall'] * 100:.1f}%"
                )
        lines.append(f"rule: {ATTRIBUTION_RULE}")
        return "\n".join(lines)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024.0
    return f"{n:.1f}GB"  # pragma: no cover — loop always returns


def _safe_label(op) -> str:
    try:
        return op._label()
    except Exception:  # dnzlint: allow(broad-except) a label built from live operator state can race teardown — the class name is always available and always correct
        return type(op).__name__


def _node_ord(node_id) -> int:
    try:
        return int(str(node_id).split("_", 1)[0])
    except ValueError:
        return 1 << 30


# -- process-global registry ------------------------------------------------


def register_query(root, config=None, registry=None) -> QueryHandle | None:
    """File one executing query; returns None when the doctor is
    disabled (``EngineConfig(doctor_enabled=False)``)."""
    if config is not None and not getattr(config, "doctor_enabled", True):
        return None
    from denormalized_tpu.state.checkpoint import assign_node_ids

    node_ids = assign_node_ids(root)
    lineage = None
    every = getattr(config, "lineage_sample_every", None)
    if every:
        from denormalized_tpu.obs.doctor.lineage import LineageTracker

        lineage = LineageTracker(
            int(every),
            max_samples=getattr(config, "lineage_max_samples", 256),
        )
    handle = QueryHandle(
        f"q{next(_IDS)}", root, node_ids,
        config=config, registry=registry, lineage=lineage,
    )
    _stamp_and_bind(root, node_ids, registry, lineage)
    with _LOCK:
        _RUNNING[handle.query_id] = handle
    return handle


def _stamp_and_bind(root, node_ids, registry, lineage=None) -> None:
    """Stamp every operator once: node id for attribution/lineage
    keying, tracker for the handoff/emission hooks (base defaults are
    None, so un-doctored trees — direct build_physical callers — stay
    inert).  Stateful operators also bind their state-observatory
    gauges here — the node id IS the series label, and it only exists
    now.  Binds must land in the query's resolved registry even when
    the caller sits outside the executor's binding context.  Shared by
    register_query and register_shared so the binding rules cannot
    diverge between single- and multi-query registration."""
    import contextlib

    from denormalized_tpu import obs as _obs

    bind_ctx = (
        _obs.bound_registry(registry) if registry is not None
        else contextlib.nullcontext()
    )
    with bind_ctx:
        stack = [root]
        while stack:
            op = stack.pop()
            nid = node_ids.get(id(op))
            op._dr_node_id = nid
            op._dr_lineage = lineage
            if nid is not None:
                try:
                    op.bind_state_obs(nid)
                except Exception:  # dnzlint: allow(broad-except) a test double subclassing ExecOperator with a partial surface must not break query registration — its state gauges simply don't bind
                    pass
            stack.extend(getattr(op, "children", ()))


def register_shared(
    root, count: int, config=None, registry=None, labels=None
) -> list["QueryHandle"]:
    """File ``count`` subscriber queries over ONE shared operator tree
    (the multi-query runtime's registration): each gets its own query
    id and a ``shared`` descriptor with weight ``1/count``, so
    ``/queries/<id>/plan`` and ``/queries/<id>/state`` report that
    query's truthful cost share of the shared nodes.  When the root
    measures per-subscriber cost (``shared_fractions()`` — the slice
    operator's ledger of re-filter + accumulate + fold time), each
    descriptor also carries a ``weight_fn`` resolving the ACTUAL
    fraction at snapshot time: under subsumption sharing a member with
    an expensive residual predicate costs more than 1/N, and the even
    split would lie.  The tree is stamped and its state gauges bound
    ONCE (under the first handle) — the registry must not bind
    duplicate gauge series per subscriber.  One shared LineageTracker
    (when ``lineage_sample_every`` is set) serves every member: the
    slice operator tags emissions with the member's query id via the
    ``_dr_mq_qids`` stamp, and each handle's ``/lineage`` filters to
    its own.  Returns [] when the doctor is disabled."""
    if config is not None and not getattr(config, "doctor_enabled", True):
        return []
    from denormalized_tpu.state.checkpoint import assign_node_ids

    node_ids = assign_node_ids(root)
    qids = [f"q{next(_IDS)}" for _ in range(count)]
    lineage = None
    every = getattr(config, "lineage_sample_every", None)
    if every:
        from denormalized_tpu.obs.doctor.lineage import LineageTracker

        lineage = LineageTracker(
            int(every),
            max_samples=getattr(config, "lineage_max_samples", 256),
        )
    fractions = getattr(root, "shared_fractions", None)

    def _weight_fn_for(tag: int):
        if fractions is None:
            return None

        def weight() -> float:
            return float(fractions().get(tag, 1.0 / count))

        return weight

    handles = []
    for i, qid in enumerate(qids):
        handles.append(
            QueryHandle(
                qid, root, node_ids, config=config, registry=registry,
                lineage=lineage,
                shared={
                    "group_size": count,
                    "member": i,
                    "weight": 1.0 / count,
                    "weight_fn": _weight_fn_for(i),
                    "label": labels[i] if labels else None,
                    "group": qids,
                },
            )
        )
    _stamp_and_bind(root, node_ids, registry, lineage)
    # subscriber tag → query id, read by the slice operator's emission
    # hook to tag lineage links per member query
    root._dr_mq_qids = {i: qid for i, qid in enumerate(qids)}
    with _LOCK:
        for h in handles:
            _RUNNING[h.query_id] = h
    return handles


def get_query(query_id: str) -> QueryHandle | None:
    with _LOCK:
        h = _RUNNING.get(query_id)
        if h is not None:
            return h
        for h in _RECENT:
            if h.query_id == query_id:
                return h
    return None


def queries() -> list[QueryHandle]:
    """Running queries first (newest last), then the retained finished
    ring."""
    with _LOCK:
        return list(_RUNNING.values()) + list(_RECENT)


def running_count() -> int:
    with _LOCK:
        return len(_RUNNING)


def counts() -> tuple[int, int]:
    """(running, retained-finished) under ONE lock acquisition, so a
    liveness payload can never show a torn (e.g. negative) count."""
    with _LOCK:
        return len(_RUNNING), len(_RECENT)
