"""State observatory doctor pass — /state payloads + health verdicts.

Builds on :mod:`denormalized_tpu.obs.statewatch`: every stateful
operator's exact accounting (``state_info()``), key-distribution
sketches, and growth ring roll up into one per-query snapshot served at
``GET /queries/<id>/state`` and frozen into the finished-query ring.

Verdicts are RANKED (severity desc) and rule-documented — the rule text
ships inside every payload so a dashboard never has to guess what a
verdict means (the same contract as the bottleneck attribution rule).
"""

from __future__ import annotations

import time

#: verdict rules, shipped verbatim in every /state payload
STATE_VERDICT_RULES = (
    "skewed-join-side: one join side's top-1 sketched key holds >= "
    "{share:.0%} of that side's rows AND skew factor (top-1 share x "
    "live keys) >= {factor:g}; "
    "unbounded-session-growth: a session operator's state-bytes growth "
    "fit is positive with r2 >= 0.5 over >= 3 samples; "
    "retention-leak: oldest retained event time lags the operator "
    "watermark by more than {leak} retention units (session gap / "
    "window length / join retention); "
    "state-budget-pressure: projected time-to-budget against "
    "EngineConfig(state_budget_bytes) is under {pressure:.0f}s; "
    "spill-thrashing: the cold tier reloaded >= {thrash_ratio:.0%} of "
    "the blocks it spilled within the rolling {thrash_window:.0f}s "
    "window (>= {thrash_min} spills) — the working set does not fit the "
    "budget and state is ping-ponging through the LSM."
)

SKEW_SHARE_MIN = 0.2
SKEW_FACTOR_MIN = 4.0
RETENTION_LEAK_UNITS = 3
BUDGET_PRESSURE_S = 600.0
THRASH_RATIO_MIN = 0.5
THRASH_SPILLS_MIN = 4


def rules_text() -> str:
    from denormalized_tpu.state.tiering import THRASH_WINDOW_S

    return STATE_VERDICT_RULES.format(
        share=SKEW_SHARE_MIN, factor=SKEW_FACTOR_MIN,
        leak=RETENTION_LEAK_UNITS, pressure=BUDGET_PRESSURE_S,
        thrash_ratio=THRASH_RATIO_MIN, thrash_window=THRASH_WINDOW_S,
        thrash_min=THRASH_SPILLS_MIN,
    )


def node_state(op, node_id) -> dict | None:
    """One operator's /state entry, or None for stateless operators.
    Defensive throughout: a read racing operator teardown degrades to a
    partial entry, never raises into the endpoint."""
    try:
        info = op.state_info()
    except Exception:  # dnzlint: allow(broad-except) accounting reads race the operator thread by design (single-writer, lock-free) — a torn read degrades to no entry, never a 500
        return None
    if info is None:
        return None
    node = {"node_id": node_id, "label": type(op).__name__}
    node.update(info)
    sketches: dict = {}
    try:
        views = op._state_watch_views()
    except Exception:  # dnzlint: allow(broad-except) same teardown race as above — accounting without sketches is still a useful entry
        views = []
    from denormalized_tpu.obs.statewatch import side_live_keys

    for side, watch, resolve in views:
        if not watch:
            continue
        sketches[side or "all"] = watch.summary(
            live_keys=side_live_keys(info, side), resolve=resolve
        )
    if sketches:
        node["sketches"] = sketches
    sw = getattr(op, "_sw", None)
    if sw:
        # /state polls feed the growth ring too, so a budget forecast
        # exists (and tightens) even without a JSONL/Prometheus exporter
        sw.record_sample(info.get("state_bytes", 0))
        fc = sw.forecast()
        if fc is not None:
            node["forecast"] = fc
    return node


def _query_forecast(nodes: list[dict], budget) -> dict | None:
    """Query-level growth projection: slopes and current bytes sum over
    the per-node fits (the budget bounds TOTAL state)."""
    fits = [n["forecast"] for n in nodes if n.get("forecast")]
    if not fits:
        return None
    slope = sum(f["slope_bytes_per_s"] for f in fits)
    current = sum(n.get("state_bytes") or 0 for n in nodes)
    out = {
        "slope_bytes_per_s": round(slope, 3),
        "current_bytes": current,
        "r2_min": min(f["r2"] for f in fits),
        "samples": min(f["samples"] for f in fits),
        "window_s": max(f["window_s"] for f in fits),
    }
    if budget is not None:
        out["budget_bytes"] = budget
        if current >= budget:
            out["time_to_budget_s"] = 0.0
        elif slope > 0:
            out["time_to_budget_s"] = round((budget - current) / slope, 1)
        else:
            out["time_to_budget_s"] = None
    return out


def verdicts(nodes: list[dict], budget=None) -> list[dict]:
    """Ranked health verdicts over the per-node state entries."""
    out: list[dict] = []
    for n in nodes:
        nid = n.get("node_id")
        sketches = n.get("sketches", {})
        if n.get("op") == "join":
            for side in ("left", "right"):
                s = sketches.get(side)
                if not s or not s.get("hot_keys"):
                    continue
                top = s["hot_keys"][0]
                skewf = s.get("skew_factor") or 0.0
                if (
                    top["share"] >= SKEW_SHARE_MIN
                    and skewf >= SKEW_FACTOR_MIN
                ):
                    side_info = n.get("sides", {}).get(side, {})
                    out.append({
                        "kind": "skewed-join-side",
                        "node_id": nid,
                        "severity": round(min(1.0, top["share"]), 4),
                        "side": side,
                        "key": top["key"],
                        "share": top["share"],
                        "err_rows": top["err_rows"],
                        "skew_factor": skewf,
                        "detail": (
                            f"{side} side: key {top['key']!r} holds "
                            f"~{top['share']:.0%} of sketched rows "
                            f"(overestimate <= {top['err_rows']} rows) "
                            f"across {side_info.get('live_keys', '?')} "
                            "live keys — a celebrity key will serialize "
                            "the probe and dominate side memory"
                        ),
                    })
        unit = n.get("retention_unit_ms")
        lag = n.get("oldest_event_lag_ms")
        if unit and lag is not None and lag > RETENTION_LEAK_UNITS * unit:
            out.append({
                "kind": "retention-leak",
                "node_id": nid,
                "severity": round(
                    min(1.0, lag / (10.0 * unit)), 4
                ),
                "lag_ms": lag,
                "retention_unit_ms": unit,
                "detail": (
                    f"oldest retained event lags the watermark by "
                    f"{lag / unit:.1f} retention units "
                    f"({lag}ms vs unit {unit}ms) — state is being "
                    "retained far past its close horizon"
                ),
            })
        sp = n.get("spill")
        if sp:
            rs = int(sp.get("recent_spill_blocks") or 0)
            rr = int(sp.get("recent_reload_blocks") or 0)
            if rs >= THRASH_SPILLS_MIN and rr >= THRASH_RATIO_MIN * rs:
                out.append({
                    "kind": "spill-thrashing",
                    "node_id": nid,
                    "severity": round(min(1.0, rr / max(rs, 1)), 4),
                    "recent_spill_blocks": rs,
                    "recent_reload_blocks": rr,
                    "spilled_bytes": n.get("spilled_bytes") or 0,
                    "detail": (
                        f"cold tier reloaded {rr} of the {rs} blocks it "
                        "spilled inside the rolling window — the hot "
                        "working set exceeds state_budget_bytes, so "
                        "state is ping-ponging through the LSM instead "
                        "of settling; raise the budget or expect "
                        "disk-speed throughput"
                    ),
                })
        fc = n.get("forecast")
        if (
            n.get("op") in ("session", "session_ref")
            and fc
            and fc["slope_bytes_per_s"] > 0
            and fc["r2"] >= 0.5
            and fc["samples"] >= 3
        ):
            sev = 0.3
            if budget is not None:
                # per-node forecasts are computed budget-less; derive
                # this node's time-to-budget from its slope so severity
                # actually escalates as exhaustion nears (a fc.get of a
                # key that is never set would pin severity at 0.3)
                cur = n.get("state_bytes") or 0
                tt = (
                    0.0 if cur >= budget
                    else (budget - cur) / fc["slope_bytes_per_s"]
                )
                sev = max(sev, min(1.0, BUDGET_PRESSURE_S / max(tt, 1.0)))
            out.append({
                "kind": "unbounded-session-growth",
                "node_id": nid,
                "severity": round(sev, 4),
                "slope_bytes_per_s": fc["slope_bytes_per_s"],
                "r2": fc["r2"],
                "detail": (
                    f"session state growing at "
                    f"{fc['slope_bytes_per_s']:.0f} B/s (r2 "
                    f"{fc['r2']:.2f} over {fc['window_s']:.0f}s) with "
                    "no sign of plateau — keys are opening faster than "
                    "the gap closes them"
                ),
            })
        if budget is not None and fc:
            tt_n = None
            if fc.get("slope_bytes_per_s", 0) > 0:
                cur = n.get("state_bytes") or 0
                if cur >= budget:
                    tt_n = 0.0
                else:
                    tt_n = (budget - cur) / fc["slope_bytes_per_s"]
            if tt_n is not None and tt_n <= BUDGET_PRESSURE_S:
                out.append({
                    "kind": "state-budget-pressure",
                    "node_id": nid,
                    "severity": round(
                        min(1.0, 1.0 - tt_n / (2 * BUDGET_PRESSURE_S)), 4
                    ),
                    "time_to_budget_s": round(tt_n, 1),
                    "detail": (
                        f"on the current growth trend this node alone "
                        f"reaches the {budget}-byte state budget in "
                        f"{tt_n:.0f}s"
                    ),
                })
    out.sort(key=lambda v: -v["severity"])
    return out


def state_snapshot(handle) -> dict:
    """The full /state payload of one query."""
    nodes = []
    shared = getattr(handle, "shared", None)
    for op, nid, _parent in handle._walk():
        ns = node_state(op, nid)
        if ns is not None:
            nodes.append(ns)
    budget = (
        getattr(handle.config, "state_budget_bytes", None)
        if handle.config is not None else None
    )
    total = sum(n.get("state_bytes") or 0 for n in nodes)
    qf = _query_forecast(nodes, budget)
    ranked = verdicts(nodes, budget)
    # the budget bounds TOTAL state: several individually-slow growers
    # can jointly breach it inside the pressure window while no single
    # node does — the QUERY-level projection must raise the verdict too
    qtt = (qf or {}).get("time_to_budget_s")
    if qtt is not None and qtt <= BUDGET_PRESSURE_S:
        ranked.append({
            "kind": "state-budget-pressure",
            "node_id": None,
            "severity": round(
                min(1.0, 1.0 - qtt / (2 * BUDGET_PRESSURE_S)), 4
            ),
            "time_to_budget_s": qtt,
            "detail": (
                f"TOTAL state across all nodes reaches the {budget}-byte "
                f"budget in {qtt:.0f}s on the current combined trend"
            ),
        })
        ranked.sort(key=lambda v: -v["severity"])
    if shared is not None:
        # shared-operator attribution (docs/multi_query.md): each node's
        # DISPLAYED state bytes are this query's 1/N share, with the raw
        # number kept under "state_bytes_shared_total".  The scaling
        # happens strictly AFTER the budget math above — verdicts,
        # forecasts, and time-to-budget consume RAW bytes, because the
        # budget bounds live memory, which does not shrink by being
        # shared (scaled inputs would silence budget pressure by a
        # factor of N exactly in the high-fan-in case)
        w = float(shared.get("weight", 1.0))
        fn = shared.get("weight_fn")
        if fn is not None:
            # measured per-subscriber fraction (the slice operator's
            # cost ledger) — see registry.register_shared
            try:
                w = float(fn())
            except Exception:  # dnzlint: allow(broad-except) ledger read races the operator thread — fall back to the even split
                pass
        for ns in nodes:
            raw = int(ns.get("state_bytes") or 0)
            ns["state_bytes_shared_total"] = raw
            ns["state_bytes"] = int(raw * w)
            ns["shared"] = {
                "subscribers": shared.get("group_size"),
                "fraction": round(w, 6),
            }
    return {
        "query_id": handle.query_id,
        "state": "running" if handle.running else "finished",
        "t": time.time(),
        "budget_bytes": budget,
        # raw total: the budget/verdict basis (per-node dicts carry the
        # per-query share when this handle is a shared subscriber)
        "total_state_bytes": total,
        "nodes": nodes,
        "forecast": qf,
        "verdicts": ranked,
        "rules": rules_text(),
    }
