"""HTTP routing for the doctor — mounted on the PR-6 Prometheus server.

The stdlib server (``EngineConfig(prometheus_port=...)``) serves, next
to ``/metrics``:

- ``GET /healthz`` — liveness: always 200 while the server is up, with
  running/retained query counts;
- ``GET /queries`` — every registered query (running + the retained
  finished ring);
- ``GET /queries/<id>/plan`` — the full live plan snapshot: per-node
  rows/s, batch-time share, queue depth, watermark lag, plus the ranked
  bottleneck attribution;
- ``GET /queries/<id>/state`` — the state observatory: per-stateful-node
  exact accounting (live bytes/keys, slot occupancy, oldest-retained
  lag), sketch-derived hot keys + skew factor, growth forecasts with
  time-to-budget, and ranked health verdicts;
- ``GET /queries/<id>/lineage[?window_start_ms=&source=]`` — sampled
  record lineage chains (ingest offset → operator hops → emission);
- ``GET|POST /queries/<id>/profile/start[?hz=]`` / ``.../profile/stop``
  — the on-demand sampling profiler; ``GET /queries/<id>/profile``
  returns the folded stacks as text/plain.

Contract: :func:`route` is TOTAL — it never raises.  A scrape racing
operator teardown gets a degraded JSON body, never a 5xx or a hung
socket (pinned by the concurrent-teardown test riding the lock witness).
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs, urlsplit

from denormalized_tpu.obs.doctor import registry as _reg

_JSON = "application/json; charset=utf-8"
_TEXT = "text/plain; charset=utf-8"


def _json_resp(status: int, obj) -> tuple[int, str, bytes]:
    return status, _JSON, json.dumps(obj, default=str).encode()


def healthz() -> tuple[int, str, bytes]:
    running, retained = _reg.counts()
    return _json_resp(200, {
        "status": "ok",
        "queries_running": running,
        "queries_retained": retained,
    })


def _query_row(h) -> dict:
    return {
        "query_id": h.query_id,
        "state": "running" if h.running else "finished",
        "started_unix": h.started_unix,
        "wall_s": round(h.wall_s(), 3),
        "lineage": h.lineage is not None,
        "profiler_running": bool(h.profiler and h.profiler.running),
    }


def route(path: str, method: str = "GET") -> tuple[int, str, bytes] | None:
    """(status, content_type, body) for doctor paths; None when the path
    is not ours (the caller then 404s).  Never raises."""
    try:
        return _route(path, method)
    except Exception as e:  # dnzlint: allow(broad-except) the introspection surface must degrade to an error payload when a snapshot races operator teardown — never a 5xx, never a closed socket mid-scrape
        return _json_resp(200, {"error": f"{type(e).__name__}: {e}"})


def _route(path: str, method: str) -> tuple[int, str, bytes] | None:
    split = urlsplit(path)
    parts = [p for p in split.path.split("/") if p]
    params = parse_qs(split.query)
    if parts == ["healthz"]:
        return healthz()
    if not parts or parts[0] != "queries":
        return None
    if len(parts) == 1:
        return _json_resp(200, {
            "queries": [_query_row(h) for h in _reg.queries()],
        })
    handle = _reg.get_query(parts[1])
    if handle is None:
        return _json_resp(404, {
            "error": f"unknown query {parts[1]!r}",
            "known": [h.query_id for h in _reg.queries()],
        })
    tail = parts[2:]
    if tail == ["plan"] or tail == []:
        return _json_resp(200, handle.snapshot())
    if tail == ["state"]:
        return _json_resp(200, handle.state_snapshot())
    if tail == ["lineage"]:
        if handle.lineage is None:
            return _json_resp(200, {
                "error": "lineage sampling is off for this query — set "
                "EngineConfig(lineage_sample_every=N)",
                "chains": [],
            })
        ws = params.get("window_start_ms", [None])[0]
        src = params.get("source", [None])[0]
        chains = handle.lineage.chains(
            window_start_ms=int(ws) if ws is not None else None,
            source=src,
            # a shared pipeline's tracker serves every member query —
            # filter the view to THIS handle's tagged emissions
            query=handle.query_id if handle.shared is not None else None,
        )
        return _json_resp(200, {
            "sampled_total": handle.lineage.sampled_total,
            "sample_every": handle.lineage.sample_every,
            "chains": chains,
        })
    if tail == ["profile", "start"]:
        hz = params.get("hz", [None])[0]
        # the authoritative finished check happens inside start_profiler
        # under its lock (a bare handle.running pre-check here would
        # race finish() and leak a sampler)
        prof = handle.start_profiler(float(hz) if hz else None)
        if prof is None:
            return _json_resp(404, {"error": "query already finished"})
        return _json_resp(200, {
            "profiling": True, "interval_s": prof.interval_s,
        })
    if tail == ["profile", "stop"]:
        n = handle.stop_profiler()
        return _json_resp(200, {"profiling": False, "samples": n})
    if tail == ["profile"]:
        if handle.profiler is None:
            return _json_resp(200, {
                "error": "profiler never started for this query",
            })
        return 200, _TEXT, handle.profiler.folded().encode()
    return _json_resp(404, {"error": f"unknown doctor path {path!r}"})
