"""Sampled record lineage: "why is this window late" as a lookup.

A configurable sample of rows (``EngineConfig(lineage_sample_every=N)``:
every Nth row per partition, capped at ``lineage_max_samples`` live
samples) is tagged at ingest with ``(source, partition, offset snapshot,
event time)``.  The tag is threaded through the pipeline:

- **ingest** — ``SourceExec`` registers the sample the moment the batch
  leaves the reader, with the reader's own post-batch offset snapshot
  (the same snapshot checkpoint barriers persist, so the recorded offset
  is replay-exact);
- **hops** — every operator's instrumented input handoff
  (``ExecOperator._doctor_input``) records the first wall-clock moment a
  batch whose event-time range covers the sample reached that operator
  (batch-granular by design: the vectorized kernels never see per-row
  Python, so lineage must not reintroduce it);
- **emission** — stateful operators report every emitted window's
  ``[start, end)``; a sample lands in the window containing its event
  time, closing the chain.

Each stage also lands a flow event (``ph: s/t/f`` sharing the sample id)
on the PR-6 span stream, so a Perfetto trace draws the chain as arrows
across threads — and the whole chain set is queryable live via
``GET /queries/<id>/lineage``.

Hot-path contract: with lineage off (the default) the only cost is one
``is None`` check per stream item.  With it on, the per-batch cost is an
O(rows) min/max over the timestamp column plus an O(live samples)
vectorized compare — documented in docs/observability.md.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.obs import spans as obs_spans


class LineageTracker:
    """Per-query sample store.  Mutated from the consumer thread AND the
    join's pump threads, so mutation is lock-protected; the lock only
    ever guards plain list/array bookkeeping (no blocking calls)."""

    def __init__(self, sample_every: int, max_samples: int = 256):
        if sample_every < 1:
            raise ValueError(
                f"lineage_sample_every must be >= 1, got {sample_every}"
            )
        self.sample_every = int(sample_every)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        #: sample id -> record dict (the chain under assembly)
        self._samples: dict[int, dict] = {}
        #: rows seen per (source, partition) — drives every-Nth sampling
        self._seen: dict[tuple, int] = {}
        #: parallel arrays rebuilt on ingest for vectorized matching
        self._live_ids: list[int] = []
        self._live_ts = np.empty(0, dtype=np.int64)
        #: (sample id, node id) hop dedup
        self._hopped: set[tuple] = set()
        self.sampled_total = 0

    # -- ingest (SourceExec) ---------------------------------------------
    def ingest(self, source: str, partition: int, offset_snapshot: dict,
               batch) -> None:
        key = (source, partition)
        prev = self._seen.get(key, 0)
        n = batch.num_rows
        self._seen[key] = prev + n
        first = (-prev) % self.sample_every
        # dnzlint: allow(unguarded) racy fullness peek only skips work early; the insert loop re-checks max_samples under _lock before every admit
        if first >= n or len(self._samples) >= self.max_samples:
            return
        ts_col = np.asarray(
            batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
        )
        rec = obs_spans.recorder()
        now = time.time()
        with self._lock:
            for idx in range(first, n, self.sample_every):
                if len(self._samples) >= self.max_samples:
                    break
                sid = next(self._ids)
                self._samples[sid] = {
                    "id": sid,
                    "source": source,
                    "partition": int(partition),
                    "offset": dict(offset_snapshot or {}),
                    "row_in_batch": int(idx),
                    "event_time_ms": int(ts_col[idx]),
                    "ingest_wall": now,
                    "hops": [],
                    "emissions": [],
                }
                self.sampled_total += 1
                if rec is not None:
                    rec.flow("lineage", sid, "s", {
                        "source": source, "partition": int(partition),
                        "event_time_ms": int(ts_col[idx]),
                    })
            self._rebuild_live()

    def _rebuild_live(self) -> None:
        self._live_ids = list(self._samples)
        self._live_ts = np.fromiter(
            (self._samples[i]["event_time_ms"] for i in self._live_ids),
            dtype=np.int64, count=len(self._live_ids),
        )

    # -- operator handoff ------------------------------------------------
    def hop(self, node_id: str | None, batch) -> None:
        """Record the first arrival of each covered sample at a node.
        Matching is by event-time-range containment — exact before any
        aggregation, approximate after (emissions re-stamp event time),
        which is why emission matching is a separate explicit call."""
        # dnzlint: allow(unguarded) racy liveness peek only skips the column decode; matching below re-reads _live_ids/_live_ts as a consistent pair under _lock
        if not self._live_ids or node_id is None:
            return
        if not batch.schema.has(CANONICAL_TIMESTAMP_COLUMN):
            return
        ts = np.asarray(
            batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
        )
        if not len(ts):
            return
        mn, mx = int(ts.min()), int(ts.max())
        rec = obs_spans.recorder()
        now = time.time()
        with self._lock:
            # _live_ts indices resolve through _live_ids — both rebuilt
            # together under _lock, so the pair must be read under it
            # too or a concurrent ingest leaves the indices pointing
            # into a different generation of the id list
            hit = (self._live_ts >= mn) & (self._live_ts <= mx)
            if not hit.any():
                return
            for i in np.nonzero(hit)[0]:
                sid = self._live_ids[int(i)]
                s = self._samples.get(sid)
                if s is None or (sid, node_id) in self._hopped:
                    continue
                self._hopped.add((sid, node_id))
                s["hops"].append({"node_id": node_id, "wall": now})
                if rec is not None:
                    rec.flow("lineage", sid, "t", {"node_id": node_id})

    # -- emission (stateful operators) ------------------------------------
    def emitted(
        self, node_id: str | None, start_ms, end_ms, query: str | None = None
    ) -> None:
        """One emitted window ``[start_ms, end_ms)`` (scalars or equal-
        length arrays for a multi-window sweep, e.g. a session close
        cycle).  Every live sample whose event time the window contains
        gains an emission link — completing its ingest → emission chain.
        ``query`` tags the link with the subscriber query id when a
        SHARED pipeline emits for one of its member queries, so one
        tracker serves every member's ``/lineage`` view."""
        # dnzlint: allow(unguarded) racy liveness peek only skips work; the matching loop reads _live_ids/_live_ts under _lock
        if not self._live_ids or node_id is None:
            return
        starts = np.atleast_1d(np.asarray(start_ms, dtype=np.int64))
        ends = np.atleast_1d(np.asarray(end_ms, dtype=np.int64))
        rec = obs_spans.recorder()
        now = time.time()
        with self._lock:
            for i, sid in enumerate(self._live_ids):
                ts = int(self._live_ts[i])
                win = np.nonzero((starts <= ts) & (ts < ends))[0]
                if not len(win):
                    continue
                s = self._samples.get(sid)
                if s is None:
                    continue
                w = int(win[0])
                link = {
                    "node_id": node_id,
                    "window_start_ms": int(starts[w]),
                    "window_end_ms": int(ends[w]),
                    "wall": now,
                    "emit_lag_ms": round(now * 1000.0 - int(ends[w]), 3),
                }
                if query is not None:
                    link["query"] = query
                s["emissions"].append(link)
                if rec is not None:
                    rec.flow("lineage", sid, "f", {
                        "node_id": node_id,
                        "window_start_ms": int(starts[w]),
                        "window_end_ms": int(ends[w]),
                    })

    # -- read side ---------------------------------------------------------
    def chains(self, window_start_ms: int | None = None,
               source: str | None = None,
               query: str | None = None) -> list[dict]:
        """Assembled chains, optionally filtered to samples that landed
        in the window starting at ``window_start_ms`` (the "why is this
        window late" lookup), to one source, or — for a shared pipeline
        whose tracker serves several member queries — to samples with an
        emission tagged for ``query`` (untagged emission links, e.g.
        from a non-shared downstream operator, stay in every member's
        view)."""
        with self._lock:
            out = [dict(s) for s in self._samples.values()]
        if source is not None:
            out = [s for s in out if s["source"] == source]
        if query is not None:
            out = [
                dict(
                    s,
                    emissions=[
                        e for e in s["emissions"]
                        if e.get("query") in (None, query)
                    ],
                )
                for s in out
                if any(
                    e.get("query") in (None, query)
                    for e in s["emissions"]
                ) or not s["emissions"]
            ]
        if window_start_ms is not None:
            out = [
                s for s in out
                if any(
                    e["window_start_ms"] == window_start_ms
                    for e in s["emissions"]
                )
            ]
        return out
