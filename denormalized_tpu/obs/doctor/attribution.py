"""Bottleneck attribution: turn per-node busy/wait numbers into ONE
ranked suspect list, so the slowest stage is *named*, never inferred by
the reader from raw series.

The attribution rule (documented in docs/observability.md and repeated
verbatim in every plan snapshot so a dashboard can render it next to the
ranking):

1. Every operator that brackets its batch processing reports **measured
   busy time** (``dnz_op_batch_ms`` — eval + device dispatch + emission
   assembly, with time suspended in downstream operators excluded).
2. Every operator also reports how long it spent **waiting on its
   upstream** to yield the next item (``dnz_op_input_wait_ms``).  In a
   pull pipeline that wait is exactly the upstream subtree's production
   time, so the *residual* of a node's input wait after subtracting its
   children's measured busy + wait is attributed to the children's
   un-bracketed work — for a leaf ``SourceExec`` that residual IS its
   fetch+decode time, which has no bracket of its own.  Multi-child
   nodes (the join, whose sides run on pump threads) split the residual
   evenly across children, a documented approximation.
3. A node's **total** = measured busy + attributed residual; its score
   is total / query wall time (the DS2-style busy fraction).  The node
   with the highest score is the named bottleneck.

The rule deliberately uses *time shares*, not rows/s: a stage can move
few rows slowly (a throttled UDF) or many rows quickly, and only the
share of wall time it consumes says which stage to fix first.
"""

from __future__ import annotations

ATTRIBUTION_RULE = (
    "rank = (measured batch-processing time + input-wait residual "
    "attributed from the consumer) / query wall time; the highest share "
    "is the named bottleneck.  A source's share is its consumer's input "
    "wait minus the measured time of everything between them (its own "
    "un-bracketed fetch+decode); multi-input operators split the "
    "residual evenly across inputs."
)


def rank(nodes: list[dict], wall_ms: float) -> list[dict]:
    """Ranked suspects from plan-node stat dicts (see
    ``QueryHandle.snapshot``).  Each input dict needs ``node_id``,
    ``label``, ``children`` (node ids), ``busy_ms``, ``input_wait_ms``.
    Returns one entry per node, most suspect first."""
    by_id = {n["node_id"]: n for n in nodes}
    attributed: dict[str, float] = {n["node_id"]: 0.0 for n in nodes}
    for n in nodes:
        kids = [by_id[c] for c in n.get("children", ()) if c in by_id]
        if not kids:
            continue
        accounted = sum(
            k.get("busy_ms", 0.0) + k.get("input_wait_ms", 0.0)
            for k in kids
        )
        residual = max(0.0, n.get("input_wait_ms", 0.0) - accounted)
        share = residual / len(kids)
        for k in kids:
            attributed[k["node_id"]] += share
    out = []
    for n in nodes:
        busy = float(n.get("busy_ms", 0.0))
        attr = attributed[n["node_id"]]
        total = busy + attr
        basis = (
            "measured" if attr == 0.0
            else "attributed" if busy == 0.0
            else "mixed"
        )
        out.append({
            "node_id": n["node_id"],
            "label": n.get("label", n["node_id"]),
            "busy_ms": round(busy, 3),
            "attributed_wait_ms": round(attr, 3),
            "total_ms": round(total, 3),
            "share_of_wall": round(total / wall_ms, 4) if wall_ms else 0.0,
            "basis": basis,
        })
    out.sort(key=lambda s: s["total_ms"], reverse=True)
    return out
