"""Verdicts that act — the doctor's first closed control loop.

PRs 6–9 built the sense layer: every stateful operator feeds intern-time
Space-Saving sketches and the doctor ranks a ``skewed-join-side``
verdict when one key dominates a join side (statedoc.py).  Until now
every verdict was advisory.  :class:`JoinAdaptationPolicy` closes the
loop for the join: it consumes the operator's own sketch stream, applies
the SAME rule the verdict documents (top-1 share ≥ ``SKEW_SHARE_MIN``
AND share × live keys ≥ ``SKEW_FACTOR_MIN``), and issues the plan
adaptation — migrate the named key's rows into a dense hot
sub-partition (``_SideState.adapt``), fold it back when its share
decays (``fold``) — with hysteresis so a key oscillating around the
threshold doesn't thrash the layout.

Placement contract: the policy object is owned by the operator and
``tick`` runs ON THE JOIN'S OWN THREAD between batches (the executor
never calls it cross-thread) — layout migration must not race the
probe.  The doctor's role is the rule and the telemetry: every
adaptation increments ``dnz_join_adaptations_total`` (labeled
action=adapt|fold, side=left|right), lands as a Perfetto instant event
on the span stream, and is surfaced in ``state_info()["adaptations"]``
→ ``GET /queries/<id>/state``.

Two-tier rule with hysteresis (docs/joins.md):

- **trigger**: a side enters mitigation when its top-1 sketched key
  crosses the verdict thresholds (share ≥ ``adapt_share`` AND share ×
  live keys ≥ ``adapt_factor``) — or is already mitigated (has live
  hot blocks to manage);
- **adapt**: while triggered, EVERY tracked key with share ≥
  ``hot_share_min`` and share × live keys ≥ ``adapt_factor``
  sub-partitions, up to ``max_hot_keys`` concurrent blocks per side.
  A zipf-shaped feed's probe is serialized by the whole heavy-hitter
  set, not just the single verdict-crossing celebrity — adapting only
  the top key would leave the #2..#k chains as the next bottleneck;
- **fold** when a hot key's share has stayed below ``fold_share``
  (default half ``hot_share_min``) for ``hold_ticks`` CONSECUTIVE
  ticks.  Space-Saving counts are monotone, so a retired celebrity's
  share decays as total grows — folding is deliberately slower than
  adapting;
- decisions wait for ``min_rows`` sketched rows (a cold sketch names
  no hot keys), and a join re-intern resets the sketches — ``min_rows``
  then holds the policy off until they re-warm, so a reset never
  triggers a fold burst on stale zeros.
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from denormalized_tpu.obs.doctor.statedoc import (
    SKEW_FACTOR_MIN,
    SKEW_SHARE_MIN,
)

#: policy defaults (ctor-overridable; the TRIGGER thresholds are shared
#: with the skewed-join-side verdict so the loop acts exactly when the
#: doctor would have reported)
ADAPT_MIN_ROWS = 4096
HOT_SHARE_MIN = 0.002
FOLD_SHARE_RATIO = 0.5
FOLD_HOLD_TICKS = 3
MAX_HOT_KEYS = 32


class JoinAdaptationPolicy:
    """Closed-loop hot-key sub-partitioning for one StreamingJoinExec."""

    def __init__(
        self,
        *,
        adapt_share: float = SKEW_SHARE_MIN,
        adapt_factor: float = SKEW_FACTOR_MIN,
        hot_share_min: float = HOT_SHARE_MIN,
        fold_share: float | None = None,
        hold_ticks: int = FOLD_HOLD_TICKS,
        max_hot_keys: int = MAX_HOT_KEYS,
        min_rows: int = ADAPT_MIN_ROWS,
        interval_s: float = 1.0,
    ) -> None:
        self.adapt_share = float(adapt_share)
        self.adapt_factor = float(adapt_factor)
        self.hot_share_min = float(hot_share_min)
        self.fold_share = (
            self.hot_share_min * FOLD_SHARE_RATIO
            if fold_share is None else float(fold_share)
        )
        self.hold_ticks = int(hold_ticks)
        self.max_hot_keys = int(max_hot_keys)
        self.min_rows = int(min_rows)
        self.interval_s = float(interval_s)
        self._last_tick = 0.0
        # (side_id, gid) -> consecutive below-fold-threshold ticks
        self._cold_streak: dict[tuple[int, int], int] = {}
        self.events: deque = deque(maxlen=256)
        self.adaptations_total = 0

    # -- operator-thread entry points ------------------------------------
    def maybe_tick(self, op, sides) -> None:
        """Rate-limited tick — one monotonic-clock check per batch."""
        now = time.monotonic()
        if now - self._last_tick < self.interval_s:
            return
        self._last_tick = now
        self.tick(op, sides)

    def tick(self, op, sides) -> None:
        """One policy evaluation over both sides' sketches."""
        for side_id, side in enumerate(sides):
            watch = op._sw if side_id == 0 else op._sw_right
            if not watch:
                continue
            sk = watch.sketch
            total = int(sk.total)
            if total < self.min_rows:
                continue
            live = int(np.count_nonzero(side.head >= 0)) + int(
                side.hot.nslots
            )
            gids, counts, _errs = sk.top(self.max_hot_keys)
            shares = {
                int(g): int(c) / total for g, c in zip(gids, counts)
            }
            # trigger: the verdict condition on the side's top key — or
            # the side is already mitigated and keeps managing its set
            top_share = max(shares.values(), default=0.0)
            triggered = side.hot.nslots > 0 or (
                top_share >= self.adapt_share
                and top_share * max(live, 1) >= self.adapt_factor
            )
            if triggered:
                for g, share in shares.items():
                    if (
                        share >= self.hot_share_min
                        and share * max(live, 1) >= self.adapt_factor
                        and side.hot.nslots < self.max_hot_keys
                        and not side.hot.contains(g)
                    ):
                        if side.adapt(g):
                            self._record(op, side_id, "adapt", g, share)
            for g in [int(x) for x in side.hot.gids()]:
                share = shares.get(g, 0.0)
                key = (side_id, g)
                if share < self.fold_share:
                    streak = self._cold_streak.get(key, 0) + 1
                    if streak >= self.hold_ticks:
                        side.fold(g)
                        self._cold_streak.pop(key, None)
                        self._record(op, side_id, "fold", g, share)
                    else:
                        self._cold_streak[key] = streak
                else:
                    self._cold_streak.pop(key, None)
        # drop streak entries whose key is no longer hot anywhere (a
        # re-intern renumbered gids, or a fold removed the block)
        live_hot = {
            (sid, int(g))
            for sid, s in enumerate(sides)
            for g in s.hot.gids()
        }
        for k in [k for k in self._cold_streak if k not in live_hot]:
            del self._cold_streak[k]

    # -- telemetry -------------------------------------------------------
    def _record(self, op, side_id: int, action: str, gid: int,
                share: float) -> None:
        from denormalized_tpu import obs
        from denormalized_tpu.ops.interner import display_keys

        side = "left" if side_id == 0 else "right"
        try:
            name = display_keys(op._interner, np.asarray([gid]))[0]
        except Exception:  # dnzlint: allow(broad-except) a racing re-intern may have retired the gid between decision and display resolution — degrade to the numeric label, never kill the join thread
            name = None
        ev = {
            "t": time.time(),
            "action": action,
            "side": side,
            "gid": int(gid),
            "key": str(name) if name is not None else f"gid:{int(gid)}",
            "share": round(float(share), 6),
        }
        self.events.append(ev)
        self.adaptations_total += 1
        # handles pre-bound by the operator at construction (the lint's
        # binder scan covers engine modules, and the event path should
        # allocate nothing)
        op._obs_adapt[(action, side)].add(1)
        rec = obs.spans.recorder()
        if rec is not None:
            rec.instant(f"join.{action}", dict(ev))
