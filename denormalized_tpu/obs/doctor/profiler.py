"""On-demand sampling profiler: folded stacks for flamegraphs.

A daemon thread samples ``sys._current_frames()`` at ``hz`` (default
~100), folds each thread's stack into the classic semicolon-joined
``frame;frame;frame`` form (outermost first, prefixed with the thread
name), and counts occurrences — the exact input ``flamegraph.pl`` and
speedscope's "folded" importer consume.

Opt-in and per-query: started/stopped through the doctor HTTP surface
(``POST /queries/<id>/profile/start|stop``) or ``QueryHandle``; the
sampler is process-wide (``_current_frames`` sees every thread) but its
lifetime is tied to the query that asked.  Overhead is the GIL pause of
one frame walk per tick — measured by ``bench.py run_obs_overhead``
(``obs_profiler_ratio``) and documented in docs/observability.md; the
default-off state costs literally nothing.
"""

from __future__ import annotations

import sys
import threading
import time


class SamplingProfiler:
    def __init__(self, hz: float = 100.0, max_stack_depth: int = 64):
        if hz <= 0:
            raise ValueError(f"profiler hz must be > 0, got {hz}")
        self.interval_s = 1.0 / float(hz)
        self.max_stack_depth = int(max_stack_depth)
        self.samples_taken = 0
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-doctor-profiler"
        )
        self._thread.start()
        return self

    def stop(self) -> int:
        """Stop sampling; returns the number of samples taken."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            # the join can time out with the sampler mid-flush; the
            # counter is only coherent with the sample buffer under its
            # lock
            return self.samples_taken

    def _run(self) -> None:
        own = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            self._sample_once(own)

    def _sample_once(self, own_tid: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        folded: list[str] = []
        for tid, frame in frames.items():
            if tid == own_tid:
                continue
            stack: list[str] = []
            f = frame
            while f is not None and len(stack) < self.max_stack_depth:
                code = f.f_code
                stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{code.co_name}")
                f = f.f_back
            stack.reverse()
            tname = names.get(tid, f"tid-{tid}")
            folded.append(";".join([tname] + stack))
        with self._lock:
            self.samples_taken += 1
            for key in folded:
                self._counts[key] = self._counts.get(key, 0) + 1

    def folded(self) -> str:
        """The folded-stack text: one ``stack count`` line per distinct
        stack, heaviest first."""
        with self._lock:
            items = sorted(
                self._counts.items(), key=lambda kv: kv[1], reverse=True
            )
        return "\n".join(f"{stack} {count}" for stack, count in items)
