"""denormalized_tpu.obs.doctor — live query introspection.

The operator-facing half of the PR-6 observability stack: where the
registry answers "what are the numbers", the doctor answers the two
questions an on-call human actually asks —

- **"which stage is the bottleneck right now?"** — every executing
  query registers its physical plan (node-id keyed, the same ids the
  checkpointer uses); per-operator busy time plus upstream queue-wait
  roll into ONE ranked suspect list under a documented attribution rule
  (:mod:`~denormalized_tpu.obs.doctor.attribution`), rendered live at
  ``GET /queries/<id>/plan`` and by ``df.explain_analyze()``;
- **"why was this window late?"** — a configurable sample of rows is
  tagged at ingest with (source, partition, offset, event time) and
  followed through operator handoffs into window emission
  (:mod:`~denormalized_tpu.obs.doctor.lineage`), queryable at
  ``GET /queries/<id>/lineage`` and drawn as Perfetto flow events on
  the PR-6 span stream.

Plus an opt-in ~100 Hz sampling profiler exporting folded stacks for
flamegraphs (:mod:`~denormalized_tpu.obs.doctor.profiler`), started and
stopped per query over HTTP.  See docs/observability.md §"Operating the
doctor".
"""

from __future__ import annotations

from denormalized_tpu.obs.doctor.attribution import (  # noqa: F401
    ATTRIBUTION_RULE,
    rank,
)
from denormalized_tpu.obs.doctor.registry import (  # noqa: F401
    QueryHandle,
    get_query,
    queries,
    register_query,
    register_shared,
    running_count,
)

__all__ = [
    "ATTRIBUTION_RULE", "QueryHandle", "get_query", "queries",
    "rank", "register_query", "register_shared", "running_count",
]
