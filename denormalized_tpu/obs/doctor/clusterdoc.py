"""Cluster doctor pass — per-worker recovery state + health verdicts.

The coordinator (cluster/coordinator.py) maintains
``meta/cluster_state.json`` as its supervision state machine moves:
per-worker incarnation number (``gen``), last acked epoch, and whether
the worker is up / mid-rejoin / at EOS, plus the cluster commit
frontier and every aborted epoch.  This module turns that snapshot into
the same contract the state observatory ships (statedoc.py): RANKED
verdicts (severity desc) with the rule text included verbatim in every
payload, so a dashboard never has to guess what a verdict means.

Stdlib-only on the read path — soak parents and external tooling load
it against a workdir without importing the engine."""

from __future__ import annotations

import json
import os
import time

#: verdict rules, shipped verbatim in every cluster payload
CLUSTER_VERDICT_RULES = (
    "recovering-worker: a worker is mid-rejoin (respawned, not yet "
    "ready) — barriers are held and its exchange edges are buffering; "
    "escalates to the full-cluster fallback if the rejoin exceeds "
    "rejoin_timeout_s; "
    "degraded-edge: an exchange edge touches a recovering or silent "
    "worker (or a worker reports nonzero dnz_exchange_edges_down) — "
    "senders buffer-or-backpressure and redial with bounded backoff; "
    "restart-storm: one worker's incarnation number reached the "
    "per-worker budget (worker_max_restarts={cap}) without a healing "
    "interval — its next death escalates to a full-cluster restart; "
    "stale-ack: an up worker's last acked epoch lags the cluster "
    "commit frontier by >= {stale} epochs — it is alive but falling "
    "behind the barrier cadence."
)

STALE_ACK_EPOCHS = 3


def rules_text(worker_max_restarts: int = 3) -> str:
    return CLUSTER_VERDICT_RULES.format(
        cap=worker_max_restarts, stale=STALE_ACK_EPOCHS
    )


def verdicts(state: dict, edges_down: dict | None = None) -> list[dict]:
    """Ranked health verdicts over one coordinator state snapshot.

    ``edges_down`` optionally maps worker-id strings to that worker's
    current ``dnz_exchange_edges_down`` gauge reading (from the merged
    obs JSONL) — degraded edges are otherwise inferred from recovery
    state alone."""
    out: list[dict] = []
    workers = state.get("workers", {})
    n = int(state.get("n_workers") or len(workers))
    committed = int(state.get("committed_epoch") or 0)
    cap = int(state.get("worker_max_restarts") or 3)
    for wid, w in sorted(workers.items(), key=lambda kv: kv[0]):
        gen = int(w.get("gen") or 0)
        st = w.get("state")
        if st == "recovering":
            out.append({
                "kind": "recovering-worker",
                "worker": wid,
                "severity": 0.8,
                "gen": gen,
                "detail": (
                    f"worker {wid} is mid-rejoin (incarnation {gen}); "
                    f"peers keep streaming but barriers are held and "
                    f"{2 * max(0, n - 1)} exchange edges are degraded "
                    "until it reports ready"
                ),
            })
            out.append({
                "kind": "degraded-edge",
                "worker": wid,
                "severity": 0.6,
                "edges": 2 * max(0, n - 1),
                "detail": (
                    f"every edge into or out of worker {wid} is "
                    "buffering-or-down while it rejoins — senders hold "
                    "frames since the last cluster commit and redial "
                    "with bounded backoff"
                ),
            })
        if gen >= cap > 0:
            out.append({
                "kind": "restart-storm",
                "worker": wid,
                "severity": 1.0,
                "gen": gen,
                "detail": (
                    f"worker {wid} burned its whole per-worker restart "
                    f"budget (incarnation {gen} of cap {cap}) without "
                    "healing — the next death falls back to a "
                    "full-cluster restart"
                ),
            })
        last_ack = w.get("last_ack_epoch")
        if (
            st == "up"
            and last_ack is not None
            and committed - int(last_ack) >= STALE_ACK_EPOCHS
        ):
            out.append({
                "kind": "stale-ack",
                "worker": wid,
                "severity": round(
                    min(1.0, (committed - int(last_ack)) / 10.0), 4
                ),
                "last_ack_epoch": int(last_ack),
                "committed_epoch": committed,
                "detail": (
                    f"worker {wid} last acked epoch {last_ack} while "
                    f"the cluster frontier is {committed} — alive but "
                    "behind the barrier cadence"
                ),
            })
    for wid, down in sorted((edges_down or {}).items()):
        if int(down) > 0:
            out.append({
                "kind": "degraded-edge",
                "worker": str(wid),
                "severity": 0.6,
                "edges": int(down),
                "detail": (
                    f"worker {wid} reports {int(down)} inbound "
                    "exchange edge(s) down "
                    "(dnz_exchange_edges_down) — a peer is dead, "
                    "mid-rejoin, or its last frame tore"
                ),
            })
    out.sort(key=lambda v: -v["severity"])
    return out


def cluster_snapshot(
    workdir: str, edges_down: dict | None = None
) -> dict:
    """The full cluster-doctor payload for one coordinator workdir."""
    path = os.path.join(workdir, "meta", "cluster_state.json")
    try:
        with open(path) as f:
            state = json.load(f)
    except (FileNotFoundError, ValueError):
        state = {}
    cap = int(state.get("worker_max_restarts") or 3)
    return {
        "t": time.time(),
        "state": state,
        "verdicts": verdicts(state, edges_down),
        "rules": rules_text(cap),
    }
