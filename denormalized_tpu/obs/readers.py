"""Read-side helpers for the JSONL telemetry stream — dependency-free.

This module imports NOTHING from the engine (stdlib only) so consumers
that must stay jax-free — the soak PARENT, external report tooling —
can load it standalone by file path::

    spec = importlib.util.spec_from_file_location("obs_readers", path)

In-process consumers import the same names via
:mod:`denormalized_tpu.obs.jsonl`, which re-exports them; the histogram
quantile estimator here is also the one the live registry uses
(:mod:`~denormalized_tpu.obs.registry` imports it), so writer and
reader can never disagree about interpolation.
"""

from __future__ import annotations

import json


def quantile_from_buckets(
    bounds, counts, total, q, *, vmin=None, vmax=None
) -> float | None:
    """Interpolated q-quantile (0..1) from exponential bucket counts,
    clamped by the exact observed min/max when known; None when empty."""
    if not total:
        return None
    rank = q * total
    acc = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        lo = bounds[i - 1] if i > 0 else (
            vmin if vmin is not None else 0.0
        )
        hi = bounds[i] if i < len(bounds) else (
            vmax if vmax is not None else bounds[-1]
        )
        # tighten the interpolation edges by the exact observed range:
        # when all mass lands in one bucket (e.g. a replay offset pushing
        # everything past the top bound) this degrades gracefully to a
        # linear min→max estimate instead of saturating at a bucket edge
        if vmin is not None and vmin > lo:
            lo = min(vmin, hi)
        if vmax is not None and vmax < hi:
            hi = max(vmax, lo)
        if acc + c >= rank:
            frac = (rank - acc) / c
            est = lo + (hi - lo) * max(0.0, min(1.0, frac))
            if vmax is not None:
                est = min(est, vmax)
            if vmin is not None:
                est = max(est, vmin)
            return est
        acc += c
    return vmax


def read_stream(path) -> list[dict]:
    """All obs snapshots of one JSONL file, oldest first; torn tail
    lines (SIGKILL mid-write) are skipped."""
    out = []
    try:
        f = open(path)
    except FileNotFoundError:
        return out
    with f:
        for line in f:
            try:
                o = json.loads(line)
            except json.JSONDecodeError:
                continue
            if o.get("event") == "obs":
                out.append(o)
    return out


def last_stats(snapshots: list[dict], series: str):
    """The final value/stats of one series across a snapshot stream."""
    for snap in reversed(snapshots):
        v = snap.get("metrics", {}).get(series)
        if v is not None:
            return v
    return None


def merge_histogram(stats_list: list[dict]) -> dict | None:
    """Merge several processes' final histogram stats (same bucket
    layout) into one: counts/sums add, min/max combine, percentiles
    re-derived over the merged buckets."""
    stats_list = [s for s in stats_list if s and s.get("count")]
    if not stats_list:
        return None
    bounds = stats_list[0]["bounds"]
    counts = [0] * (len(bounds) + 1)
    total, total_sum = 0, 0.0
    vmin, vmax = None, None
    for s in stats_list:
        if s["bounds"] != bounds:
            continue  # layout changed between runs: skip, never mis-merge
        for i, c in enumerate(s["bucket_counts"]):
            counts[i] += c
        total += s["count"]
        total_sum += s["sum"]
        if s["min"] is not None and (vmin is None or s["min"] < vmin):
            vmin = s["min"]
        if s["max"] is not None and (vmax is None or s["max"] > vmax):
            vmax = s["max"]
    if not total:
        return None
    q = lambda p: quantile_from_buckets(  # noqa: E731
        bounds, counts, total, p, vmin=vmin, vmax=vmax
    )
    return {
        "count": total,
        "sum": total_sum,
        "min": vmin,
        "max": vmax,
        "p50": q(0.50),
        "p95": q(0.95),
        "p99": q(0.99),
    }


def linear_forecast(points, budget=None) -> dict | None:
    """Least-squares growth fit over ``[(unix_t, value), ...]`` points —
    the state observatory's time-to-budget projection (stdlib-only so
    the jax-free soak parent can run the same fit over a JSONL snapshot
    history that the live doctor runs over its in-memory ring).

    Returns ``None`` below two distinct-time points; otherwise a dict of
    ``slope_bytes_per_s``, ``current_bytes`` (last observed),
    ``window_s`` (ring span), ``r2`` (fit quality, 0..1), ``samples``,
    and — when ``budget`` is given — ``budget_bytes`` plus
    ``time_to_budget_s``: 0 when already at/over budget, a finite
    projection when growing, ``None`` when flat or shrinking (never
    reaches it on trend)."""
    pts = [(float(t), float(v)) for t, v in points]
    n = len(pts)
    if n < 2 or pts[-1][0] == pts[0][0]:
        return None
    t0 = pts[0][0]
    xs = [t - t0 for t, _v in pts]
    ys = [v for _t, v in pts]
    sx = sum(xs)
    sy = sum(ys)
    sxx = sum(x * x for x in xs)
    sxy = sum(x * y for x, y in zip(xs, ys))
    denom = n * sxx - sx * sx
    if denom == 0:
        return None
    slope = (n * sxy - sx * sy) / denom
    intercept = (sy - slope * sx) / n
    mean = sy / n
    ss_tot = sum((y - mean) ** 2 for y in ys)
    ss_res = sum((y - (intercept + slope * x)) ** 2 for x, y in zip(xs, ys))
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    out = {
        "slope_bytes_per_s": round(slope, 3),
        "current_bytes": ys[-1],
        "window_s": round(xs[-1], 3),
        "r2": round(r2, 4),
        "samples": n,
    }
    if budget is not None:
        out["budget_bytes"] = budget
        if ys[-1] >= budget:
            out["time_to_budget_s"] = 0.0
        elif slope > 0:
            out["time_to_budget_s"] = round((budget - ys[-1]) / slope, 1)
        else:
            out["time_to_budget_s"] = None
    return out


def gauge_series(snapshots: list[dict], series: str) -> list[tuple]:
    """``[(t, value), ...]`` of one scalar gauge series across a JSONL
    snapshot stream — the offline feed for :func:`linear_forecast`."""
    out = []
    for snap in snapshots:
        v = snap.get("metrics", {}).get(series)
        t = snap.get("t")
        if t is not None and isinstance(v, (int, float)):
            out.append((t, v))
    return out


def counter_timeline(snapshots: list[dict], prefix: str) -> list[dict]:
    """Per-interval increments of every counter series starting with
    ``prefix``, as ``[{"t": <s>, "series": ..., "delta": n}, ...]`` —
    how the soak report reconstructs the fault-event timeline from the
    cumulative ``dnz_fault_injections_total{site=...}`` counters.

    Call this per PROCESS stream: counters restart at zero with each
    process, so a concatenated multi-segment stream must be split by
    segment first (tools/soak.py does).  A decrease is still treated as
    a reset (delta = new value) rather than dropped, so an unsplit
    stream degrades to undercounting only when a restarted counter
    overtakes its predecessor between snapshots."""
    last: dict[str, float] = {}
    out: list[dict] = []
    for snap in snapshots:
        t = snap.get("t")
        for series, v in snap.get("metrics", {}).items():
            if not series.startswith(prefix) or isinstance(v, dict):
                continue
            prev = last.get(series, 0)
            delta = v if v < prev else v - prev
            if delta > 0:
                out.append({"t": t, "series": series, "delta": delta})
            last[series] = v
    return out


def merge_final_snapshots(paths) -> dict:
    """Merge N processes' JSONL telemetry streams into ONE registry
    view: each file's FINAL value per series, combined across files —
    counters and scalar gauges sum, histograms merge bucket-wise with
    percentiles re-derived over the union (:func:`merge_histogram`).

    This is the user-facing merger for the multi-process-mergeable
    format the registry writes (one cluster worker per file)::

        python -m denormalized_tpu.obs.readers merge out/obs/w*.jsonl

    Returns ``{"files": n, "series": {name: value-or-stats}}``.  A
    series that is a histogram in one file and a scalar in another is
    skipped (layout drift between engine versions — never mis-merged).
    """
    finals: list[dict] = []
    for p in paths:
        snaps = read_stream(p)
        if not snaps:
            continue
        series: dict = {}
        for snap in snaps:  # last value per series wins (cumulative)
            for name, v in snap.get("metrics", {}).items():
                series[name] = v
        finals.append(series)
    names: dict[str, None] = {}
    for s in finals:
        for name in s:
            names.setdefault(name)
    merged: dict = {}
    for name in names:
        vals = [s[name] for s in finals if name in s]
        hists = [v for v in vals if isinstance(v, dict)]
        scalars = [v for v in vals if isinstance(v, (int, float))]
        if hists and scalars:
            continue  # mixed kinds across files: refuse to guess
        if hists:
            m = merge_histogram(hists)
            if m is not None:
                merged[name] = m
        elif scalars:
            total = sum(scalars)
            merged[name] = round(total, 6) if isinstance(total, float) \
                else total
    return {"files": len(finals), "series": merged}


def _merge_cli(argv) -> int:
    import sys

    if not argv or argv[0] != "merge" or len(argv) < 2:
        sys.stderr.write(
            "usage: python -m denormalized_tpu.obs.readers "
            "merge <snap.jsonl> [<snap.jsonl> ...]\n"
        )
        return 2
    out = merge_final_snapshots(argv[1:])
    json.dump(out, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    import sys

    sys.exit(_merge_cli(sys.argv[1:]))
