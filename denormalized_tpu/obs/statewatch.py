"""State & skew observatory — the measurement layer under every
stateful operator.

The third operator question after "which stage is slow" (PR 7) and
"what are my latencies" (PR 6) is "how big is my state, which keys are
hot, and when do I OOM".  This module answers it with three parts:

1. **Exact state accounting** — every stateful operator
   (window/session/join/udaf, the interners' free lists, the LSM
   backend) implements ``state_info()``: live bytes, live keys,
   slot-table capacity vs occupancy, and oldest-retained-event-time.
   Accounting is PULL-ONLY (computed when a snapshot/export asks), so
   it costs the hot path nothing; the registry view binds in
   ``ExecOperator.bind_state_obs`` via weakref'd gauge_fns — the same
   no-graph-pinning rule ``dnz_decode_fallback_rows`` established.

2. **Streaming key-distribution sketches** — a vectorized Space-Saving
   heavy-hitter sketch (:class:`SpaceSaving`) and a HyperLogLog
   cardinality estimator (:class:`Hll`), both fed DENSE GIDS in batch
   right after intern time.  Updates are pure numpy (bucketed
   ``np.unique`` + scatter adds; pinned loop-free in ``hotpaths.toml``)
   so the 49M rows/s hot path pays microseconds per batch, not per-row
   Python.  Accuracy bounds (documented in docs/observability.md):

   - Space-Saving with K slots overestimates a key's count by at most
     its reported ``err`` (the count of the slot it evicted); any key
     with true share > 1/K is guaranteed tracked.  The batch variant
     admits the ``min(K, new-keys)`` largest newcomers per batch and
     folds the remainder into ``total`` only — same overestimate
     guarantee, slightly looser tail recall than item-at-a-time.
   - HLL with ``2**p`` registers has standard error
     ``1.04 / sqrt(2**p)`` (p=12 → ~1.6%).
   - Sketches are keyed by dense gid: for non-recycling interners
     (window/join/udaf) a gid IS one key for the interner's lifetime;
     a join/udaf re-intern resets the sketch (it re-warms from live
     traffic).  The session interner RECYCLES closed keys' gids, so a
     long-closed key's residual sketch mass can alias onto the key
     that inherits its id — bounded by ``err`` and washed out by the
     next refresh cycle; hot keys, by definition, keep their gid.
   - Sketches do NOT ride checkpoints: after a restore they re-warm
     from live traffic (a few seconds of feed at soak rates).  Exact
     accounting is recomputed from restored state and therefore
     matches the pre-kill values immediately (pinned by
     tests/test_statewatch.py).

3. **Growth forecasting** — each watch keeps a bounded ring of
   (wall time, state bytes) samples, appended whenever an exporter or
   the doctor's ``/state`` endpoint reads the state-bytes gauge.  A
   least-squares fit over the ring projects time-to-budget against
   ``EngineConfig(state_budget_bytes=...)``; the fit itself lives in
   :func:`obs.readers.linear_forecast` (stdlib-only, so the jax-free
   soak parent can run the same fit over a JSONL snapshot history).

Health verdicts (``skewed-join-side``, ``unbounded-session-growth``,
``retention-leak``) are ranked by the doctor from these signals — see
:mod:`denormalized_tpu.obs.doctor.statedoc`.
"""

from __future__ import annotations

import math
import time
from collections import deque

import numpy as np

from denormalized_tpu.obs.readers import linear_forecast
from denormalized_tpu.ops.sketches import (  # noqa: F401 - re-exports
    Hll,
    SpaceSaving,
    _aggregate_gids,
    _mix64,
)

__all__ = [
    "SpaceSaving", "Hll", "StateWatch", "NULL_WATCH", "arrays_nbytes",
    "acc_nbytes", "linear_forecast",
]


def arrays_nbytes(*arrays) -> int:
    """Total nbytes of the given numpy arrays (None entries skipped)."""
    return sum(int(a.nbytes) for a in arrays if a is not None)


#: documented per-object estimates for state that lives in Python
#: objects (accounting for them exactly would mean walking user object
#: graphs on every export).  Being CONSTANTS makes the accounting
#: restore-invariant: bytes derive only from live counts, so the
#: pre-kill and post-restore numbers are identical by construction.
KEY_EST_BYTES = 64  # one interned key: dict entry + row tuple + id
ACC_EST_BYTES = 512  # one accumulator object (UDAF/builtin, amortized)
OBJ_CELL_EST_BYTES = 56  # one object-dtype cell (string ref + header)


def acc_nbytes(acc) -> int:
    """Accounting bytes of one accumulator: its own ``state_nbytes()``
    when it reports one (the unbounded exact accumulators — median,
    count_distinct, percentile, array_agg — derive it from their
    element counts, so it is restore-invariant AND actually grows),
    else the constant :data:`ACC_EST_BYTES` estimate.  Without this the
    doctor's unbounded-growth / budget-pressure verdicts were blind to
    exactly the accumulators most likely to OOM."""
    fn = getattr(acc, "state_nbytes", None)
    if fn is None:
        return ACC_EST_BYTES
    return int(fn())


def side_live_keys(info: dict, side) -> int:
    """Live keys of ONE watch view: the side's own count for a join
    ('left'/'right'), the node total otherwise.  Every skew-factor
    consumer must use this — a per-side sketch's top-1 share multiplied
    by the COMBINED both-sides key count would read ~2 on a perfectly
    uniform join and flag it skewed."""
    if side is not None:
        return int(
            info.get("sides", {}).get(side, {}).get("live_keys") or 0
        )
    return int(info.get("live_keys") or 0)


def rb_nbytes(batch) -> int:
    """Accounting bytes of one RecordBatch: exact nbytes for numeric
    columns and masks, the documented per-cell estimate for object
    (string) columns."""
    import numpy as _np

    from denormalized_tpu.common.columns import Column as _ColData

    total = 0
    for name in batch.schema.names:
        col = batch.column(name)
        if isinstance(col, _ColData):
            # columnar string/nested columns have EXACT buffer bytes —
            # no estimate needed (and no accidental materialization:
            # np.asarray here would build every Python row just to
            # count them)
            total += int(col.nbytes)
            if getattr(col, "_obj", None) is not None:
                # a legacy touch materialized (and cached) Python rows:
                # that parallel object array is real resident memory —
                # charge it like the pre-columnar estimate did
                total += len(col) * OBJ_CELL_EST_BYTES
        else:
            col = _np.asarray(col)
            if col.dtype == object:
                total += len(col) * OBJ_CELL_EST_BYTES
            else:
                total += int(col.nbytes)
        m = batch.mask(name)
        if m is not None:
            total += int(_np.asarray(m).nbytes)
    return total


#: rows per sketch update: batches beyond this update through a
#: CONTIGUOUS block sample whose start rotates across updates, with
#: counts rescaled to row units.  16k samples put the sampling error on
#: a heavy hitter's share around +-1% — far below the Space-Saving slot
#: guarantee — while capping the per-batch cost at ~0.1ms regardless of
#: how large source coalescing makes a batch.
SKETCH_ROW_CAP = 16_384


# -- sketches ------------------------------------------------------------
# The SpaceSaving / Hll / _mix64 / _aggregate_gids kernels moved to
# ops/sketches.py (ISSUE 18) — ONE implementation now serves the
# intern-time observatory sketches here, the slice store's first-class
# approx aggregates, and the UDAF fallback HLL shim.  They are
# re-imported above so every existing consumer (join_exec's decayed
# sketch, the doctor, tests) keeps its import path; decay semantics
# stay a SpaceSaving constructor option, used only by the join.

#: decay horizon for the JOIN's windowed sketches: one decay step (×½)
#: every quarter-million rows per side ⇒ a retired celebrity's share
#: halves every ~256k rows regardless of run length, so the adaptation
#: policy's fold condition (share below fold_share for hold_ticks) is
#: reachable in bounded rows.  Other operators keep monotone sketches.
JOIN_SKETCH_DECAY_ROWS = 1 << 18


# -- the per-operator watch ----------------------------------------------


#: minimum seconds between two growth-ring samples (a Prometheus scrape
#: and a JSONL snapshot racing each other must not double-enter a point)
_SAMPLE_MIN_INTERVAL_S = 0.2

#: growth-ring depth: at the 1 s JSONL cadence this is ~8.5 minutes of
#: history — enough for a stable fit, bounded regardless of run length
_SAMPLE_RING = 512


class StateWatch:
    """One stateful operator's (or one join side's) sketch + growth set.

    Created unconditionally at operator construction; ``enabled``
    resolves from the bound registry's enabledness so the metrics-off
    path pays one attribute check per batch and nothing else (the exact
    accounting is pull-only and works either way)."""

    __slots__ = (
        "label", "enabled", "sketch", "hll", "update_s", "update_batches",
        "samples", "_last_sample_t", "_hot_bound", "_sample_phase",
    )

    def __init__(self, label: str, *, capacity: int = 64,
                 enabled: bool = True, decay_every: int = 0,
                 decay_factor: float = 0.5) -> None:
        self.label = label
        self.enabled = bool(enabled)
        self.sketch = SpaceSaving(
            capacity, decay_every=decay_every, decay_factor=decay_factor
        )
        self.hll = Hll()
        self.update_s = 0.0  # cumulative sketch-update cost (bench reports)
        self.update_batches = 0
        self.samples: deque = deque(maxlen=_SAMPLE_RING)
        self._last_sample_t = 0.0
        self._sample_phase = 0
        # hot-key gauge handles by key label (stale ones are zeroed, not
        # unbound — the registry has no eviction by design)
        self._hot_bound: dict = {}

    def __bool__(self) -> bool:
        return True

    # -- hot path --------------------------------------------------------
    def update(self, gids: np.ndarray) -> None:
        """Feed one batch's dense gids (call right after intern).  One
        shared per-gid aggregation feeds both sketches: the Space-Saving
        update works on (uniques, counts), and distinct-value sketches
        only care about the uniques, so the HLL hashes those — not the
        full batch.  Batches beyond SKETCH_ROW_CAP update through a
        CONTIGUOUS block sample whose start rotates across updates
        (counts scaled back to row units): contiguous keeps the memory
        traffic at one block regardless of batch size, rotation keeps
        the coverage uniform across the stream even when keys cluster
        within a batch."""
        n = len(gids)
        if not self.enabled or n == 0:
            return
        t0 = time.perf_counter()
        g = gids if isinstance(gids, np.ndarray) else np.asarray(gids)
        sampled = False
        if n > SKETCH_ROW_CAP:
            sampled = True
            # wrap the phase over the VALID start range [0, n - CAP], not
            # back to 0: constant-size batches would otherwise alternate
            # start 0 -> CAP -> 0 and never sample the tail rows past the
            # last full block (a partition appended last by coalescing
            # would be permanently invisible to the sketch)
            start = self._sample_phase % (n - SKETCH_ROW_CAP + 1)
            self._sample_phase = start + SKETCH_ROW_CAP
            g = g[start:start + SKETCH_ROW_CAP]
        u, c = _aggregate_gids(g)
        if sampled:
            # rescale by the TRUE sampling ratio (n / sample size), not
            # an integer ceiling: a 17k-row batch samples 16384 rows at
            # ratio ~1.04 — a ceil(17000/16384)=2 multiplier would
            # double every share and falsely trip skew verdicts
            c = np.rint(c * (n / len(g))).astype(np.int64)
        self.sketch.update_aggregated(u, c, n)
        self.hll.update(u)
        self.update_s += time.perf_counter() - t0
        self.update_batches += 1

    def reset_sketches(self) -> None:
        """A re-intern replaced the gid space: old gids no longer name
        the same keys, so the sketches restart (documented re-warm)."""
        self.sketch.reset()
        self.hll.reset()

    # -- growth ring -----------------------------------------------------
    def record_sample(self, bytes_now: float, t: float | None = None) -> None:
        """Append one (wall time, state bytes) growth point; rate-limited
        so concurrent exporters don't double-sample.  Called from the
        state-bytes gauge_fn (export-driven history) and from the
        doctor's /state snapshots."""
        now = time.time() if t is None else t
        if now - self._last_sample_t < _SAMPLE_MIN_INTERVAL_S:
            return
        self._last_sample_t = now
        self.samples.append((now, float(bytes_now)))

    def forecast(self, budget_bytes: int | None = None) -> dict | None:
        """Least-squares growth fit over the sample ring (None until two
        samples exist)."""
        return linear_forecast(list(self.samples), budget=budget_bytes)

    # -- distribution summaries -----------------------------------------
    def hot_keys(self, k: int = 8, resolve=None) -> list[dict]:
        """Top-k tracked keys: ``[{key, rows, err_rows, share}]``, share
        = tracked rows / total rows fed (the key's state-mass share for
        row-proportional state).  ``resolve(gids) -> list[str]`` maps
        dense gids to display keys; unresolvable gids (recycled/closed)
        render as ``gid:<n>``."""
        gids, counts, errs = self.sketch.top(k)
        total = max(self.sketch.total, 1)
        names = None
        if resolve is not None and len(gids):
            try:
                names = resolve(gids)
            except Exception:  # dnzlint: allow(broad-except) a hot gid may have been released/re-interned between sketch update and resolution — degrade to the numeric gid label, never take the state endpoint down
                names = None
        out = []
        for i in range(len(gids)):
            name = (
                str(names[i]) if names is not None and names[i] is not None
                else f"gid:{int(gids[i])}"
            )
            out.append({
                "key": name,
                "rows": int(counts[i]),
                "err_rows": int(errs[i]),
                "share": round(int(counts[i]) / total, 6),
            })
        return out

    def skew_factor(self, live_keys: int) -> float | None:
        """top-1 share x live keys: ~1 for a uniform distribution, >> 1
        when one key dominates (the PanJoin hot-key trigger signal)."""
        _gids, counts, _errs = self.sketch.top(1)
        if len(counts) == 0 or self.sketch.total == 0 or live_keys <= 0:
            return None
        return round(
            int(counts[0]) / self.sketch.total * live_keys, 3
        )

    def distinct_estimate(self) -> int:
        return int(round(self.hll.estimate()))

    def summary(self, live_keys: int = 0, resolve=None, k: int = 8) -> dict:
        """The sketch block of one node's /state payload."""
        return {
            "hot_keys": self.hot_keys(k, resolve=resolve),
            "skew_factor": self.skew_factor(live_keys),
            "distinct_gids_estimate": self.distinct_estimate(),
            "sketch_rows": self.sketch.total,
            "sketch_update_ms_total": round(self.update_s * 1e3, 3),
            "sketch_update_batches": self.update_batches,
            "enabled": self.enabled,
        }


class _NullWatch:
    """Falsy no-op watch (metrics-disabled path).  Exact accounting is
    unaffected (it never routes through the watch); sketches and the
    growth ring are simply off."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def update(self, gids) -> None:
        pass

    def reset_sketches(self) -> None:
        pass

    def record_sample(self, bytes_now, t=None) -> None:
        pass

    def forecast(self, budget_bytes=None):
        return None

    def hot_keys(self, k=8, resolve=None):
        return []

    def skew_factor(self, live_keys):
        return None

    def distinct_estimate(self) -> int:
        return 0

    def summary(self, live_keys=0, resolve=None, k=8) -> dict:
        return {
            "hot_keys": [], "skew_factor": None,
            "distinct_gids_estimate": 0, "sketch_rows": 0,
            "sketch_update_ms_total": 0.0, "sketch_update_batches": 0,
            "enabled": False,
        }

    update_s = 0.0
    update_batches = 0
    samples: deque = deque()


NULL_WATCH = _NullWatch()


def make_watch(label: str, *, capacity: int = 64, decay_every: int = 0,
               decay_factor: float = 0.5):
    """A live :class:`StateWatch` when the currently bound registry has
    metrics enabled, else the shared falsy null — the same
    resolve-at-construction rule every obs handle follows.
    ``decay_every``/``decay_factor`` make the heavy-hitter sketch
    windowed (see :class:`SpaceSaving`) — the join passes them so its
    adaptation policy sees recent shares."""
    from denormalized_tpu import obs

    if obs.enabled():
        return StateWatch(
            label, capacity=capacity,
            decay_every=decay_every, decay_factor=decay_factor,
        )
    return NULL_WATCH
