"""The instrument catalog — every metric the engine emits, declared once.

This is the observability analog of ``faults.SITES``: a single registry
of instrument names with help strings and bucket layouts, machine-checked
both ways by dnzlint (DNZ-M001) — an ``obs.counter("dnz_typo_total")``
call anywhere in the engine fails the lint gate (the name keys nothing
here), and a declared instrument nobody binds fails it too (a renamed
call site must not leave the catalog advertising a metric that never
reports).  ``docs/observability.md`` embeds the table generated from
this dict (``python -m tools.dnzlint --metric-catalog``), so the doc
cannot drift from the declarations.

Naming convention (lint-enforced):

- every name matches ``^dnz_[a-z][a-z0-9_]*$``;
- counters end in ``_total`` (Prometheus counter convention);
- histograms end in a unit suffix: ``_ms``, ``_s``, ``_bytes`` or
  ``_rows``;
- every entry carries a non-trivial help string.

Entries are ``name: (kind, help[, buckets])`` where ``kind`` is
``"counter"`` / ``"gauge"`` / ``"histogram"`` and ``buckets`` (histograms
only) is an exponential layout ``{"start": s, "factor": f, "count": n}``
producing bounds ``s, s*f, s*f^2, ...`` plus the implicit +Inf bucket.
"""

from __future__ import annotations

# exponential bucket layouts (see exp_bounds): latencies from 50µs to
# ~7min, sizes from 256B to ~4GB, row counts from 1 to ~1B — wide enough
# that a soak never saturates the top bucket and percentile estimates
# stay meaningful
MS_BUCKETS = {"start": 0.05, "factor": 2.0, "count": 23}
BYTES_BUCKETS = {"start": 256.0, "factor": 4.0, "count": 12}
ROWS_BUCKETS = {"start": 1.0, "factor": 4.0, "count": 15}

INSTRUMENTS: dict[str, tuple] = {
    # -- per-operator (physical/*) -------------------------------------
    "dnz_op_rows_in_total": (
        "counter",
        "rows entering a physical operator, labeled op=<operator>",
    ),
    "dnz_op_rows_out_total": (
        "counter",
        "rows leaving a physical operator (source/join/sink emission)",
    ),
    "dnz_op_batch_ms": (
        "histogram",
        "wall time one operator spent processing one input batch "
        "(eval + device dispatch + emission assembly; excludes time "
        "spent suspended in downstream operators)",
        MS_BUCKETS,
    ),
    "dnz_windows_emitted_total": (
        "counter",
        "windows/sessions emitted by a stateful operator",
    ),
    "dnz_late_rows_total": (
        "counter",
        "rows dropped late (behind the watermark) by a stateful operator",
    ),
    # -- watermark / end-to-end latency (stamped at window emit) --------
    "dnz_watermark_lag_ms": (
        "gauge",
        "wall clock minus the operator's event-time watermark at the "
        "last trigger — how far event time trails real time (includes "
        "the replay offset when replaying historical data)",
    ),
    "dnz_watermark_lag_hist_ms": (
        "histogram",
        "distribution of wall-minus-watermark samples taken at every "
        "trigger (the max over a run is the peak watermark lag)",
        MS_BUCKETS,
    ),
    "dnz_emit_event_lag_ms": (
        "histogram",
        "end-to-end event-time emission latency: wall clock minus "
        "window end, observed once per emitted window (for a replayed "
        "feed this includes the constant replay offset; consumers "
        "subtract their feed anchor — see tools/soak.py)",
        MS_BUCKETS,
    ),
    # -- ingest (runtime/prefetch.py, sources/kafka.py) -----------------
    "dnz_prefetch_queue_depth": (
        "gauge",
        "rowful batches enqueued but not yet consumed for one "
        "partition's prefetch buffer (backpressure: the bounded "
        "per-partition double buffer is full when depth == depth limit)",
    ),
    "dnz_prefetch_restarts_total": (
        "counter",
        "supervised prefetch-worker restarts (crash + rebuild + reseek)",
    ),
    "dnz_kafka_consumer_lag_rows": (
        "gauge",
        "records between this reader's cursor and the partition high "
        "watermark reported by the last fetch response (broker-side "
        "backlog; 0 = caught up)",
    ),
    "dnz_decode_fallback_rows": (
        "gauge",
        "rows decoded through the ~30x-slower Python fallback path "
        "instead of the native columnar parser (registry view of the "
        "SourceExec.metrics() counter)",
    ),
    # -- state (state/lsm.py, state/checkpoint.py) ----------------------
    "dnz_lsm_op_ms": (
        "histogram",
        "latency of one LSM state-backend operation, labeled "
        "op=put|get|flush",
        MS_BUCKETS,
    ),
    "dnz_checkpoint_commit_ms": (
        "histogram",
        "duration of a checkpoint commit (manifest + fsync + commit "
        "record + fsync + GC)",
        MS_BUCKETS,
    ),
    "dnz_checkpoint_snapshot_bytes": (
        "histogram",
        "size of one operator snapshot blob as persisted (framed)",
        BYTES_BUCKETS,
    ),
    "dnz_checkpoint_committed_epoch": (
        "gauge",
        "the last durably committed checkpoint epoch",
    ),
    "dnz_checkpoint_commit_retries_total": (
        "counter",
        "transient StateErrors absorbed by the bounded commit retry "
        "(registry view of CheckpointCoordinator.commit_retries)",
    ),
    "dnz_lsm_replay_truncated_total": (
        "counter",
        "torn segment tails dropped by LSM startup replay (registry "
        "view of LsmStore.replay_truncated; pure-Python engine only)",
    ),
    # -- pipeline doctor (obs/doctor, docs/observability.md) ------------
    "dnz_op_input_wait_ms": (
        "histogram",
        "time an operator spent suspended waiting for its upstream to "
        "yield the next stream item — the doctor's queue-wait signal "
        "(high wait + low busy = this stage is starved by upstream)",
        MS_BUCKETS,
    ),
    "dnz_prefetch_queue_dwell_ms": (
        "histogram",
        "time a rowful batch sat in the prefetch ready queue between "
        "worker enqueue and consumer dequeue (handoff dwell: sustained "
        "growth means the consumer thread is the bottleneck, not ingest)",
        MS_BUCKETS,
    ),
    # -- state observatory (obs/statewatch.py, docs/observability.md) ---
    "dnz_state_bytes": (
        "gauge",
        "live bytes of keyed state held by one stateful operator "
        "(restore-invariant accounting: exact numpy storage for live "
        "slots/rows plus documented per-object estimates for Python "
        "accumulators and interned keys), labeled node=<plan node id>",
    ),
    "dnz_state_live_keys": (
        "gauge",
        "keys/groups currently holding live state in one stateful "
        "operator, labeled node=<plan node id>",
    ),
    "dnz_state_slots": (
        "gauge",
        "slot-table shape of one stateful operator, labeled node= and "
        "kind=capacity|live — occupancy vs allocated capacity (a low "
        "ratio means the table grew for a churn spike and has not "
        "shrunk back)",
    ),
    "dnz_state_oldest_event_lag_ms": (
        "gauge",
        "operator watermark minus the oldest retained event time — how "
        "far back live state reaches; sustained growth beyond a few "
        "window/gap/retention units is the retention-leak signal",
    ),
    "dnz_state_hot_key_share": (
        "gauge",
        "estimated state-mass share of one Space-Saving-tracked hot "
        "key (labeled node=, key=, and side= for joins); only the "
        "current top-K are refreshed, keys that fall out read 0",
    ),
    "dnz_state_skew_factor": (
        "gauge",
        "top-1 key share x live keys for one stateful operator: ~1 on "
        "a uniform key distribution, >>1 when one key dominates (the "
        "adaptive-join sub-partitioning trigger signal)",
    ),
    "dnz_checkpoint_last_snapshot_bytes": (
        "gauge",
        "size of the most recent snapshot blob persisted under one "
        "state key (framed bytes), labeled key=<node-scoped state key> "
        "— restore-size regressions are attributable to one operator",
    ),
    # -- tiered state / spill (state/tiering.py) ------------------------
    "dnz_state_spilled_bytes": (
        "gauge",
        "bytes of one stateful operator's keyed state currently resident "
        "in the cold LSM tier instead of RAM (payload bytes as stored), "
        "labeled node=<plan node id>",
    ),
    "dnz_state_spilled_keys": (
        "gauge",
        "keys/groups (join: retained rows) whose state currently lives "
        "in the cold LSM tier, labeled node=<plan node id>",
    ),
    "dnz_spill_op_ms": (
        "histogram",
        "latency of one cold-tier block operation, labeled "
        "op=spill|reload (spill = serialize + LSM put of one evicted "
        "block; reload = LSM get on touch, excluding re-merge)",
        MS_BUCKETS,
    ),
    "dnz_spill_blocks_total": (
        "counter",
        "cold-tier blocks moved, labeled op=spill|reload — a reload "
        "rate tracking the spill rate is the spill-thrashing signal",
    ),
    "dnz_spill_backpressure_total": (
        "counter",
        "escalations to end-of-line prefetch backpressure because "
        "accounted state exceeded the hard ceiling with no evictable "
        "cold state left",
    ),
    # -- closed-loop skew adaptation (obs/doctor/actions.py) ------------
    "dnz_join_adaptations_total": (
        "counter",
        "hot-key sub-partition layout changes applied by the join's "
        "closed-loop policy, labeled action=adapt|fold and "
        "side=left|right — the first doctor verdict that acts instead "
        "of reporting (each change also lands as a Perfetto instant "
        "event)",
    ),
    # -- multi-query slice store (physical/slice_exec.py) ---------------
    "dnz_mq_emit_lag_ms": (
        "gauge",
        "per-subscriber end-to-end emission lag of a shared slice "
        "pipeline: wall clock minus window end at that query's last "
        "emitted window, labeled query=<subscriber label> — attributes "
        "shared-pipeline lag to the individual query (the aggregate "
        "dnz_emit_event_lag_ms histogram sums over subscribers)",
    ),
    "dnz_slice_rows_total": (
        "counter",
        "rows folded into shared slice partials by a SliceWindowExec — "
        "each row is aggregated ONCE here regardless of how many "
        "overlapping windows or subscriber queries later fold it",
    ),
    "dnz_slice_units": (
        "gauge",
        "live slice units (slide-unit partial rows) resident in one "
        "shared slice store — bounded by the longest subscriber window "
        "plus watermark lag over the gcd slice width",
    ),
    "dnz_slice_subscribers": (
        "gauge",
        "window specs (concurrent queries) folding their windows from "
        "one shared slice store — 1 on the single-query fast path",
    ),
    "dnz_slice_folds_total": (
        "counter",
        "window folds served from slice partials (one per closable "
        "window per subscriber, including folds that found no active "
        "groups and emitted nothing)",
    ),
    "dnz_slice_fold_ms": (
        "histogram",
        "latency of one window fold: combining L/gcd slice partials + "
        "finalize + emission assembly for one subscriber's window",
        MS_BUCKETS,
    ),
    "dnz_sketch_rows_total": (
        "counter",
        "rows fed through slice-store sketch kernels (HLL / Space-"
        "Saving / quantile compactor planes) by a SliceWindowExec — "
        "counted once per batch over all filter classes, so a row a "
        "residual class re-accumulates counts again (it ran the kernel "
        "again)",
    ),
    "dnz_sketch_state_bytes": (
        "gauge",
        "exact bytes held by sketch planes across a SliceWindowExec's "
        "live slices — constant in value cardinality by construction "
        "(the contrast to unbounded exact distinct/median accumulator "
        "growth the doctor's state verdicts flag)",
    ),
    "dnz_sketch_update_ms": (
        "histogram",
        "per-batch time inside sketch accumulate kernels (all planes, "
        "all filter classes) — the marginal ingest cost of approximate "
        "aggregates riding a shared slice pipeline",
        MS_BUCKETS,
    ),
    # -- query-dense serving: live registration + subsumption (ISSUE 16) -
    "dnz_mq_subscribers_live": (
        "gauge",
        "subscriber queries currently attached to one shared slice "
        "pipeline — moves on live attach/detach, unlike "
        "dnz_slice_subscribers it counts the instantaneous registry "
        "(after mid-stream joins and leaves), not the planning-time set",
    ),
    "dnz_mq_backfill_windows_total": (
        "counter",
        "windows served to a mid-stream joiner from the slice store's "
        "RETAINED partials at attach time — each one is a window the "
        "query got without replaying the stream, exact from the gcd "
        "slices already covering it",
    ),
    "dnz_mq_refilter_ms": (
        "histogram",
        "per-batch cost of the residual re-filter masks in a shared "
        "slice pipeline (predicate-subsumption sharing): evaluating "
        "each stronger member's own predicate over the batch — or over "
        "NEW interner keys only on the gid lane — before per-class "
        "accumulation; observed only when a residual class exists",
        MS_BUCKETS,
    ),
    # -- query-dense joins: shared StreamingJoinExec (ISSUE 17) ---------
    "dnz_mq_join_stage_ms": (
        "histogram",
        "per-batch time one SHARED join spent in each stage, labeled "
        "stage=build|probe|gather (build = intern+insert, probe = "
        "equi/band index probe, gather = pair materialization+filter) "
        "— observed only when the join feeds a shared slice pipeline "
        "(enable_shared_attribution); feeds the doctor's measured-cost "
        "attribution across subscriber queries",
        MS_BUCKETS,
    ),
    "dnz_mq_join_fanout_rows_total": (
        "counter",
        "joined rows fanned out from one shared StreamingJoinExec into "
        "its group's slice pipeline — rows every subscriber's residual "
        "class then re-filters, vs dnz_op_rows_out_total{op=join} which "
        "also counts unshared joins",
    ),
    # -- sink (sources/kafka.py KafkaSinkWriter) ------------------------
    "dnz_sink_retries_total": (
        "counter",
        "transient produce errors absorbed by the sink's bounded "
        "exp-backoff retry (registry view of KafkaSinkWriter."
        "sink_retries) — a rising rate means the output broker is "
        "flapping even though segments still succeed",
    ),
    # -- source salvage (sources/kafka.py _salvage_decode) --------------
    "dnz_source_salvaged_rows": (
        "gauge",
        "poison records skipped by per-record salvage decode (the fetch "
        "kept its co-fetched good rows; these were undecodable and "
        "dropped), labeled source= and partition= — invisible data loss "
        "otherwise",
    ),
    # -- fault injection (runtime/faults.py) ----------------------------
    "dnz_fault_injections_total": (
        "counter",
        "fault-plan rules fired, labeled site=<injection site> — the "
        "chaos event stream's counter view (timeline derivable from "
        "successive JSONL snapshots)",
    ),
    # -- cluster exchange (cluster/exchange.py) -------------------------
    "dnz_exchange_frames_total": (
        "counter",
        "exchange frames moved, labeled dir=send|recv and edge=src->dst "
        "(recv aggregates per receiving worker) — barrier and watermark "
        "frames included, loopback excluded",
    ),
    "dnz_exchange_bytes_total": (
        "counter",
        "framed exchange bytes moved (wire size incl. header+CRC on "
        "send, payload on recv), labeled like dnz_exchange_frames_total",
    ),
    "dnz_exchange_send_ms": (
        "histogram",
        "wall time one framed exchange send spent in sendall — rising "
        "percentiles mean the peer's edge queue (backpressure) or the "
        "socket buffer is the bottleneck, not this worker's ingest",
        MS_BUCKETS,
    ),
    "dnz_exchange_edge_depth": (
        "gauge",
        "decoded frames queued on one inbound exchange edge awaiting "
        "the keyed half (labeled edge=src->dst); pinned at the bound "
        "while an edge is barrier-blocked during alignment",
    ),
    "dnz_exchange_reconnects_total": (
        "counter",
        "successful redials of a down exchange edge (labeled "
        "edge=src->dst): each one is a tear or peer death the sender "
        "survived by buffering and resuming in place",
    ),
    "dnz_exchange_replayed_frames_total": (
        "counter",
        "buffered frames re-sent on a resumed exchange edge (labeled "
        "edge=src->dst) — the receiver's rejoin ledgers dedupe them, "
        "so replay volume is a recovery-cost signal, not a "
        "correctness one",
    ),
    "dnz_exchange_edges_down": (
        "gauge",
        "inbound exchange edges currently disconnected on one worker "
        "(labeled worker=id); nonzero while a peer is dead or "
        "mid-rejoin — the degraded-edge doctor verdict reads this",
    ),
    "dnz_cluster_recovery_ms": (
        "histogram",
        "wall time from detecting a worker death to its respawn "
        "reporting ready with the rejoin handshake complete — the "
        "partial-recovery latency the full-cluster fallback is "
        "measured against",
        MS_BUCKETS,
    ),
    "dnz_cluster_worker_restarts_total": (
        "counter",
        "single-worker partial respawns ordered by the coordinator "
        "(labeled worker=id); full-cluster restarts do NOT count here "
        "— a rising series on one worker label points at a sick host "
        "or a poisoned partition subset",
    ),
}


def exp_bounds(spec: dict) -> list[float]:
    """Materialize an exponential bucket layout into ascending upper
    bounds (the +Inf bucket is implicit)."""
    start = float(spec["start"])
    factor = float(spec["factor"])
    count = int(spec["count"])
    return [start * factor**i for i in range(count)]


def declaration(name: str) -> tuple:
    """(kind, help, bounds|None) for a declared instrument; raises
    KeyError with the catalog pointer for unknown names — binding an
    undeclared instrument is a programming error the lint also catches
    statically (DNZ-M001)."""
    try:
        entry = INSTRUMENTS[name]
    except KeyError:
        raise KeyError(
            f"instrument {name!r} is not declared in "
            "denormalized_tpu/obs/catalog.py (DNZ-M001: every metric "
            "name must be declared with a help string)"
        ) from None
    kind, help_str = entry[0], entry[1]
    bounds = exp_bounds(entry[2]) if kind == "histogram" else None
    return kind, help_str, bounds
