"""Import-compat shim for the reference's vendored datafusion layer.

The reference exposes its expression/function surface as
``denormalized.datafusion`` (py-denormalized/python/denormalized/datafusion/
__init__.py:29-56); migrating code does::

    from denormalized.datafusion import Accumulator, col, lit, udf, udaf
    from denormalized.datafusion import functions as f

With this shim the only change is the package name::

    from denormalized_tpu.datafusion import Accumulator, col, lit, udf, udaf
    from denormalized_tpu.datafusion import functions as f

Everything here is a re-export of the native API
(:mod:`denormalized_tpu.api.functions`, 229/229 function-surface parity
pinned by tests/test_functions_round3.py) — no separate implementation.
"""

import sys

from denormalized_tpu.api import functions
from denormalized_tpu.api.functions import col, lit, udf, udaf
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.logical.expr import Expr

# the reference aliases these in its __all__ (datafusion/__init__.py)
column = col
literal = lit

# `from denormalized.datafusion.functions import count` works against the
# reference (functions.py is a real module there); register the submodule
# path so the renamed import works too
sys.modules[__name__ + ".functions"] = functions

__all__ = [
    "Accumulator",
    "Expr",
    "col",
    "column",
    "functions",
    "lit",
    "literal",
    "udf",
    "udaf",
]
