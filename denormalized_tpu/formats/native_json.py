"""ctypes wrapper over the native one-pass JSON → columnar parser (shared
plumbing in :mod:`denormalized_tpu.formats._native_parser_base`).

Flat schemas use the historical column ABI; nested schemas (structs to
any depth, lists of scalars, lists of structs, lists of lists — the full
shape set the reference's arrow-json reader handles natively,
decoders/json.rs:11-49) use the shredded node-tree ABI
(``jp_create_tree``).  Only dynamic-map structs (no declared children)
raise :class:`FormatError`, which routes the decoder to the Python
fallback."""

from __future__ import annotations

import ctypes

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.formats._native_parser_base import (
    ColumnarNativeParser,
    NodeDesc,
    configure_lib,
)
from denormalized_tpu.native.build import load

_TYPE_CODE = {
    DataType.INT64: 0,
    DataType.TIMESTAMP_MS: 0,
    DataType.INT32: 0,
    DataType.FLOAT64: 1,
    DataType.FLOAT32: 1,
    DataType.BOOL: 2,
    DataType.STRING: 3,
}
_OUT_KIND = {0: "i64", 1: "f64", 2: "bool", 3: "str"}


def _lib():
    lib = load("json_parser")
    configure_lib(
        lib,
        "jp",
        [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int),
        ],
    )
    if not getattr(lib, "_jp_tree_configured", False):
        lib.jp_create_tree.restype = ctypes.c_void_p
        lib.jp_create_tree.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib._jp_tree_configured = True
    return lib


def build_node_tree(schema: Schema):
    """Flatten a (possibly nested) schema into the parallel arrays the
    ``jp_create_tree`` ABI takes, plus the :class:`NodeDesc` tree used for
    extraction.  Scalar-element lists use the packed type-5 layout
    (elements in the list node's own vectors); lists of structs / lists
    of lists become type-6 generic lists whose single child node is the
    element subtree.  Raises :class:`FormatError` only for childless
    structs — dynamic maps stay on the Python fallback."""
    names: list[bytes] = []
    types: list[int] = []
    etypes: list[int] = []
    parents: list[int] = []

    def add(f: Field, parent: int) -> NodeDesc:
        idx = len(names)
        names.append(f.name.encode())
        parents.append(parent)
        if f.dtype in _TYPE_CODE:
            code = _TYPE_CODE[f.dtype]
            types.append(code)
            etypes.append(-1)
            return NodeDesc(idx, f, _OUT_KIND[code])
        if f.dtype is DataType.STRUCT:
            if not f.children:
                raise FormatError(
                    f"native parser cannot shred dynamic-map struct "
                    f"{f.name!r} (no declared children)"
                )
            types.append(4)
            etypes.append(-1)
            nd = NodeDesc(idx, f, "struct")
            for c in f.children:
                nd.children.append(add(c, idx))
            return nd
        if f.dtype is DataType.LIST:
            if len(f.children) != 1:
                raise FormatError(
                    f"native parser cannot shred list {f.name!r} "
                    f"(exactly one declared element required)"
                )
            elem = f.children[0]
            if elem.dtype in _TYPE_CODE:
                ecode = _TYPE_CODE[elem.dtype]
                types.append(5)
                etypes.append(ecode)
                return NodeDesc(idx, f, "list", elem_kind=_OUT_KIND[ecode])
            # list of structs / list of lists: generic list node, element
            # subtree as the single child
            types.append(6)
            etypes.append(-1)
            nd = NodeDesc(idx, f, "list")
            nd.children.append(add(elem, idx))
            return nd
        raise FormatError(f"native parser cannot handle {f.dtype}")

    tree = [add(f, -1) for f in schema]
    return names, types, etypes, parents, tree


class NativeJsonParser(ColumnarNativeParser):
    _prefix = "jp"

    def __init__(self, schema: Schema):
        self.schema = schema
        self._libref = _lib()
        if all(f.dtype in _TYPE_CODE for f in schema):
            # flat schema: historical column ABI (node i = column i)
            self._tree = None
            self._kinds = [_OUT_KIND[_TYPE_CODE[f.dtype]] for f in schema]
            names = (ctypes.c_char_p * len(schema))(
                *[f.name.encode() for f in schema]
            )
            types = (ctypes.c_int * len(schema))(
                *[_TYPE_CODE[f.dtype] for f in schema]
            )
            self._h = self._libref.jp_create(len(schema), names, types)
            return
        names, types, etypes, parents, tree = build_node_tree(schema)
        n = len(names)
        self._tree = tree
        self._kinds = []  # unused on the tree path
        self._h = self._libref.jp_create_tree(
            n,
            (ctypes.c_char_p * n)(*names),
            (ctypes.c_int * n)(*types),
            (ctypes.c_int * n)(*etypes),
            (ctypes.c_int * n)(*parents),
        )
