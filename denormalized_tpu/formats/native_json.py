"""ctypes wrapper over the native one-pass JSON → columnar parser."""

from __future__ import annotations

import ctypes

import numpy as np

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Schema
from denormalized_tpu.native.build import load

_TYPE_CODE = {
    DataType.INT64: 0,
    DataType.TIMESTAMP_MS: 0,
    DataType.INT32: 0,
    DataType.FLOAT64: 1,
    DataType.FLOAT32: 1,
    DataType.BOOL: 2,
    DataType.STRING: 3,
}


def _lib():
    lib = load("json_parser")
    if not getattr(lib, "_jp_configured", False):
        lib.jp_create.restype = ctypes.c_void_p
        lib.jp_create.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.jp_parse.restype = ctypes.c_int
        lib.jp_parse.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,  # bytes or a raw pointer into a native buffer
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
        ]
        lib.jp_error.restype = ctypes.c_char_p
        lib.jp_error.argtypes = [ctypes.c_void_p]
        lib.jp_nrows.restype = ctypes.c_uint64
        lib.jp_nrows.argtypes = [ctypes.c_void_p]
        for fn, restype in (
            ("jp_col_i64", ctypes.POINTER(ctypes.c_int64)),
            ("jp_col_f64", ctypes.POINTER(ctypes.c_double)),
            ("jp_col_bool", ctypes.POINTER(ctypes.c_uint8)),
            ("jp_col_valid", ctypes.POINTER(ctypes.c_uint8)),
            ("jp_col_str_offsets", ctypes.POINTER(ctypes.c_uint64)),
        ):
            getattr(lib, fn).restype = restype
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.jp_col_str_bytes.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.jp_col_str_bytes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.jp_clear.argtypes = [ctypes.c_void_p]
        lib.jp_destroy.argtypes = [ctypes.c_void_p]
        lib._jp_configured = True
    return lib


class NativeJsonParser:
    def __init__(self, schema: Schema):
        for f in schema:
            if f.dtype not in _TYPE_CODE:
                raise FormatError(f"native parser cannot handle {f.dtype}")
        self.schema = schema
        self._libref = _lib()
        names = (ctypes.c_char_p * len(schema))(
            *[f.name.encode() for f in schema]
        )
        types = (ctypes.c_int * len(schema))(
            *[_TYPE_CODE[f.dtype] for f in schema]
        )
        self._h = self._libref.jp_create(len(schema), names, types)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._libref.jp_destroy(h)
            self._h = None

    def parse(self, rows: list[bytes]) -> RecordBatch:
        n = len(rows)
        if n == 0:
            return RecordBatch.empty(self.schema)
        data = b"".join(rows)
        offsets = np.zeros(n + 1, dtype=np.uint64)
        offsets[1:] = np.cumsum([len(r) for r in rows], dtype=np.uint64)
        return self.parse_ptr(
            data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )

    def parse_ptr(self, data, offsets_ptr, n: int) -> RecordBatch:
        """Zero-copy entry: ``data`` may be a bytes object OR a raw ctypes
        pointer into another native component's buffer (e.g. the Kafka
        client's fetch arena) — payload bytes never become Python objects."""
        lib = self._libref
        lib.jp_clear(self._h)
        rc = lib.jp_parse(self._h, data, offsets_ptr, n)
        if rc != 0:
            raise FormatError(lib.jp_error(self._h).decode())
        cols, masks = [], []
        for ci, f in enumerate(self.schema):
            valid = np.ctypeslib.as_array(
                lib.jp_col_valid(self._h, ci), shape=(n,)
            ).astype(bool)
            code = _TYPE_CODE[f.dtype]
            if code == 0:
                arr = np.ctypeslib.as_array(
                    lib.jp_col_i64(self._h, ci), shape=(n,)
                ).astype(f.dtype.to_numpy(), copy=True)
            elif code == 1:
                arr = np.ctypeslib.as_array(
                    lib.jp_col_f64(self._h, ci), shape=(n,)
                ).astype(f.dtype.to_numpy(), copy=True)
            elif code == 2:
                arr = np.ctypeslib.as_array(
                    lib.jp_col_bool(self._h, ci), shape=(n,)
                ).astype(bool)
            else:
                nb = ctypes.c_uint64()
                bptr = lib.jp_col_str_bytes(self._h, ci, ctypes.byref(nb))
                raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
                offs = np.ctypeslib.as_array(
                    lib.jp_col_str_offsets(self._h, ci), shape=(n + 1,)
                )
                arr = np.empty(n, dtype=object)
                for i in range(n):
                    # errors='replace': never crash the reader on weird
                    # escape sequences; lone surrogates become U+FFFD
                    arr[i] = raw[offs[i] : offs[i + 1]].decode(
                        errors="replace"
                    )
            cols.append(arr)
            masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)
