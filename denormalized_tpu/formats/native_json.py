"""ctypes wrapper over the native one-pass JSON → columnar parser (shared
plumbing in :mod:`denormalized_tpu.formats._native_parser_base`)."""

from __future__ import annotations

import ctypes

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.schema import DataType, Schema
from denormalized_tpu.formats._native_parser_base import (
    ColumnarNativeParser,
    configure_lib,
)
from denormalized_tpu.native.build import load

_TYPE_CODE = {
    DataType.INT64: 0,
    DataType.TIMESTAMP_MS: 0,
    DataType.INT32: 0,
    DataType.FLOAT64: 1,
    DataType.FLOAT32: 1,
    DataType.BOOL: 2,
    DataType.STRING: 3,
}
_OUT_KIND = {0: "i64", 1: "f64", 2: "bool", 3: "str"}


def _lib():
    lib = load("json_parser")
    configure_lib(
        lib,
        "jp",
        [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int),
        ],
    )
    return lib


class NativeJsonParser(ColumnarNativeParser):
    _prefix = "jp"

    def __init__(self, schema: Schema):
        for f in schema:
            if f.dtype not in _TYPE_CODE:
                raise FormatError(f"native parser cannot handle {f.dtype}")
        self.schema = schema
        self._kinds = [_OUT_KIND[_TYPE_CODE[f.dtype]] for f in schema]
        self._libref = _lib()
        names = (ctypes.c_char_p * len(schema))(
            *[f.name.encode() for f in schema]
        )
        types = (ctypes.c_int * len(schema))(
            *[_TYPE_CODE[f.dtype] for f in schema]
        )
        self._h = self._libref.jp_create(len(schema), names, types)
