"""ctypes wrapper over the native one-pass Avro-binary → columnar parser.

Same shape as :mod:`denormalized_tpu.formats.native_json` (shared plumbing
in :mod:`denormalized_tpu.formats._native_parser_base`): ``parse_ptr``
accepts either bytes or a raw pointer into another native component's
buffer (the Kafka fetch arena), so payload bytes never become Python
objects on the hot path.  Reference capability: the Rust-native Avro
decode at crates/core/src/formats/decoders/avro.rs:11-54.
"""

from __future__ import annotations

import ctypes

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.formats._native_parser_base import (
    ColumnarNativeParser,
    configure_lib,
)
from denormalized_tpu.native.build import load

# native type codes (see avro_parser.cpp): base Avro type → code.
# 'bytes' is deliberately absent: the native path would decode it as UTF-8
# text (destroying binary payloads) while the Python fallback returns raw
# bytes — schemas with bytes fields fall back to the Python decoder so the
# column content never depends on whether a compiler was available.
_AVRO_CODE = {
    "int": 0,
    "long": 0,
    "boolean": 2,
    "float": 4,
    "double": 1,
    "string": 3,
}
_OUT_KIND = {0: "i64", 1: "f64", 4: "f64", 2: "bool", 3: "str"}


def _lib():
    lib = load("avro_parser")
    configure_lib(
        lib,
        "ap",
        [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ],
    )
    return lib


def _base_type(t) -> str:
    if isinstance(t, dict):
        return str(t.get("type"))
    return str(t)


class NativeAvroParser(ColumnarNativeParser):
    """One parser per AvroSchema; positional fields, flat records only."""

    _prefix = "ap"

    def __init__(self, avro_schema, schema: Schema):
        # Avro fields are positional: the engine schema MUST align
        # one-to-one with the Avro declaration, or columns would be
        # silently mislabeled (a reordered/subset user schema falls back to
        # the by-name pure-Python decoder instead)
        if len(schema) != len(avro_schema.fields) or any(
            f.name != name
            for f, (name, _, _) in zip(schema, avro_schema.fields)
        ):
            raise FormatError(
                "engine schema does not align positionally with the Avro "
                "declaration"
            )
        self.schema = schema
        codes = []
        nullables = []
        for name, t, nullable in avro_schema.fields:
            base = _base_type(t)
            if base not in _AVRO_CODE:
                raise FormatError(f"native Avro parser cannot handle {t!r}")
            codes.append(_AVRO_CODE[base])
            nullables.append(1 if nullable else 0)
        self._kinds = [_OUT_KIND[c] for c in codes]
        self._libref = _lib()
        ctypes_codes = (ctypes.c_int * len(codes))(*codes)
        ctypes_nulls = (ctypes.c_int * len(codes))(*nullables)
        self._h = self._libref.ap_create(len(codes), ctypes_codes, ctypes_nulls)
