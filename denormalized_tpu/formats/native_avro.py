"""ctypes wrapper over the native one-pass Avro-binary → columnar parser.

Same shape as :mod:`denormalized_tpu.formats.native_json` (shared plumbing
in :mod:`denormalized_tpu.formats._native_parser_base`): ``parse_ptr``
accepts either bytes or a raw pointer into another native component's
buffer (the Kafka fetch arena), so payload bytes never become Python
objects on the hot path.  Reference capability: the Rust-native Avro
decode at crates/core/src/formats/decoders/avro.rs:11-54.

Flat records of primitives use the historical positional-column ABI;
nested records and arrays (of primitives, records, or arrays — to any
depth) use the schema-tree ABI (``ap_create_tree``), the Avro analog of
the JSON parser's shredded node tree.  Shapes outside that — maps, enums,
fixed, ``bytes`` fields, unions beyond the ``["null", T]`` sugar,
recursive named types — raise :class:`FormatError`, which routes the
decoder to the recursive pure-Python codec.
"""

from __future__ import annotations

import ctypes

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.formats._native_parser_base import (
    ColumnarNativeParser,
    NodeDesc,
    configure_lib,
)
from denormalized_tpu.native.build import load

# native type codes (see avro_parser.cpp): base Avro type → code.
# 'bytes' is deliberately absent: the native path would decode it as UTF-8
# text (destroying binary payloads) while the Python fallback returns raw
# bytes — schemas with bytes fields fall back to the Python decoder so the
# column content never depends on whether a compiler was available.
_AVRO_CODE = {
    "int": 0,
    "long": 0,
    "boolean": 2,
    "float": 4,
    "double": 1,
    "string": 3,
}
_OUT_KIND = {0: "i64", 1: "f64", 4: "f64", 2: "bool", 3: "str"}

_STRUCT_CODE = 5
_LIST_CODE = 6


def _lib():
    lib = load("avro_parser")
    configure_lib(
        lib,
        "ap",
        [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ],
    )
    if not getattr(lib, "_ap_tree_configured", False):
        lib.ap_create_tree.restype = ctypes.c_void_p
        lib.ap_create_tree.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib._ap_tree_configured = True
    return lib


def _scalar_code(t) -> int | None:
    """Native scalar code for a resolved Avro type, or None (annotated
    primitives — timestamp-millis longs — count as their base)."""
    base = t.get("type") if isinstance(t, dict) else t
    if not isinstance(base, str):
        return None
    return _AVRO_CODE.get(base)


def build_avro_node_tree(avro_schema, schema: Schema):
    """Flatten a resolved :class:`AvroSchema` into the parallel arrays the
    ``ap_create_tree`` ABI takes, plus the :class:`NodeDesc` tree used
    for extraction.  The engine ``schema`` must align positionally (Avro
    decode is positional — a reordered/subset user schema would silently
    mislabel columns) at EVERY record level.  Raises :class:`FormatError`
    for any shape the native walker does not decode (see module doc)."""
    types: list[int] = []
    nullables: list[int] = []
    parents: list[int] = []

    def add(name: str, t, nullable: bool, field: Field, parent: int) -> NodeDesc:
        if field.name != name:
            raise FormatError(
                f"engine field {field.name!r} does not align positionally "
                f"with Avro field {name!r}"
            )
        if isinstance(t, list):
            # general union (includes ['T', 'null'] order, whose wire
            # branch indices invert the nullable sugar): Python decoder
            raise FormatError(
                f"native Avro parser cannot handle union {t!r}"
            )
        idx = len(types)
        code = _scalar_code(t)
        if code is not None:
            types.append(code)
            nullables.append(1 if nullable else 0)
            parents.append(parent)
            return NodeDesc(idx, field, _OUT_KIND[code])
        if not isinstance(t, dict):
            raise FormatError(f"native Avro parser cannot handle {t!r}")
        kind = t.get("type")
        if kind == "record":
            fields_spec = t["_fields"]
            if (
                field.dtype is not DataType.STRUCT
                or len(field.children) != len(fields_spec)
            ):
                # childless STRUCT = recursive back-reference or a shape
                # mismatch — either way the static tree can't cover it
                raise FormatError(
                    f"engine field {field.name!r} does not match Avro "
                    f"record {t.get('name')!r}"
                )
            types.append(_STRUCT_CODE)
            nullables.append(1 if nullable else 0)
            parents.append(parent)
            nd = NodeDesc(idx, field, "struct")
            for (fname, ftype, fnull), cf in zip(fields_spec, field.children):
                nd.children.append(add(fname, ftype, fnull, cf, idx))
            return nd
        if kind == "array":
            if field.dtype is not DataType.LIST or len(field.children) != 1:
                raise FormatError(
                    f"engine field {field.name!r} does not match Avro array"
                )
            items = t["items"]
            inull = False
            if isinstance(items, list):
                # items-level nullable sugar; only the ['null', T] order
                # maps onto the branch-0-is-null wire walk
                if len(items) == 2 and items[0] == "null":
                    items, inull = items[1], True
                else:
                    raise FormatError(
                        f"native Avro parser cannot handle item union "
                        f"{items!r}"
                    )
            types.append(_LIST_CODE)
            nullables.append(1 if nullable else 0)
            parents.append(parent)
            nd = NodeDesc(idx, field, "list")
            elem = field.children[0]
            nd.children.append(add(elem.name, items, inull, elem, idx))
            return nd
        # maps (dynamic keys), enums, fixed, bytes: Python decoder
        raise FormatError(f"native Avro parser cannot handle {t!r}")

    if len(schema) != len(avro_schema.fields):
        raise FormatError(
            "engine schema does not align positionally with the Avro "
            "declaration"
        )
    tree = [
        add(name, t, nullable, f, -1)
        for (name, t, nullable), f in zip(avro_schema.fields, schema)
    ]
    return types, nullables, parents, tree


class NativeAvroParser(ColumnarNativeParser):
    """One parser per AvroSchema; positional fields, schema-tree driven."""

    _prefix = "ap"

    def __init__(self, avro_schema, schema: Schema):
        # Avro fields are positional: the engine schema MUST align
        # one-to-one with the Avro declaration, or columns would be
        # silently mislabeled (a reordered/subset user schema falls back to
        # the by-name pure-Python decoder instead)
        if len(schema) != len(avro_schema.fields) or any(
            f.name != name
            for f, (name, _, _) in zip(schema, avro_schema.fields)
        ):
            raise FormatError(
                "engine schema does not align positionally with the Avro "
                "declaration"
            )
        self.schema = schema
        self._libref = _lib()
        flat_codes = [
            _scalar_code(t) for _, t, _ in avro_schema.fields
        ]
        if all(c is not None for c in flat_codes):
            # flat record of primitives: historical positional-column ABI
            self._tree = None
            self._kinds = [_OUT_KIND[c] for c in flat_codes]
            nullables = [
                1 if nullable else 0 for _, _, nullable in avro_schema.fields
            ]
            n = len(flat_codes)
            self._h = self._libref.ap_create(
                n,
                (ctypes.c_int * n)(*flat_codes),
                (ctypes.c_int * n)(*nullables),
            )
            return
        types, nullables, parents, tree = build_avro_node_tree(
            avro_schema, schema
        )
        n = len(types)
        self._tree = tree
        self._kinds = []  # unused on the tree path
        self._h = self._libref.ap_create_tree(
            n,
            (ctypes.c_int * n)(*types),
            (ctypes.c_int * n)(*nullables),
            (ctypes.c_int * n)(*parents),
        )
