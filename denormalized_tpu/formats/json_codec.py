"""JSON payloads ⇄ columnar batches.

Covers three reference components:
- ``JsonDecoder`` (formats/decoders/json.rs:11-49): buffer payload bytes,
  flush one batch against a target schema;
- JSON schema inference (utils/arrow_helpers.rs:283
  ``infer_arrow_schema_from_json_value`` — nested structs/lists recursed);
- ``JsonRowEncoder`` (utils/row_encoder.rs:5-44): batch → per-row JSON
  byte payloads for sinks.

The decode hot path uses the native C++ columnar parser
(:mod:`denormalized_tpu.formats.native_json`) — flat schemas AND nested
ones (structs to any depth, lists of scalars, lists of structs, lists of
lists) via the shredded node-tree ABI.  Python ``json`` remains only for
dynamic-map structs (no declared children), the one shape with no static
shredding.

Both paths normalize nested struct values to the DECLARED schema shape
(missing children become None, undeclared keys are dropped) — the same
semantics the reference gets from arrow-json's schema-driven reader, and
a precondition for the two decode paths staying bit-identical.
"""

from __future__ import annotations

import json
import math

import numpy as np

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.formats import Decoder, _warn_native_unavailable


# -- schema inference ----------------------------------------------------


def infer_field(name: str, value) -> Field:
    if isinstance(value, bool):
        return Field(name, DataType.BOOL)
    if isinstance(value, int):
        return Field(name, DataType.INT64)
    if isinstance(value, float):
        return Field(name, DataType.FLOAT64)
    if isinstance(value, str):
        return Field(name, DataType.STRING)
    if value is None:
        return Field(name, DataType.STRING)
    if isinstance(value, dict):
        children = tuple(infer_field(k, v) for k, v in value.items())
        return Field(name, DataType.STRUCT, children=children)
    if isinstance(value, list):
        child = (
            infer_field("item", value[0]) if value else Field("item", DataType.STRING)
        )
        return Field(name, DataType.LIST, children=(child,))
    raise FormatError(f"cannot infer type for {name}={value!r}")


def infer_schema_from_json(sample: str | bytes) -> Schema:
    """Schema from one sample JSON object (the from_topic sample_json path,
    py-denormalized/src/context.rs:64-83)."""
    obj = json.loads(sample)
    if not isinstance(obj, dict):
        raise FormatError("sample JSON must be an object")
    return Schema([infer_field(k, v) for k, v in obj.items()])


# -- decoding ------------------------------------------------------------


class JsonDecoder(Decoder):
    """``decode_fallback_rows`` counts rows that decoded on the Python
    path (native parser unavailable or schema declined) — surfaced
    through source ``metrics()`` so a schema that silently routes to the
    ~30x-slower fallback is observable, never a quiet perf cliff."""

    def __init__(self, schema: Schema, use_native: bool = True):
        self.schema = schema
        self._rows: list[bytes] = []
        self._native = None
        self.decode_fallback_rows = 0
        if use_native:
            try:
                from denormalized_tpu.formats.native_json import NativeJsonParser

                self._native = NativeJsonParser(schema)
            except Exception as e:  # dnzlint: allow(broad-except) pure-Python decode is the designed fallback (no compiler / unsupported schema shape); the downgrade is logged once and counted in decode_fallback_rows, and test_native_build_gate fails images where the build should work
                _warn_native_unavailable("JSON", e)
                self._native = None

    def push(self, payload: bytes) -> None:
        if payload:
            self._rows.append(payload)

    def flush(self) -> RecordBatch:
        rows, self._rows = self._rows, []
        if self._native is not None:
            return self._native.parse(rows)
        self.decode_fallback_rows += len(rows)
        return decode_json_rows(rows, self.schema)


_LEAF_PYTYPES = {
    DataType.INT32: (int,),
    DataType.INT64: (int,),
    DataType.TIMESTAMP_MS: (int,),
    DataType.FLOAT32: (int, float),
    DataType.FLOAT64: (int, float),
    DataType.BOOL: (bool,),
    # bytes: the avro decoder represents avro "bytes" values as python
    # bytes in STRING columns and shares rows_to_batch; json.loads can
    # never produce bytes, so this does not loosen the JSON path
    DataType.STRING: (str, bytes),
}


def _normalize_nested(v, f: Field):
    """Reshape a decoded nested value to the DECLARED field shape: struct
    values keep exactly the schema's children (missing → None, undeclared
    keys dropped), recursively; type-mismatched values (an int where a
    struct is declared, a bool on an int leaf) raise FormatError.  Structs
    with no declared children (dynamic maps) and lists with no declared
    element pass through as-is.  This is exactly what the native shredded
    parser produces — schema-strict like the reference's arrow-json
    reader (decoders/json.rs:11-49) — so downstream code (field access,
    sinks, checkpoints) sees one shape and one failure mode regardless of
    which decode path ran."""
    if v is None:
        return None
    if f.dtype is DataType.STRUCT and f.children:
        if not isinstance(v, dict):
            raise FormatError(
                f"field {f.name!r}: expected an object, got {v!r}"
            )
        return {
            c.name: _normalize_nested(v.get(c.name), c) for c in f.children
        }
    if f.dtype is DataType.LIST and len(f.children) == 1:
        if not isinstance(v, list):
            raise FormatError(
                f"field {f.name!r}: expected an array, got {v!r}"
            )
        c = f.children[0]
        return [_normalize_nested(x, c) for x in v]
    want = _LEAF_PYTYPES.get(f.dtype)
    if want is not None and (
        not isinstance(v, want)
        or (bool not in want and isinstance(v, bool))
    ):
        raise FormatError(
            f"field {f.name!r}: cannot coerce {v!r} to {f.dtype.value}"
        )
    if f.dtype in (DataType.FLOAT32, DataType.FLOAT64):
        # int-typed JSON on a float leaf: the native parser always
        # materializes float — match it, or sink/checkpoint bytes would
        # differ by decode path ('3' vs '3.0')
        return _to_float(v)
    if f.dtype is DataType.INT32:
        # nested leaves live in object columns (no numpy narrowing), so
        # the declared i32 width is enforced here — the same clamp the
        # native extraction applies (_native_parser_base._clamp_nested_ints),
        # and the same bounds flat INT32 columns saturate at
        return _saturate_int(v, _I32_MIN, _I32_MAX)
    if f.dtype in (DataType.INT64, DataType.TIMESTAMP_MS):
        # out-of-int64-range: the native parser keeps strtoll's saturate
        # semantics (json.loads accepts 20-digit ints, so refusing would
        # fail the batch); clamp identically here
        return _saturate_int(v, _I64_MIN, _I64_MAX)
    return v


_I64_MIN, _I64_MAX = -0x8000000000000000, 0x7FFFFFFFFFFFFFFF
_I32_MIN, _I32_MAX = -0x80000000, 0x7FFFFFFF


def _saturate_int(v: int, lo: int, hi: int) -> int:
    """strtoll-style saturation shared by both decode paths (the native
    parser clamps at parse for i64 and at extraction for narrower
    columns; the Python path must clamp identically or the same producer
    stream fails on one host and succeeds on another)."""
    return hi if v > hi else lo if v < lo else v


def _to_float(v) -> float:
    """int/float → float with strtod's overflow semantics: a JSON int too
    large for a double becomes ±inf (the native path's result), never an
    OverflowError escaping the codec's error contract."""
    try:
        return float(v)
    except OverflowError:
        return float("inf") if v > 0 else float("-inf")


def _null_of(dtype: DataType):
    # values behind an invalid mask are unspecified; use 0 (same convention
    # as the native parser) so both decode paths are bit-identical
    return {
        DataType.INT32: 0,
        DataType.INT64: 0,
        DataType.TIMESTAMP_MS: 0,
        DataType.FLOAT32: 0.0,
        DataType.FLOAT64: 0.0,
        DataType.BOOL: False,
    }.get(dtype)


def decode_json_rows(rows: list[bytes], schema: Schema) -> RecordBatch:
    """Pure-Python decode path (nested schemas / fallback)."""
    objs = []
    for r in rows:
        try:
            objs.append(json.loads(r))
        except json.JSONDecodeError as e:
            raise FormatError(f"invalid JSON payload: {e}") from None
    return rows_to_batch(objs, schema)


def rows_to_batch(objs: list[dict], schema: Schema) -> RecordBatch:
    for i, o in enumerate(objs):
        if not isinstance(o, dict):
            raise FormatError(
                f"row {i}: expected a JSON object, got {type(o).__name__}"
            )
    n = len(objs)
    cols, masks = [], []
    for f in schema:
        if f.dtype in (DataType.STRUCT, DataType.LIST, DataType.STRING):
            col = np.empty(n, dtype=object)
            mask = np.ones(n, dtype=bool)
            for i, o in enumerate(objs):
                v = o.get(f.name)
                if v is None:
                    mask[i] = False
                col[i] = _normalize_nested(v, f)
            cols.append(col)
            masks.append(None if mask.all() else mask)
            continue
        npdt = f.dtype.to_numpy()
        col = np.zeros(n, dtype=npdt)
        mask = np.ones(n, dtype=bool)
        null = _null_of(f.dtype)
        want = _LEAF_PYTYPES.get(f.dtype)
        # integer columns saturate wide JSON ints at the DECLARED width,
        # matching the native path (strtoll i64 saturation at parse, clip
        # at narrowing extraction) — numpy assignment alone would raise
        # (int64) or wrap (int32)
        info = np.iinfo(npdt) if npdt.kind == "i" else None
        # f32 columns: out-of-range doubles overflow to +-inf on
        # assignment — same result as the native path's narrowing cast;
        # the RuntimeWarning is expected, not actionable
        with np.errstate(over="ignore"):
            for i, o in enumerate(objs):
                v = o.get(f.name)
                if v is None:
                    mask[i] = False
                    col[i] = null
                    continue
                # same leaf strictness as the native parser and the nested
                # normalizer: a float or bool on an int column (or non-bool
                # on a bool column) fails the batch on BOTH paths — numpy's
                # unsafe-cast assignment would otherwise truncate 1.5 -> 1
                # only on hosts without the native lib
                if want is not None and (
                    not isinstance(v, want)
                    or (bool not in want and isinstance(v, bool))
                ):
                    raise FormatError(
                        f"field {f.name!r}: cannot coerce {v!r} to "
                        f"{f.dtype.value}"
                    )
                if info is not None:
                    v = _saturate_int(v, int(info.min), int(info.max))
                elif npdt.kind == "f" and isinstance(v, int):
                    # ints beyond double range saturate to +-inf like the
                    # native path's strtod overflow
                    v = _to_float(v)
                try:
                    col[i] = v
                except (TypeError, ValueError, OverflowError):
                    # 1e200 into f32 is fine (inf); exotic objects are not
                    raise FormatError(
                        f"field {f.name!r}: cannot coerce {v!r} to "
                        f"{f.dtype.value}"
                    ) from None
        cols.append(col)
        masks.append(None if mask.all() else mask)
    return RecordBatch(schema, cols, masks)


# -- encoding (sink side) ------------------------------------------------


class JsonRowEncoder:
    """RecordBatch → per-row JSON byte payloads (utils/row_encoder.rs).

    Column-major preparation: each column converts to a plain-Python value
    list ONCE (``tolist`` is one C call; NaN→None and mask→None patch in
    bulk), then rows assemble by zipping the prepared lists — the per-row
    work is exactly one dict build + ``json.dumps``, with no per-row column
    lookups, mask probes, or numpy-scalar unboxing.  Measurable on
    high-fanout kafka sink emission."""

    def encode(self, batch: RecordBatch) -> list[bytes]:
        user = batch.select(batch.schema.without_internal().names)
        names = user.schema.names
        pycols: list[list] = []
        for j in range(len(names)):
            c = user.columns[j]
            kind = getattr(c.dtype, "kind", "O")
            if c.dtype == object:
                vals = [_jsonify(v) for v in c.tolist()]
            elif kind == "f":
                vals = c.tolist()
                if np.isnan(c).any():
                    vals = [None if v != v else v for v in vals]
            else:
                # int/bool tolist() already yields native Python scalars
                vals = c.tolist()
            m = user.masks[j]
            if m is not None:
                vals = [
                    v if ok else None for v, ok in zip(vals, m.tolist())
                ]
            pycols.append(vals)
        dumps = json.dumps
        return [
            dumps(dict(zip(names, row))).encode()
            for row in zip(*pycols)
        ] if pycols else [b"{}"] * user.num_rows


def _jsonify(v):
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        f = float(v)
        return None if math.isnan(f) else f
    if isinstance(v, np.bool_):
        return bool(v)
    return v
