"""Stream payload formats.

Mirror of the reference's ``formats`` module: the ``Decoder`` seam
(crates/core/src/formats/decoders/mod.rs:4-8 — push raw payload bytes,
flush one RecordBatch), JSON and Avro decoders, and the ``StreamEncoding``
enum (formats/mod.rs:5-24).
"""

from __future__ import annotations

import enum

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema


class StreamEncoding(enum.Enum):
    JSON = "json"
    AVRO = "avro"

    @staticmethod
    def from_str(s: str) -> "StreamEncoding":
        try:
            return StreamEncoding(s.lower())
        except ValueError:
            raise FormatError(f"unknown encoding {s!r} (expected json|avro)")


class Decoder:
    """Buffer raw payloads; flush to one columnar batch."""

    schema: Schema

    def push(self, payload: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> RecordBatch:
        raise NotImplementedError


_warned_native: set[str] = set()


def _warn_native_unavailable(fmt: str, err: BaseException) -> None:
    """One warning per format per process when a native parser cannot be
    used and the ~10-30x-slower Python decode silently takes over — the
    exact downgrade that shipped unnoticed for five rounds (CHANGES.md
    PR 1).  The fallback is still the right behavior (no-compiler boxes,
    schema shapes the native tree doesn't cover); the silence was not."""
    if fmt in _warned_native:
        return
    _warned_native.add(fmt)
    from denormalized_tpu.runtime.tracing import logger

    logger.warning(
        "native %s parser unavailable (%s: %s) — decoding through the "
        "pure-Python path; decode_fallback_rows will count the rows",
        fmt, type(err).__name__, err,
    )


def make_decoder(encoding: StreamEncoding, schema: Schema, avro_schema=None):
    if encoding is StreamEncoding.JSON:
        from denormalized_tpu.formats.json_codec import JsonDecoder

        return JsonDecoder(schema)
    from denormalized_tpu.formats.avro_codec import AvroDecoder

    return AvroDecoder(schema, avro_schema)
