"""Stream payload formats.

Mirror of the reference's ``formats`` module: the ``Decoder`` seam
(crates/core/src/formats/decoders/mod.rs:4-8 — push raw payload bytes,
flush one RecordBatch), JSON and Avro decoders, and the ``StreamEncoding``
enum (formats/mod.rs:5-24).
"""

from __future__ import annotations

import enum

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema


class StreamEncoding(enum.Enum):
    JSON = "json"
    AVRO = "avro"

    @staticmethod
    def from_str(s: str) -> "StreamEncoding":
        try:
            return StreamEncoding(s.lower())
        except ValueError:
            raise FormatError(f"unknown encoding {s!r} (expected json|avro)")


class Decoder:
    """Buffer raw payloads; flush to one columnar batch."""

    schema: Schema

    def push(self, payload: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> RecordBatch:
        raise NotImplementedError


def make_decoder(encoding: StreamEncoding, schema: Schema, avro_schema=None):
    if encoding is StreamEncoding.JSON:
        from denormalized_tpu.formats.json_codec import JsonDecoder

        return JsonDecoder(schema)
    from denormalized_tpu.formats.avro_codec import AvroDecoder

    return AvroDecoder(schema, avro_schema)
