"""Avro binary payloads ⇄ columnar batches.

Mirror of the reference's Avro pipeline: schema-declaration parsing and
Avro→engine-schema conversion (formats/decoders/utils.rs:14
``to_arrow_schema``), the ``AvroDecoder`` (formats/decoders/avro.rs:11-54),
and the value⇄JSON bridges in utils/arrow_helpers.rs:52-126.  Implemented
from the Avro 1.11 binary spec (zigzag varints, length-prefixed bytes,
union-by-index) — the image ships no avro library.  An encoder is included
so tests can produce real Avro bytes (the reference tests do the same with
apache-avro, decoders/avro.rs:56-159).

Supported: records of null/boolean/int/long/float/double/string/bytes,
nullable unions ``["null", T]``, and logical type timestamp-millis.
"""

from __future__ import annotations

import io
import json
import struct

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.formats import Decoder
from denormalized_tpu.formats.json_codec import rows_to_batch

_PRIMITIVE = {
    "boolean": DataType.BOOL,
    "int": DataType.INT32,
    "long": DataType.INT64,
    "float": DataType.FLOAT32,
    "double": DataType.FLOAT64,
    "string": DataType.STRING,
    "bytes": DataType.STRING,
}


def parse_avro_schema(decl: str | dict) -> "AvroSchema":
    if isinstance(decl, str):
        decl = json.loads(decl)
    return AvroSchema(decl)


class AvroSchema:
    def __init__(self, decl: dict):
        if decl.get("type") != "record":
            raise FormatError("top-level Avro schema must be a record")
        self.decl = decl
        self.fields: list[tuple[str, object, bool]] = []  # (name, type, nullable)
        for f in decl["fields"]:
            t = f["type"]
            nullable = False
            if isinstance(t, list):  # union
                # null must come FIRST: the decoder maps union branch 0 to
                # null, so ['T', 'null'] would silently misread every value
                if len(t) != 2 or t[0] != "null":
                    raise FormatError(
                        f"only ['null', T] unions supported, got {t!r}"
                    )
                t = t[1]
                nullable = True
            self.fields.append((f["name"], t, nullable))

    def to_engine_schema(self) -> Schema:
        """Avro → engine schema (to_arrow_schema, decoders/utils.rs:14)."""
        out = []
        for name, t, nullable in self.fields:
            out.append(Field(name, _avro_type_to_dtype(t), nullable))
        return Schema(out)


def _avro_type_to_dtype(t) -> DataType:
    if isinstance(t, dict):
        lt = t.get("logicalType")
        if lt in ("timestamp-millis", "local-timestamp-millis"):
            return DataType.TIMESTAMP_MS
        t = t.get("type")
    if t in _PRIMITIVE:
        return _PRIMITIVE[t]
    raise FormatError(f"unsupported Avro type {t!r}")


# -- binary primitives (Avro spec §binary encoding) -----------------------


def _zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise FormatError("truncated Avro varint")
        b = raw[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def encode_value(t, nullable: bool, v, out: bytearray) -> None:
    if nullable:
        if v is None:
            out += _zigzag_encode(0)  # union branch 0 = null
            return
        out += _zigzag_encode(1)
    if v is None:
        raise FormatError("null value for non-nullable Avro field")
    base = t.get("type") if isinstance(t, dict) else t
    if base == "boolean":
        out.append(1 if v else 0)
    elif base in ("int", "long"):
        out += _zigzag_encode(int(v))
    elif base == "float":
        out += struct.pack("<f", float(v))
    elif base == "double":
        out += struct.pack("<d", float(v))
    elif base in ("string", "bytes"):
        raw = v.encode() if isinstance(v, str) else bytes(v)
        out += _zigzag_encode(len(raw))
        out += raw
    else:
        raise FormatError(f"unsupported Avro type {t!r}")


def decode_value(t, nullable: bool, buf: io.BytesIO):
    if nullable:
        branch = _zigzag_decode(buf)
        if branch == 0:
            return None
        if branch != 1:
            raise FormatError(
                f"invalid union branch {branch} (only ['null', T])"
            )
    base = t.get("type") if isinstance(t, dict) else t
    if base == "boolean":
        raw = buf.read(1)
        if len(raw) != 1:
            raise FormatError("truncated Avro boolean")
        return raw == b"\x01"
    if base in ("int", "long"):
        return _zigzag_decode(buf)
    if base == "float":
        raw = buf.read(4)
        if len(raw) != 4:
            raise FormatError("truncated Avro float")
        return struct.unpack("<f", raw)[0]
    if base == "double":
        raw = buf.read(8)
        if len(raw) != 8:
            raise FormatError("truncated Avro double")
        return struct.unpack("<d", raw)[0]
    if base in ("string", "bytes"):
        n = _zigzag_decode(buf)
        if n < 0:
            raise FormatError("negative Avro string length")
        raw = buf.read(n)
        if len(raw) != n:
            raise FormatError("truncated Avro string")
        # errors='replace' matches the native parser: invalid UTF-8 becomes
        # U+FFFD rather than an exception class the reader's per-record
        # salvage doesn't catch
        return raw.decode(errors="replace") if base == "string" else raw
    raise FormatError(f"unsupported Avro type {t!r}")


def encode_record(schema: AvroSchema, record: dict) -> bytes:
    out = bytearray()
    for name, t, nullable in schema.fields:
        encode_value(t, nullable, record.get(name), out)
    return bytes(out)


def decode_record(schema: AvroSchema, payload: bytes) -> dict:
    buf = io.BytesIO(payload)
    out = {
        name: decode_value(t, nullable, buf)
        for name, t, nullable in schema.fields
    }
    if buf.read(1):
        # same contract as the native parser: trailing bytes after the last
        # field mean a corrupt record or a mismatched schema
        raise FormatError("trailing bytes after Avro record")
    return out


class AvroDecoder(Decoder):
    """Buffer Avro-encoded records; flush one batch.

    Decode is native (C++ one-pass columnar, avro_parser.cpp — mirroring
    the reference's Rust-native path) whenever the schema is flat; the
    pure-Python record decoder remains as the no-compiler fallback and the
    differential-test oracle."""

    def __init__(self, schema: Schema | None, avro_schema, use_native=True):
        if avro_schema is None:
            raise FormatError("Avro decoding requires an Avro schema")
        if not isinstance(avro_schema, AvroSchema):
            avro_schema = parse_avro_schema(avro_schema)
        self.avro_schema = avro_schema
        self.schema = schema or avro_schema.to_engine_schema()
        self._rows: list[bytes] = []
        self._native = None
        if use_native:
            try:
                from denormalized_tpu.formats.native_avro import (
                    NativeAvroParser,
                )

                self._native = NativeAvroParser(avro_schema, self.schema)
            except Exception:
                self._native = None

    def push(self, payload: bytes) -> None:
        if payload:
            self._rows.append(payload)

    def flush(self) -> RecordBatch:
        rows, self._rows = self._rows, []
        if self._native is not None:
            return self._native.parse(rows)
        objs = [decode_record(self.avro_schema, r) for r in rows]
        return rows_to_batch(objs, self.schema)
