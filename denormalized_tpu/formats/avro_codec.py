"""Avro binary payloads ⇄ columnar batches.

Mirror of the reference's Avro pipeline: schema-declaration parsing and
recursive Avro→engine-schema conversion (formats/decoders/utils.rs:14
``to_arrow_schema``, which defers to DataFusion's avro_to_arrow recursive
schema converter), the ``AvroDecoder`` (formats/decoders/avro.rs:11-54),
and the value⇄JSON bridges in utils/arrow_helpers.rs:52-126.  Implemented
from the Avro 1.11 binary spec (zigzag varints, length-prefixed bytes,
union-by-index, block-encoded arrays/maps) — the image ships no avro
library.  An encoder is included so tests can produce real Avro bytes
(the reference tests do the same with apache-avro, decoders/avro.rs:56-159).

Supported (round-4: full recursive coverage):
  - primitives null/boolean/int/long/float/double/string/bytes
  - logical type timestamp-millis / local-timestamp-millis
  - records nested to any depth  → engine STRUCT columns
  - arrays (block-encoded, negative block counts) → engine LIST columns
  - maps with string keys        → engine STRUCT columns (dynamic keys:
    decoded as plain dicts; no per-key child fields)
  - enums → engine STRING (symbol name), fixed → raw bytes
  - named-type references (a record/enum/fixed may be referenced by name,
    including namespace-qualified, after its definition)
  - unions: ``["null", T]`` (either order) is the nullable sugar; general
    multi-branch unions decode by branch index, and convert to an engine
    dtype only when all non-null branches share one engine dtype.

The native one-pass parser (avro_parser.cpp) decodes flat records AND
nested records/arrays (of primitives, records, or arrays, to any depth)
via its schema-tree ABI; :class:`AvroDecoder` routes the remaining shapes
(maps, enums, fixed, bytes fields, general unions, recursive named types)
to this recursive pure-Python decoder — defined fallback, not an error,
and counted in ``decode_fallback_rows`` so it is observable.
"""

from __future__ import annotations

import io
import json
import struct

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.formats import Decoder, _warn_native_unavailable
from denormalized_tpu.formats.json_codec import rows_to_batch

_PRIMITIVE = {
    "boolean": DataType.BOOL,
    "int": DataType.INT32,
    "long": DataType.INT64,
    "float": DataType.FLOAT32,
    "double": DataType.FLOAT64,
    "string": DataType.STRING,
    "bytes": DataType.STRING,
}

_PRIMITIVE_NAMES = frozenset(
    ("null", "boolean", "int", "long", "float", "double", "string", "bytes")
)


def parse_avro_schema(decl: str | dict) -> "AvroSchema":
    if isinstance(decl, str):
        decl = json.loads(decl)
    return AvroSchema(decl)


def _fullname(decl: dict, enclosing_ns: str | None) -> tuple[str, str | None]:
    """(fullname, namespace) per Avro spec §names."""
    name = decl.get("name")
    if not name:
        raise FormatError(f"named Avro type missing 'name': {decl!r}")
    if "." in name:
        ns, _, short = name.rpartition(".")
        return name, ns
    ns = decl.get("namespace", enclosing_ns)
    return (f"{ns}.{name}" if ns else name), ns


class AvroSchema:
    """Parsed + resolved Avro schema.

    ``self.fields`` is a list of ``(name, resolved_type, nullable)`` for the
    top-level record — the shape the native parser and the encoder consume.
    A *resolved type* is one of:
      - a primitive name string ("long", "string", …)
      - a dict with resolved children: record (fields as the same triple
        list under "_fields"), array ("items" resolved), map ("values"
        resolved), enum, fixed, or a logical-type annotated primitive
      - a list of resolved branches (general union, kept in branch order)
    Named-type references are resolved during parsing; unknown names raise.
    """

    def __init__(self, decl: dict):
        if isinstance(decl, str):
            decl = json.loads(decl)
        if not (isinstance(decl, dict) and decl.get("type") == "record"):
            raise FormatError("top-level Avro schema must be a record")
        self.decl = decl
        self._named: dict[str, object] = {}
        resolved = self._resolve(decl, None)
        self.fields: list[tuple[str, object, bool]] = [
            (n, t, nb) for n, t, nb in resolved["_fields"]
        ]

    # -- schema resolution -------------------------------------------------

    def _resolve(self, t, ns):
        """Recursively resolve an Avro type declaration (see class doc)."""
        if isinstance(t, str):
            if t in _PRIMITIVE_NAMES:
                return t
            # named reference — try qualified then bare
            for key in ((f"{ns}.{t}" if ns and "." not in t else t), t):
                if key in self._named:
                    return self._named[key]
            raise FormatError(f"unknown Avro type name {t!r}")
        if isinstance(t, list):
            branches = [self._resolve(b, ns) for b in t]
            if len(branches) < 2:
                raise FormatError(f"Avro union needs >= 2 branches: {t!r}")
            return branches
        if not isinstance(t, dict):
            raise FormatError(f"invalid Avro type declaration {t!r}")
        kind = t.get("type")
        if kind == "record":
            full, inner_ns = _fullname(t, ns)
            out = {"type": "record", "name": full, "_fields": []}
            # register BEFORE resolving fields so recursive types
            # (linked-list style self references) resolve
            self._named[full] = out
            for f in t.get("fields", ()):
                fname = f.get("name")
                if fname is None:
                    raise FormatError(f"record field missing name: {f!r}")
                ftype, nullable = self._field_type(f["type"], inner_ns)
                out["_fields"].append((fname, ftype, nullable))
            return out
        if kind == "array":
            return {"type": "array", "items": self._resolve(t["items"], ns)}
        if kind == "map":
            return {"type": "map", "values": self._resolve(t["values"], ns)}
        if kind == "enum":
            full, _ = _fullname(t, ns)
            symbols = list(t.get("symbols", ()))
            if not symbols:
                raise FormatError(f"Avro enum {full!r} has no symbols")
            out = {"type": "enum", "name": full, "symbols": symbols}
            self._named[full] = out
            return out
        if kind == "fixed":
            full, _ = _fullname(t, ns)
            out = {"type": "fixed", "name": full, "size": int(t["size"])}
            self._named[full] = out
            return out
        if kind in _PRIMITIVE_NAMES or isinstance(kind, (dict, list)):
            # annotated primitive ({"type": "long", "logicalType": ...})
            # or nested type declaration under "type"
            if isinstance(kind, str):
                keep = {k: v for k, v in t.items() if k != "name"}
                return keep
            return self._resolve(kind, ns)
        raise FormatError(f"unsupported Avro type {t!r}")

    def _field_type(self, t, ns) -> tuple[object, bool]:
        """Resolve a field's type; strip the ``[null, T]`` nullable sugar."""
        resolved = self._resolve(t, ns)
        if isinstance(resolved, list):
            non_null = [b for b in resolved if b != "null"]
            if len(resolved) == 2 and len(non_null) == 1:
                # nullable sugar — but branch ORDER still matters on the
                # wire, so remember whether null was branch 0
                if resolved[0] == "null":
                    return non_null[0], True
                # ['T', 'null']: keep the union so decode maps indices
                # correctly; conversion treats it as nullable T
                return resolved, True
            return resolved, any(b == "null" for b in resolved)
        return resolved, False

    # -- engine schema -----------------------------------------------------

    def to_engine_schema(self) -> Schema:
        """Avro → engine schema (to_arrow_schema, decoders/utils.rs:14)."""
        out = []
        for name, t, nullable in self.fields:
            out.append(_avro_field(name, t, nullable, set()))
        return Schema(out)


def _avro_field(name: str, t, nullable: bool, in_progress: frozenset) -> Field:
    dtype, children = _avro_type_to_dtype(t, in_progress)
    return Field(name, dtype, nullable, children=children)


def _avro_type_to_dtype(t, in_progress=frozenset()) -> tuple[DataType, tuple]:
    """Resolved Avro type → (engine DataType, children Fields).

    ``in_progress`` holds record names on the current conversion path: a
    back-reference (self-referential / mutually recursive types, valid
    Avro) can't expand to a finite static child list, so it degrades to a
    childless STRUCT — the host-only dict column, same treatment as maps.
    """
    if isinstance(t, list):  # general union
        non_null = [b for b in t if b != "null"]
        if not non_null:
            raise FormatError("Avro union of only null is not a column type")
        converted = [_avro_type_to_dtype(b, in_progress) for b in non_null]
        first = converted[0]
        # full (dtype, children) equality: two record branches that are
        # both STRUCT but with different fields have no single column
        # schema — guessing the first branch's children would silently
        # hide the other branch's fields
        if all(c == first for c in converted[1:]):
            return first
        # numeric branches widen to the largest member (float dominates
        # int, 64 dominates 32) — the avro_to_arrow-style promotion
        _RANK = {
            DataType.INT32: 0,
            DataType.INT64: 1,
            DataType.TIMESTAMP_MS: 1,
            DataType.FLOAT32: 2,
            DataType.FLOAT64: 3,
        }
        if all(c[0] in _RANK and not c[1] for c in converted):
            widest = max(converted, key=lambda c: _RANK[c[0]])[0]
            if widest is DataType.FLOAT32 or any(
                c[0] in (DataType.FLOAT32, DataType.FLOAT64)
                for c in converted
            ):
                widest = DataType.FLOAT64
            return widest, ()
        raise FormatError(
            f"Avro union branches map to mixed engine dtypes: {t!r}"
        )
    if isinstance(t, dict):
        kind = t.get("type")
        lt = t.get("logicalType")
        if lt in ("timestamp-millis", "local-timestamp-millis"):
            return DataType.TIMESTAMP_MS, ()
        if kind == "record":
            if t["name"] in in_progress:
                return DataType.STRUCT, ()  # back-reference (see docstring)
            inner = in_progress | {t["name"]}
            children = tuple(
                _avro_field(n, ft, nb, inner) for n, ft, nb in t["_fields"]
            )
            return DataType.STRUCT, children
        if kind == "array":
            item_dtype, item_children = _avro_type_to_dtype(
                t["items"], in_progress
            )
            return DataType.LIST, (
                Field("item", item_dtype, True, children=item_children),
            )
        if kind == "map":
            # dynamic string keys: host-only dict column (engine has no MAP
            # dtype; DataFusion maps these to Map<utf8, T> — our STRUCT with
            # no declared children is the object-column equivalent)
            return DataType.STRUCT, ()
        if kind == "enum":
            return DataType.STRING, ()
        if kind == "fixed":
            return DataType.STRING, ()
        t = kind
    if t in _PRIMITIVE:
        return _PRIMITIVE[t], ()
    raise FormatError(f"unsupported Avro type {t!r}")


# -- binary primitives (Avro spec §binary encoding) -----------------------


def _zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise FormatError("truncated Avro varint")
        b = raw[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _read_exact(buf: io.BytesIO, n: int, what: str) -> bytes:
    raw = buf.read(n)
    if len(raw) != n:
        raise FormatError(f"truncated Avro {what}")
    return raw


# -- encoding (tests / sink path) -----------------------------------------


def _union_branch_for(t: list, v) -> int:
    """Pick the union branch to encode ``v`` under (test encoder heuristic:
    null → the null branch, else the first non-null branch)."""
    if v is None:
        for i, b in enumerate(t):
            if b == "null":
                return i
        raise FormatError("null value but union has no null branch")
    for i, b in enumerate(t):
        if b != "null":
            return i
    raise FormatError(f"union {t!r} has no non-null branch")


def encode_value(t, nullable: bool, v, out: bytearray) -> None:
    if isinstance(t, list):  # general union (branch order preserved)
        idx = _union_branch_for(t, v)
        out += _zigzag_encode(idx)
        if t[idx] == "null":
            return
        encode_value(t[idx], False, v, out)
        return
    if nullable:
        if v is None:
            out += _zigzag_encode(0)  # union branch 0 = null
            return
        out += _zigzag_encode(1)
    if v is None:
        raise FormatError("null value for non-nullable Avro field")
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "record":
            for n, ft, nb in t["_fields"]:
                encode_value(ft, nb, (v or {}).get(n), out)
            return
        if kind == "array":
            items = list(v)
            if items:
                out += _zigzag_encode(len(items))
                for item in items:
                    encode_value(t["items"], False, item, out)
            out += _zigzag_encode(0)
            return
        if kind == "map":
            entries = dict(v)
            if entries:
                out += _zigzag_encode(len(entries))
                for k, mv in entries.items():
                    raw = str(k).encode()
                    out += _zigzag_encode(len(raw))
                    out += raw
                    encode_value(t["values"], False, mv, out)
            out += _zigzag_encode(0)
            return
        if kind == "enum":
            try:
                out += _zigzag_encode(t["symbols"].index(v))
            except ValueError:
                raise FormatError(
                    f"value {v!r} not in enum symbols {t['symbols']}"
                ) from None
            return
        if kind == "fixed":
            raw = bytes(v)
            if len(raw) != t["size"]:
                raise FormatError(
                    f"fixed value of {len(raw)} bytes != size {t['size']}"
                )
            out += raw
            return
        base = kind
    else:
        base = t
    if base == "boolean":
        out.append(1 if v else 0)
    elif base in ("int", "long"):
        out += _zigzag_encode(int(v))
    elif base == "float":
        out += struct.pack("<f", float(v))
    elif base == "double":
        out += struct.pack("<d", float(v))
    elif base in ("string", "bytes"):
        raw = v.encode() if isinstance(v, str) else bytes(v)
        out += _zigzag_encode(len(raw))
        out += raw
    elif base == "null":
        if v is not None:
            raise FormatError("non-null value for Avro null type")
    else:
        raise FormatError(f"unsupported Avro type {t!r}")


# -- decoding --------------------------------------------------------------


def _decode_blocks(buf: io.BytesIO, read_item, what: str):
    """Avro block-encoded sequence: series of counts, 0 terminates; a
    negative count is followed by a byte size (skippable block).

    Counts are capped against the bytes actually remaining in the payload:
    any item of >=1 wire byte makes count <= remaining for valid data, and
    zero-byte items (null / empty-record elements) are allowed a bounded
    slack — without the cap a 5-byte payload declaring 2^30 null items
    would allocate gigabytes off one malicious Kafka message.  The
    per-block cap alone is bypassable by REPEATED blocks of zero-byte
    items, so the record-level cumulative budget (``elem_budget`` on the
    buffer, set by :func:`decode_record`; same formula as the native
    parser) bounds total decoded elements per record too."""
    out = []
    while True:
        count = _zigzag_decode(buf)
        if count == 0:
            return out
        if count < 0:
            count = -count
            _zigzag_decode(buf)  # block byte size — we decode items anyway
        remaining = len(buf.getbuffer()) - buf.tell()
        if count > max(65536, 2 * (remaining + 1)):
            raise FormatError(
                f"Avro {what} block of {count} items exceeds payload "
                f"capacity ({remaining} bytes remain)"
            )
        budget = getattr(buf, "elem_budget", None)
        if budget is not None:
            budget -= count
            if budget < 0:
                raise FormatError(
                    f"Avro {what} blocks exceed the record's cumulative "
                    f"element budget (zero-byte-item bomb)"
                )
            buf.elem_budget = budget
        for _ in range(count):
            out.append(read_item())


def decode_value(t, nullable: bool, buf: io.BytesIO):
    if isinstance(t, list):  # general union: branch by index
        branch = _zigzag_decode(buf)
        if not 0 <= branch < len(t):
            raise FormatError(
                f"invalid union branch {branch} for {len(t)}-branch union"
            )
        b = t[branch]
        if b == "null":
            return None
        return decode_value(b, False, buf)
    if nullable:
        branch = _zigzag_decode(buf)
        if branch == 0:
            return None
        if branch != 1:
            raise FormatError(
                f"invalid union branch {branch} (only ['null', T])"
            )
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "record":
            return {
                n: decode_value(ft, nb, buf) for n, ft, nb in t["_fields"]
            }
        if kind == "array":
            return _decode_blocks(
                buf, lambda: decode_value(t["items"], False, buf), "array"
            )
        if kind == "map":
            def _entry():
                klen = _zigzag_decode(buf)
                if klen < 0:
                    raise FormatError("negative Avro map-key length")
                k = _read_exact(buf, klen, "map key").decode(errors="replace")
                return k, decode_value(t["values"], False, buf)

            return dict(_decode_blocks(buf, _entry, "map"))
        if kind == "enum":
            idx = _zigzag_decode(buf)
            symbols = t["symbols"]
            if not 0 <= idx < len(symbols):
                raise FormatError(
                    f"Avro enum index {idx} out of range ({len(symbols)})"
                )
            return symbols[idx]
        if kind == "fixed":
            return _read_exact(buf, t["size"], "fixed")
        base = kind
    else:
        base = t
    if base == "boolean":
        return _read_exact(buf, 1, "boolean") == b"\x01"
    if base in ("int", "long"):
        return _zigzag_decode(buf)
    if base == "float":
        return struct.unpack("<f", _read_exact(buf, 4, "float"))[0]
    if base == "double":
        return struct.unpack("<d", _read_exact(buf, 8, "double"))[0]
    if base in ("string", "bytes"):
        n = _zigzag_decode(buf)
        if n < 0:
            raise FormatError("negative Avro string length")
        raw = _read_exact(buf, n, "string")
        # errors='replace' matches the native parser: invalid UTF-8 becomes
        # U+FFFD rather than an exception class the reader's per-record
        # salvage doesn't catch
        return raw.decode(errors="replace") if base == "string" else raw
    if base == "null":
        return None
    raise FormatError(f"unsupported Avro type {t!r}")


def encode_record(schema: AvroSchema, record: dict) -> bytes:
    out = bytearray()
    for name, t, nullable in schema.fields:
        encode_value(t, nullable, record.get(name), out)
    return bytes(out)


class _RecordBuf(io.BytesIO):
    """BytesIO + the record-level cumulative element budget slot (builtin
    BytesIO rejects attribute assignment)."""

    elem_budget: int = 0


def decode_record(schema: AvroSchema, payload: bytes) -> dict:
    buf = _RecordBuf(payload)
    # same cumulative bound as the native parser (avro_parser.cpp
    # ap_parse): decoded array elements per record <= max(64Ki, 4x wire
    # bytes) — callers that build a plain BytesIO (tests, direct
    # decode_value use) simply skip the cumulative check
    buf.elem_budget = max(65536, 4 * len(payload))
    out = {
        name: decode_value(t, nullable, buf)
        for name, t, nullable in schema.fields
    }
    if buf.read(1):
        # same contract as the native parser: trailing bytes after the last
        # field mean a corrupt record or a mismatched schema
        raise FormatError("trailing bytes after Avro record")
    return out


class AvroDecoder(Decoder):
    """Buffer Avro-encoded records; flush one batch.

    Decode is native (C++ one-pass columnar, avro_parser.cpp — mirroring
    the reference's Rust-native path) for flat records AND nested
    records/arrays via the schema-tree ABI; the shapes the native walker
    declines (maps, enums, fixed, bytes fields, general unions, recursive
    named types) route to the recursive pure-Python decoder, which is
    also the no-compiler fallback and the differential-test oracle.
    ``decode_fallback_rows`` counts the rows that actually decoded on the
    Python path, so a schema silently routed there is observable in
    source metrics."""

    def __init__(self, schema: Schema | None, avro_schema, use_native=True):
        if avro_schema is None:
            raise FormatError("Avro decoding requires an Avro schema")
        if not isinstance(avro_schema, AvroSchema):
            avro_schema = parse_avro_schema(avro_schema)
        self.avro_schema = avro_schema
        self.schema = schema or avro_schema.to_engine_schema()
        self._rows: list[bytes] = []
        self._native = None
        self.decode_fallback_rows = 0
        if use_native:
            try:
                from denormalized_tpu.formats.native_avro import (
                    NativeAvroParser,
                )

                self._native = NativeAvroParser(avro_schema, self.schema)
            except Exception as e:  # dnzlint: allow(broad-except) pure-Python decode is the designed fallback (no compiler / unsupported schema shape); the downgrade is logged once and counted in decode_fallback_rows, and test_native_build_gate fails images where the build should work
                _warn_native_unavailable("Avro", e)
                self._native = None

    def push(self, payload: bytes) -> None:
        if payload:
            self._rows.append(payload)

    def flush(self) -> RecordBatch:
        rows, self._rows = self._rows, []
        if self._native is not None:
            return self._native.parse(rows)
        self.decode_fallback_rows += len(rows)
        objs = [decode_record(self.avro_schema, r) for r in rows]
        return rows_to_batch(objs, self.schema)
