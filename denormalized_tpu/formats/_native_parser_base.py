"""Shared ctypes plumbing for the native columnar parsers (JSON, Avro).

Both C++ parsers expose the same column-oriented ABI behind a prefix
(``jp_`` / ``ap_``): create/destroy/clear/parse/error/nrows plus per-column
getters.  This module owns the signature setup and the parse/extract loop so
the two wrappers can't drift (e.g. null-mask materialization or the
``errors='replace'`` string decode — invalid bytes become U+FFFD so a weird
payload can never crash the reader — live in exactly one place)."""

from __future__ import annotations

import ctypes

import numpy as np

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema


def configure_lib(lib, prefix: str, create_argtypes: list) -> None:
    """Set ctypes signatures for one parser library (idempotent)."""
    flag = f"_{prefix}_configured"
    if getattr(lib, flag, False):
        return
    g = lambda name: getattr(lib, f"{prefix}_{name}")  # noqa: E731
    g("create").restype = ctypes.c_void_p
    g("create").argtypes = create_argtypes
    g("parse").restype = ctypes.c_int
    g("parse").argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,  # bytes or a raw pointer into a native buffer
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    g("error").restype = ctypes.c_char_p
    g("error").argtypes = [ctypes.c_void_p]
    g("nrows").restype = ctypes.c_uint64
    g("nrows").argtypes = [ctypes.c_void_p]
    for fn, restype in (
        ("col_i64", ctypes.POINTER(ctypes.c_int64)),
        ("col_f64", ctypes.POINTER(ctypes.c_double)),
        ("col_bool", ctypes.POINTER(ctypes.c_uint8)),
        ("col_valid", ctypes.POINTER(ctypes.c_uint8)),
        ("col_str_offsets", ctypes.POINTER(ctypes.c_uint64)),
    ):
        g(fn).restype = restype
        g(fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("col_str_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
    g("col_str_bytes").argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    # dictionary-encoded string export (tolerate a stale .so without the
    # symbols — the wrapper falls back to the per-row decode loop); the
    # capability is probed ONCE here, not per batch in the parse loop
    setattr(
        lib, f"_{prefix}_has_str_dict", hasattr(lib, f"{prefix}_col_str_dict")
    )
    if getattr(lib, f"_{prefix}_has_str_dict"):
        g("col_str_dict").restype = ctypes.c_int64
        g("col_str_dict").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_codes").restype = ctypes.POINTER(ctypes.c_int32)
        g("col_str_dict_codes").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
        g("col_str_dict_bytes").argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        g("col_str_dict_offsets").restype = ctypes.POINTER(ctypes.c_uint64)
        g("col_str_dict_offsets").argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("clear").argtypes = [ctypes.c_void_p]
    g("destroy").argtypes = [ctypes.c_void_p]
    setattr(lib, flag, True)


class ColumnarNativeParser:
    """Base wrapper: subclasses set ``_libref``, ``_h``, ``_prefix``,
    ``schema`` and ``_kinds`` ('i64'|'f64'|'bool'|'str' per column)."""

    schema: Schema
    _kinds: list[str]
    _prefix: str

    def _fn(self, name: str):
        return getattr(self._libref, f"{self._prefix}_{name}")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._fn("destroy")(h)
            self._h = None

    def parse(self, rows: list[bytes]) -> RecordBatch:
        n = len(rows)
        if n == 0:
            return RecordBatch.empty(self.schema)
        data = b"".join(rows)
        offsets = np.zeros(n + 1, dtype=np.uint64)
        offsets[1:] = np.cumsum([len(r) for r in rows], dtype=np.uint64)
        return self.parse_ptr(
            data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )

    def parse_ptr(self, data, offsets_ptr, n: int) -> RecordBatch:
        """Zero-copy entry: ``data`` may be a bytes object OR a raw ctypes
        pointer into another native component's buffer (e.g. the Kafka
        client's fetch arena) — payload bytes never become Python
        objects."""
        self._fn("clear")(self._h)
        rc = self._fn("parse")(self._h, data, offsets_ptr, n)
        if rc != 0:
            raise FormatError(self._fn("error")(self._h).decode())
        cols, masks = [], []
        for ci, f in enumerate(self.schema):
            valid = np.ctypeslib.as_array(
                self._fn("col_valid")(self._h, ci), shape=(n,)
            ).astype(bool)
            kind = self._kinds[ci]
            if kind == "i64":
                arr = np.ctypeslib.as_array(
                    self._fn("col_i64")(self._h, ci), shape=(n,)
                ).astype(f.dtype.to_numpy(), copy=True)
            elif kind == "f64":
                arr = np.ctypeslib.as_array(
                    self._fn("col_f64")(self._h, ci), shape=(n,)
                ).astype(f.dtype.to_numpy(), copy=True)
            elif kind == "bool":
                arr = np.ctypeslib.as_array(
                    self._fn("col_bool")(self._h, ci), shape=(n,)
                ).astype(bool)
            elif (
                getattr(self._libref, f"_{self._prefix}_has_str_dict", False)
                and (
                    n_uniq := int(self._fn("col_str_dict")(self._h, ci))
                ) >= 0
            ):
                # dictionary path (native dedupe, str_dict.hpp): decode
                # each DISTINCT value once, fan out with one vectorized
                # take — the per-row slice+decode loop below was the
                # dominant host cost of the Kafka ingest path.  n_uniq < 0
                # = high-cardinality bail-out (dict would cost more than
                # the direct loop).
                codes = np.ctypeslib.as_array(
                    self._fn("col_str_dict_codes")(self._h, ci), shape=(n,)
                )
                nb = ctypes.c_uint64()
                bptr = self._fn("col_str_dict_bytes")(
                    self._h, ci, ctypes.byref(nb)
                )
                raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
                offs = np.ctypeslib.as_array(
                    self._fn("col_str_dict_offsets")(self._h, ci),
                    shape=(n_uniq + 1,),
                )
                uniq = np.empty(n_uniq, dtype=object)
                for i in range(n_uniq):
                    uniq[i] = raw[offs[i] : offs[i + 1]].decode(
                        errors="replace"
                    )
                arr = uniq[codes]
            else:
                nb = ctypes.c_uint64()
                bptr = self._fn("col_str_bytes")(
                    self._h, ci, ctypes.byref(nb)
                )
                raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
                offs = np.ctypeslib.as_array(
                    self._fn("col_str_offsets")(self._h, ci), shape=(n + 1,)
                )
                arr = np.empty(n, dtype=object)
                for i in range(n):
                    arr[i] = raw[offs[i] : offs[i + 1]].decode(
                        errors="replace"
                    )
            cols.append(arr)
            masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)
