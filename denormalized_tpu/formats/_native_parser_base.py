"""Shared ctypes plumbing for the native columnar parsers (JSON, Avro).

Both C++ parsers expose the same column-oriented ABI behind a prefix
(``jp_`` / ``ap_``): create/destroy/clear/parse/error/nrows plus per-column
getters.  This module owns the signature setup and the parse/extract loop so
the two wrappers can't drift (e.g. null-mask materialization or the
``errors='replace'`` string decode — invalid bytes become U+FFFD so a weird
payload can never crash the reader — live in exactly one place).

Nested schemas (the reference's arrow-json/avro readers handle nested
structs/lists natively — decoders/json.rs:11-49, decoders/avro.rs:11-54)
ride the SHREDDED node-tree ABI: the C++ side parses nested values into
typed leaf columns plus struct-presence bytes and Arrow-style list
(offsets, values, elem-validity) triples; :class:`NodeDesc` mirrors that
tree here, and ``_extract_tree`` reassembles the engine's host
representation (object arrays of dicts/lists) from the leaves — no
per-row ``json.loads``, no DOM."""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field as dc_field

import numpy as np

from denormalized_tpu.common.columns import (
    NestedColumn,
    PrimitiveColumn,
    StringColumn,
    _compile_fused_builder,  # fused builder shared with the lazy assembly
    columnar_strings_enabled,
)
from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema


def configure_lib(lib, prefix: str, create_argtypes: list) -> None:
    """Set ctypes signatures for one parser library (idempotent)."""
    flag = f"_{prefix}_configured"
    if getattr(lib, flag, False):
        return
    g = lambda name: getattr(lib, f"{prefix}_{name}")  # noqa: E731
    g("create").restype = ctypes.c_void_p
    g("create").argtypes = create_argtypes
    g("parse").restype = ctypes.c_int
    g("parse").argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,  # bytes or a raw pointer into a native buffer
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    g("error").restype = ctypes.c_char_p
    g("error").argtypes = [ctypes.c_void_p]
    g("nrows").restype = ctypes.c_uint64
    g("nrows").argtypes = [ctypes.c_void_p]
    for fn, restype in (
        ("col_i64", ctypes.POINTER(ctypes.c_int64)),
        ("col_f64", ctypes.POINTER(ctypes.c_double)),
        ("col_bool", ctypes.POINTER(ctypes.c_uint8)),
        ("col_valid", ctypes.POINTER(ctypes.c_uint8)),
        ("col_str_offsets", ctypes.POINTER(ctypes.c_uint64)),
    ):
        g(fn).restype = restype
        g(fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("col_str_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
    g("col_str_bytes").argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    # dictionary-encoded string export (tolerate a stale .so without the
    # symbols — the wrapper falls back to the per-row decode loop); the
    # capability is probed ONCE here, not per batch in the parse loop
    setattr(
        lib, f"_{prefix}_has_str_dict", hasattr(lib, f"{prefix}_col_str_dict")
    )
    if getattr(lib, f"_{prefix}_has_str_dict"):
        g("col_str_dict").restype = ctypes.c_int64
        g("col_str_dict").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_codes").restype = ctypes.POINTER(ctypes.c_int32)
        g("col_str_dict_codes").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
        g("col_str_dict_bytes").argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        g("col_str_dict_offsets").restype = ctypes.POINTER(ctypes.c_uint64)
        g("col_str_dict_offsets").argtypes = [ctypes.c_void_p, ctypes.c_int]
    # node-tree (nested) accessors — present on parsers that support the
    # shredded ABI; probed once like the dict symbols above
    setattr(
        lib, f"_{prefix}_has_tree", hasattr(lib, f"{prefix}_col_list_offsets")
    )
    if getattr(lib, f"_{prefix}_has_tree"):
        g("col_list_offsets").restype = ctypes.POINTER(ctypes.c_uint64)
        g("col_list_offsets").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_list_evalid").restype = ctypes.POINTER(ctypes.c_uint8)
        g("col_list_evalid").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_list_nelems").restype = ctypes.c_uint64
        g("col_list_nelems").argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("clear").argtypes = [ctypes.c_void_p]
    g("destroy").argtypes = [ctypes.c_void_p]
    setattr(lib, flag, True)


# natural (widest) numpy dtype per parser kind — nested python values are
# materialized at this width; INT32-declared leaves additionally clamp at
# i32 bounds (below), everything else keeps the parser's width
_NATURAL_DTYPE = {
    "i64": np.int64,
    "f64": np.float64,
    "bool": bool,
    "str": object,
}

_I32_MIN, _I32_MAX = -(2**31), 2**31 - 1

# natural storage dtype per nested-leaf kind on the columnar path (bool
# stays u8 — pyassemble's type-2 reads bytes)
_PRIM_NP = {"i64": np.int64, "f64": np.float64, "bool": np.uint8}


def _clamp_nested_ints(vals, field: Field):
    """Saturate an int64 ndarray of nested-leaf values at the DECLARED
    width.  Nested leaves live in object columns (no numpy narrowing), so
    this clamp is the only place the declared i32 width is enforced —
    mirrored by ``json_codec._normalize_nested`` on the Python path."""
    if field.dtype is DataType.INT32:
        return np.clip(vals, _I32_MIN, _I32_MAX)
    return vals


def _pyassemble():
    """The C-level row assembler, shared with the lazy sink-boundary
    materialization (see :func:`denormalized_tpu.common.columns._pyassemble`
    — one loader, one fallback policy)."""
    from denormalized_tpu.common import columns

    return columns._pyassemble()


_PA_SCALAR_CODE = {"i64": 0, "f64": 1, "bool": 2, "str": 3}


@dataclass
class NodeDesc:
    """One node of the shredded schema tree, mirroring the C++ side.

    ``kind``: 'i64' | 'f64' | 'bool' | 'str' | 'struct' | 'list'.
    For packed scalar lists, ``elem_kind`` is the scalar element kind;
    generic lists (struct/list elements) leave it None and carry the
    element subtree as the single entry of ``children``."""

    idx: int
    field: Field
    kind: str
    children: list = dc_field(default_factory=list)
    elem_kind: str | None = None
    # lazily compiled fused row builders, keyed by which sub-structs are
    # all-present in the batch (see _compile_fused_builder)
    fused_builders: dict | None = None




class ColumnarNativeParser:
    """Base wrapper: subclasses set ``_libref``, ``_h``, ``_prefix``,
    ``schema`` and ``_kinds`` ('i64'|'f64'|'bool'|'str' per column)."""

    schema: Schema
    _kinds: list[str]
    _prefix: str

    def _fn(self, name: str):
        return getattr(self._libref, f"{self._prefix}_{name}")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._fn("destroy")(h)
            self._h = None

    def parse(self, rows: list[bytes]) -> RecordBatch:
        n = len(rows)
        if n == 0:
            return RecordBatch.empty(self.schema)
        data = b"".join(rows)
        offsets = np.zeros(n + 1, dtype=np.uint64)
        offsets[1:] = np.cumsum([len(r) for r in rows], dtype=np.uint64)
        return self.parse_ptr(
            data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )

    def parse_ptr(self, data, offsets_ptr, n: int) -> RecordBatch:
        """Zero-copy entry: ``data`` may be a bytes object OR a raw ctypes
        pointer into another native component's buffer (e.g. the Kafka
        client's fetch arena) — payload bytes never become Python
        objects."""
        self._fn("clear")(self._h)
        rc = self._fn("parse")(self._h, data, offsets_ptr, n)
        if rc != 0:
            raise FormatError(self._fn("error")(self._h).decode())
        tree = getattr(self, "_tree", None)
        columnar = columnar_strings_enabled()
        if tree is not None:
            return self._extract_tree(tree, n, columnar)
        cols, masks = [], []
        for ci, f in enumerate(self.schema):
            if columnar and self._kinds[ci] == "str":
                # zero-copy handoff: offsets+bytes snapshot into a
                # StringColumn (one bulk memcpy off the parser arena),
                # no per-row str materialization on the decode path
                col = self._snapshot_string(ci, n)
                cols.append(col)
                masks.append(col.validity)
                continue
            arr, valid = self._scalar_arrays(
                ci, self._kinds[ci], n, f.dtype.to_numpy()
            )
            cols.append(arr)
            masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)

    def _scalar_arrays(self, ci: int, kind: str, count: int, np_dtype):
        """(values, validity) for one scalar node: ``ci`` is the C-side
        node index, ``count`` the entry count (nrows for row-level nodes,
        nelems for list elements)."""
        valid = np.ctypeslib.as_array(
            self._fn("col_valid")(self._h, ci), shape=(count,)
        ).astype(bool) if count else np.ones(0, dtype=bool)
        vals = self._scalar_values(ci, kind, count, np_dtype)
        if kind == "str" and not valid.all():
            # masked-out strings materialize as None, matching the Python
            # fallback and the nested reassembly (numeric columns use 0 on
            # both paths; '' here would differ from the fallback's None)
            vals[~valid] = None
        return vals, valid

    def _scalar_values(self, ci: int, kind: str, count: int, np_dtype):
        if count == 0:
            return np.empty(0, dtype=np_dtype if kind != "str" else object)
        if kind == "i64":
            vals = np.ctypeslib.as_array(
                self._fn("col_i64")(self._h, ci), shape=(count,)
            )
            if np.dtype(np_dtype).itemsize < 8:
                # narrowing (INT32 columns): saturate like the i64 parse
                # itself does — astype alone would WRAP out-of-range values
                info = np.iinfo(np_dtype)
                vals = np.clip(vals, info.min, info.max)
            return vals.astype(np_dtype, copy=True)
        if kind == "f64":
            # narrowing to f32 overflows out-of-range values to +-inf —
            # the same result the Python fallback's element assignment
            # produces; the RuntimeWarning is expected, not actionable
            with np.errstate(over="ignore"):
                return np.ctypeslib.as_array(
                    self._fn("col_f64")(self._h, ci), shape=(count,)
                ).astype(np_dtype, copy=True)
        if kind == "bool":
            return np.ctypeslib.as_array(
                self._fn("col_bool")(self._h, ci), shape=(count,)
            ).astype(bool)
        # strings
        if (
            getattr(self._libref, f"_{self._prefix}_has_str_dict", False)
            and (n_uniq := int(self._fn("col_str_dict")(self._h, ci))) >= 0
        ):
            # dictionary path (native dedupe, str_dict.hpp): decode each
            # DISTINCT value once, fan out with one vectorized take — the
            # per-row slice+decode loop below was the dominant host cost
            # of the Kafka ingest path.  n_uniq < 0 = high-cardinality
            # bail-out (dict would cost more than the direct loop).
            codes = np.ctypeslib.as_array(
                self._fn("col_str_dict_codes")(self._h, ci), shape=(count,)
            )
            nb = ctypes.c_uint64()
            bptr = self._fn("col_str_dict_bytes")(
                self._h, ci, ctypes.byref(nb)
            )
            raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
            offs = np.ctypeslib.as_array(
                self._fn("col_str_dict_offsets")(self._h, ci),
                shape=(n_uniq + 1,),
            )
            uniq = np.empty(n_uniq, dtype=object)
            for i in range(n_uniq):
                uniq[i] = raw[offs[i] : offs[i + 1]].decode(errors="replace")
            return uniq[codes]
        nb = ctypes.c_uint64()
        bptr = self._fn("col_str_bytes")(self._h, ci, ctypes.byref(nb))
        raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
        offs = np.ctypeslib.as_array(
            self._fn("col_str_offsets")(self._h, ci), shape=(count + 1,)
        )
        arr = np.empty(count, dtype=object)
        for i in range(count):
            arr[i] = raw[offs[i] : offs[i + 1]].decode(errors="replace")
        return arr

    # -- columnar (zero-copy) snapshots ----------------------------------
    # One bulk copy per buffer off the parser arena into column-owned
    # ndarrays (the parser's buffers die at the next parse/clear); rows
    # materialize lazily at the sink/UDF boundary via Column.as_object.

    def _snapshot_valid(self, idx: int, count: int) -> np.ndarray | None:
        """Copied bool validity for node ``idx``, or None when all-valid."""
        if count == 0:
            return None
        valid = np.ctypeslib.as_array(
            self._fn("col_valid")(self._h, idx), shape=(count,)
        ).astype(bool)
        return None if valid.all() else valid

    def _snapshot_string(
        self, idx: int, count: int, validity: np.ndarray | None = None,
        own_valid: bool = True,
    ) -> StringColumn:
        """StringColumn snapshot of node ``idx``'s offsets+bytes vectors
        (also used for packed str list ELEMENTS, whose validity comes
        from the list node's evalid — pass it via ``validity``)."""
        if count == 0:
            return StringColumn(
                np.zeros(1, dtype=np.int64), np.empty(0, dtype=np.uint8)
            )
        if own_valid:
            validity = self._snapshot_valid(idx, count)
        nb = ctypes.c_uint64()
        bptr = self._fn("col_str_bytes")(self._h, idx, ctypes.byref(nb))
        data = (
            np.frombuffer(ctypes.string_at(bptr, nb.value), dtype=np.uint8)
            if nb.value else np.empty(0, dtype=np.uint8)
        )
        offs = np.ctypeslib.as_array(
            self._fn("col_str_offsets")(self._h, idx), shape=(count + 1,)
        ).astype(np.int64)
        return StringColumn(offs, data, validity)

    def _snapshot_scalar(
        self, idx: int, kind: str, count: int, field: Field | None,
        validity: np.ndarray | None,
    ):
        """PrimitiveColumn/StringColumn snapshot of one scalar node at
        the parser's natural width; declared-INT32 leaves saturate at
        i32 bounds here (the one place the declared width is enforced,
        same as the legacy extraction)."""
        if kind == "str":
            return self._snapshot_string(
                idx, count, validity, own_valid=False
            )
        if count == 0:
            return PrimitiveColumn(
                kind, np.empty(0, dtype=_PRIM_NP[kind]), None
            )
        if kind == "i64":
            view = np.ctypeslib.as_array(
                self._fn("col_i64")(self._h, idx), shape=(count,)
            )
            if field is not None and field.dtype is DataType.INT32:
                vals = np.clip(view, _I32_MIN, _I32_MAX)
            else:
                vals = view.copy()
        elif kind == "f64":
            vals = np.ctypeslib.as_array(
                self._fn("col_f64")(self._h, idx), shape=(count,)
            ).copy()
        else:  # bool, stored u8 (pyassemble type-2 reads bytes)
            vals = np.ctypeslib.as_array(
                self._fn("col_bool")(self._h, idx), shape=(count,)
            ).copy()
        return PrimitiveColumn(kind, vals, validity)

    def _snapshot_node(self, nd: "NodeDesc", count: int):
        """Column snapshot of one shredded node subtree."""
        validity = self._snapshot_valid(nd.idx, count)
        if nd.kind == "struct":
            children = [
                self._snapshot_node(c, count) for c in nd.children
            ]
            return NestedColumn(
                nd.field, "struct", count, children, validity
            )
        if nd.kind == "list":
            offs = (
                np.ctypeslib.as_array(
                    self._fn("col_list_offsets")(self._h, nd.idx),
                    shape=(count + 1,),
                ).astype(np.int64)
                if count else np.zeros(1, dtype=np.int64)
            )
            ne = (
                int(self._fn("col_list_nelems")(self._h, nd.idx))
                if count else 0
            )
            if nd.elem_kind is not None:
                # packed scalar elements: values live in the list node's
                # own vectors, element validity in evalid
                evalid = None
                if ne:
                    ev = np.ctypeslib.as_array(
                        self._fn("col_list_evalid")(self._h, nd.idx),
                        shape=(ne,),
                    ).astype(bool)
                    evalid = None if ev.all() else ev
                efield = (
                    nd.field.children[0] if nd.field.children else None
                )
                elem = self._snapshot_scalar(
                    nd.idx, nd.elem_kind, ne, efield, evalid
                )
            else:
                elem = self._snapshot_node(nd.children[0], ne)
            return NestedColumn(
                nd.field, "list", count, [elem], validity, offs
            )
        return self._snapshot_scalar(
            nd.idx, nd.kind, count, nd.field, validity
        )

    # -- nested (shredded) extraction ------------------------------------

    def _extract_tree(
        self, tree: list, n: int, columnar: bool = False
    ) -> RecordBatch:
        cols, masks = [], []
        for nd in tree:
            if columnar and nd.kind in ("struct", "list"):
                col = self._snapshot_node(nd, n)
                cols.append(col)
                masks.append(col.validity)
                continue
            if columnar and nd.kind == "str":
                col = self._snapshot_string(nd.idx, n)
                cols.append(col)
                masks.append(col.validity)
                continue
            # top-level scalar leaves stay plain ndarrays at the DECLARED
            # dtype, exactly like the flat column path
            if nd.kind in ("struct", "list"):
                vals, valid = self._node_pyvalues(nd, n)
                arr = np.empty(n, dtype=object)
                arr[:] = vals
                cols.append(arr)
                masks.append(None if valid.all() else valid)
            else:
                arr, valid = self._scalar_arrays(
                    nd.idx, nd.kind, n, nd.field.dtype.to_numpy()
                )
                cols.append(arr)
                masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)

    def _assemble_rows_c(self, nd: "NodeDesc", n: int, fn):
        """Assemble one nested column's rows through the C assembler:
        flatten the NodeDesc subtree into the parallel arrays pa_rows
        takes, handing it the parser's OWN buffers (typed leaves,
        presence bytes, list offsets) — the only Python-side
        materialization left is string decode (dict-coded, vectorized)
        and the INT32 nested-leaf clamp, both per COLUMN."""
        types: list[int] = []
        parents: list[int] = []
        names: list[bytes] = []
        datas: list[int | None] = []
        valids: list = []
        offs: list = []
        keep: list = []  # ndarrays that must outlive the call

        def add_scalar_payload(idx, kind, field, node_idx, count, valid_ptr):
            types[idx] = _PA_SCALAR_CODE[kind]
            valids[idx] = valid_arg(valid_ptr, count)
            if kind == "str":
                arr = self._scalar_values(node_idx, "str", count, object)
                keep.append(arr)
                datas[idx] = arr.ctypes.data
            elif count and kind == "i64" and field is not None and (
                field.dtype is DataType.INT32
            ):
                view = np.ctypeslib.as_array(
                    self._fn("col_i64")(self._h, node_idx), shape=(count,)
                )
                clamped = np.clip(view, _I32_MIN, _I32_MAX)
                keep.append(clamped)
                datas[idx] = clamped.ctypes.data
            else:
                getter = {"i64": "col_i64", "f64": "col_f64",
                          "bool": "col_bool"}[kind]
                datas[idx] = ctypes.cast(
                    self._fn(getter)(self._h, node_idx), ctypes.c_void_p
                )

        def valid_arg(valid_ptr, count: int):
            """NULL when every entry is valid — the C walker then skips
            the per-value presence load entirely (the common all-present
            case pays nothing for nullability)."""
            if count == 0:
                return None
            v = np.ctypeslib.as_array(valid_ptr, shape=(count,))
            if v.all():
                return None
            return ctypes.cast(valid_ptr, ctypes.c_void_p)

        def add(node: "NodeDesc", parent: int, count: int) -> None:
            idx = len(types)
            types.append(0)
            parents.append(parent)
            names.append(node.field.name.encode())
            datas.append(None)
            valids.append(None)
            offs.append(None)
            valid_ptr = self._fn("col_valid")(self._h, node.idx)
            if node.kind == "struct":
                types[idx] = 4
                valids[idx] = valid_arg(valid_ptr, count)
                for c in node.children:
                    add(c, idx, count)
            elif node.kind == "list":
                types[idx] = 5
                valids[idx] = valid_arg(valid_ptr, count)
                offs[idx] = ctypes.cast(
                    self._fn("col_list_offsets")(self._h, node.idx),
                    ctypes.c_void_p,
                )
                ne = int(self._fn("col_list_nelems")(self._h, node.idx))
                if node.elem_kind is not None:
                    # packed scalar elements: they live in the list
                    # node's own vectors with evalid as their validity —
                    # synthesized as the single child
                    eidx = len(types)
                    types.append(0)
                    parents.append(idx)
                    names.append(b"item")
                    datas.append(None)
                    valids.append(None)
                    offs.append(None)
                    efield = (
                        node.field.children[0]
                        if node.field.children else None
                    )
                    add_scalar_payload(
                        eidx, node.elem_kind, efield, node.idx, ne,
                        self._fn("col_list_evalid")(self._h, node.idx),
                    )
                else:
                    add(node.children[0], idx, ne)
            else:
                add_scalar_payload(
                    idx, node.kind, node.field, node.idx, count, valid_ptr
                )

        add(nd, -1, n)
        nn = len(types)
        rows = fn(
            nn,
            (ctypes.c_int * nn)(*types),
            (ctypes.c_int * nn)(*parents),
            (ctypes.c_char_p * nn)(*names),
            (ctypes.c_void_p * nn)(*datas),
            (ctypes.c_void_p * nn)(*valids),
            (ctypes.c_void_p * nn)(*offs),
            n,
        )
        del keep  # buffers were only needed during the call
        pres = np.ctypeslib.as_array(
            self._fn("col_valid")(self._h, nd.idx), shape=(n,)
        ).astype(bool)
        return rows, pres

    def _node_pyvalues(self, nd: "NodeDesc", n: int):
        """Python value list (dicts / lists / scalars, None for null) plus
        row-validity for one node — the reassembly of the shredded leaves.
        The C assembler (pyassemble.cpp) does the per-row work when it
        built; otherwise scalar leaves decode once per COLUMN (vectorized
        ``tolist``) and struct rows assemble through compiled dict-literal
        builders, so even the fallback costs a few list comprehensions
        rather than a ``json.loads`` per row."""
        if n and nd.kind in ("struct", "list") and (
            nd.children or nd.elem_kind is not None
        ):
            fn = _pyassemble()
            if fn is not None:
                return self._assemble_rows_c(nd, n, fn)
        if nd.kind == "struct":
            if n == 0:
                return [], np.ones(0, dtype=bool)
            # fuse the whole struct SUBTREE into one generated
            # comprehension: leaf/list value lists and (only when needed)
            # sub-struct presence lists become zip arguments, nested
            # structs become inline dict literals.  The builder is cached
            # per (which sub-structs were all-present) — presence varies
            # by batch, the expression shape only varies with that key.
            atoms: list = []
            key: list[bool] = []

            def gen(node: "NodeDesc") -> tuple[str, np.ndarray]:
                pres = np.ctypeslib.as_array(
                    self._fn("col_valid")(self._h, node.idx), shape=(n,)
                ).astype(bool)
                parts = []
                for c in node.children:
                    if c.kind == "struct" and c.children:
                        cexpr, _ = gen(c)
                    else:
                        ai = len(atoms)
                        atoms.append(self._node_pyvalues(c, n)[0])
                        cexpr = f"a{ai}"
                    parts.append(f"{c.field.name!r}: {cexpr}")
                literal = "{" + ", ".join(parts) + "}"
                if pres.all():
                    key.append(True)
                    return literal, pres
                key.append(False)
                pi = len(atoms)
                atoms.append(pres.tolist())
                return f"({literal} if a{pi} else None)", pres

            if not nd.children:
                pres = np.ctypeslib.as_array(
                    self._fn("col_valid")(self._h, nd.idx), shape=(n,)
                ).astype(bool)
                return [dict() if p else None for p in pres.tolist()], pres
            expr, pres = gen(nd)
            if nd.fused_builders is None:
                nd.fused_builders = {}
            builder = nd.fused_builders.get(tuple(key))
            if builder is None:
                builder = _compile_fused_builder(expr, len(atoms))
                nd.fused_builders[tuple(key)] = builder
            return builder(*atoms), pres
        if nd.kind == "list":
            valid = np.ctypeslib.as_array(
                self._fn("col_valid")(self._h, nd.idx), shape=(n,)
            ).astype(bool) if n else np.ones(0, dtype=bool)
            offs = np.ctypeslib.as_array(
                self._fn("col_list_offsets")(self._h, nd.idx), shape=(n + 1,)
            ).tolist()
            ne = int(self._fn("col_list_nelems")(self._h, nd.idx))
            if nd.elem_kind is not None:
                # packed scalar elements: values live in the list node's
                # own vectors, element validity in evalid
                evals = self._scalar_values(
                    nd.idx, nd.elem_kind, ne, _NATURAL_DTYPE[nd.elem_kind]
                )
                if nd.elem_kind == "i64" and nd.field.children:
                    evals = _clamp_nested_ints(evals, nd.field.children[0])
                elems = evals.tolist()
                if ne:
                    evalid = np.ctypeslib.as_array(
                        self._fn("col_list_evalid")(self._h, nd.idx),
                        shape=(ne,),
                    )
                    if not evalid.all():
                        for i in np.flatnonzero(evalid == 0):
                            elems[i] = None
            else:
                # generic list: the single child node holds one entry per
                # ELEMENT (struct / nested list / scalar subtree) — its
                # reassembled python values ARE the elements, with None
                # already in place for null elements
                elems = self._node_pyvalues(nd.children[0], ne)[0]
            vals = [
                elems[offs[i] : offs[i + 1]] if v else None
                for i, v in enumerate(valid.tolist())
            ]
            return vals, valid
        # python values inside dicts keep the parser's NATURAL width
        # (int64/float64) rather than the declared leaf dtype — json.loads
        # (the fallback) never narrows — EXCEPT declared-INT32 leaves,
        # which saturate at i32 bounds on both decode paths.  Numeric and
        # bool leaves tolist() straight off the C++ buffers (stable for
        # the duration of the extraction) — the astype copy the flat
        # column path makes would be pure overhead here.
        valid = np.ctypeslib.as_array(
            self._fn("col_valid")(self._h, nd.idx), shape=(n,)
        ).astype(bool) if n else np.ones(0, dtype=bool)
        if n == 0:
            return [], valid
        if nd.kind == "i64":
            view = np.ctypeslib.as_array(
                self._fn("col_i64")(self._h, nd.idx), shape=(n,)
            )
            vals = _clamp_nested_ints(view, nd.field).tolist()
        elif nd.kind == "f64":
            vals = np.ctypeslib.as_array(
                self._fn("col_f64")(self._h, nd.idx), shape=(n,)
            ).tolist()
        elif nd.kind == "bool":
            vals = np.ctypeslib.as_array(
                self._fn("col_bool")(self._h, nd.idx), shape=(n,)
            ).view(np.bool_).tolist()
        else:
            vals = self._scalar_values(nd.idx, "str", n, object).tolist()
        if not valid.all():
            for i in np.flatnonzero(~valid):
                vals[i] = None
        return vals, valid
