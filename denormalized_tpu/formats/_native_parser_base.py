"""Shared ctypes plumbing for the native columnar parsers (JSON, Avro).

Both C++ parsers expose the same column-oriented ABI behind a prefix
(``jp_`` / ``ap_``): create/destroy/clear/parse/error/nrows plus per-column
getters.  This module owns the signature setup and the parse/extract loop so
the two wrappers can't drift (e.g. null-mask materialization or the
``errors='replace'`` string decode — invalid bytes become U+FFFD so a weird
payload can never crash the reader — live in exactly one place).

Nested schemas (the reference's arrow-json/avro readers handle nested
structs/lists natively — decoders/json.rs:11-49, decoders/avro.rs:11-54)
ride the SHREDDED node-tree ABI: the C++ side parses nested values into
typed leaf columns plus struct-presence bytes and Arrow-style list
(offsets, values, elem-validity) triples; :class:`NodeDesc` mirrors that
tree here, and ``_extract_tree`` reassembles the engine's host
representation (object arrays of dicts/lists) from the leaves — no
per-row ``json.loads``, no DOM."""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field as dc_field

import numpy as np

from denormalized_tpu.common.errors import FormatError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Field, Schema


def configure_lib(lib, prefix: str, create_argtypes: list) -> None:
    """Set ctypes signatures for one parser library (idempotent)."""
    flag = f"_{prefix}_configured"
    if getattr(lib, flag, False):
        return
    g = lambda name: getattr(lib, f"{prefix}_{name}")  # noqa: E731
    g("create").restype = ctypes.c_void_p
    g("create").argtypes = create_argtypes
    g("parse").restype = ctypes.c_int
    g("parse").argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,  # bytes or a raw pointer into a native buffer
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    g("error").restype = ctypes.c_char_p
    g("error").argtypes = [ctypes.c_void_p]
    g("nrows").restype = ctypes.c_uint64
    g("nrows").argtypes = [ctypes.c_void_p]
    for fn, restype in (
        ("col_i64", ctypes.POINTER(ctypes.c_int64)),
        ("col_f64", ctypes.POINTER(ctypes.c_double)),
        ("col_bool", ctypes.POINTER(ctypes.c_uint8)),
        ("col_valid", ctypes.POINTER(ctypes.c_uint8)),
        ("col_str_offsets", ctypes.POINTER(ctypes.c_uint64)),
    ):
        g(fn).restype = restype
        g(fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("col_str_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
    g("col_str_bytes").argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    # dictionary-encoded string export (tolerate a stale .so without the
    # symbols — the wrapper falls back to the per-row decode loop); the
    # capability is probed ONCE here, not per batch in the parse loop
    setattr(
        lib, f"_{prefix}_has_str_dict", hasattr(lib, f"{prefix}_col_str_dict")
    )
    if getattr(lib, f"_{prefix}_has_str_dict"):
        g("col_str_dict").restype = ctypes.c_int64
        g("col_str_dict").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_codes").restype = ctypes.POINTER(ctypes.c_int32)
        g("col_str_dict_codes").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_str_dict_bytes").restype = ctypes.POINTER(ctypes.c_uint8)
        g("col_str_dict_bytes").argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        g("col_str_dict_offsets").restype = ctypes.POINTER(ctypes.c_uint64)
        g("col_str_dict_offsets").argtypes = [ctypes.c_void_p, ctypes.c_int]
    # node-tree (nested) accessors — present on parsers that support the
    # shredded ABI; probed once like the dict symbols above
    setattr(
        lib, f"_{prefix}_has_tree", hasattr(lib, f"{prefix}_col_list_offsets")
    )
    if getattr(lib, f"_{prefix}_has_tree"):
        g("col_list_offsets").restype = ctypes.POINTER(ctypes.c_uint64)
        g("col_list_offsets").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_list_evalid").restype = ctypes.POINTER(ctypes.c_uint8)
        g("col_list_evalid").argtypes = [ctypes.c_void_p, ctypes.c_int]
        g("col_list_nelems").restype = ctypes.c_uint64
        g("col_list_nelems").argtypes = [ctypes.c_void_p, ctypes.c_int]
    g("clear").argtypes = [ctypes.c_void_p]
    g("destroy").argtypes = [ctypes.c_void_p]
    setattr(lib, flag, True)


# natural (widest) numpy dtype per parser kind — nested python values are
# materialized at this width regardless of the declared leaf dtype
_NATURAL_DTYPE = {
    "i64": np.int64,
    "f64": np.float64,
    "bool": bool,
    "str": object,
}


@dataclass
class NodeDesc:
    """One node of the shredded schema tree, mirroring the C++ side.

    ``kind``: 'i64' | 'f64' | 'bool' | 'str' | 'struct' | 'list'.
    For lists, ``elem_kind`` is the scalar element kind and ``field``'s
    single child declares the element dtype."""

    idx: int
    field: Field
    kind: str
    children: list = dc_field(default_factory=list)
    elem_kind: str | None = None


class ColumnarNativeParser:
    """Base wrapper: subclasses set ``_libref``, ``_h``, ``_prefix``,
    ``schema`` and ``_kinds`` ('i64'|'f64'|'bool'|'str' per column)."""

    schema: Schema
    _kinds: list[str]
    _prefix: str

    def _fn(self, name: str):
        return getattr(self._libref, f"{self._prefix}_{name}")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._fn("destroy")(h)
            self._h = None

    def parse(self, rows: list[bytes]) -> RecordBatch:
        n = len(rows)
        if n == 0:
            return RecordBatch.empty(self.schema)
        data = b"".join(rows)
        offsets = np.zeros(n + 1, dtype=np.uint64)
        offsets[1:] = np.cumsum([len(r) for r in rows], dtype=np.uint64)
        return self.parse_ptr(
            data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n
        )

    def parse_ptr(self, data, offsets_ptr, n: int) -> RecordBatch:
        """Zero-copy entry: ``data`` may be a bytes object OR a raw ctypes
        pointer into another native component's buffer (e.g. the Kafka
        client's fetch arena) — payload bytes never become Python
        objects."""
        self._fn("clear")(self._h)
        rc = self._fn("parse")(self._h, data, offsets_ptr, n)
        if rc != 0:
            raise FormatError(self._fn("error")(self._h).decode())
        tree = getattr(self, "_tree", None)
        if tree is not None:
            return self._extract_tree(tree, n)
        cols, masks = [], []
        for ci, f in enumerate(self.schema):
            arr, valid = self._scalar_arrays(
                ci, self._kinds[ci], n, f.dtype.to_numpy()
            )
            cols.append(arr)
            masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)

    def _scalar_arrays(self, ci: int, kind: str, count: int, np_dtype):
        """(values, validity) for one scalar node: ``ci`` is the C-side
        node index, ``count`` the entry count (nrows for row-level nodes,
        nelems for list elements)."""
        valid = np.ctypeslib.as_array(
            self._fn("col_valid")(self._h, ci), shape=(count,)
        ).astype(bool) if count else np.ones(0, dtype=bool)
        vals = self._scalar_values(ci, kind, count, np_dtype)
        if kind == "str" and not valid.all():
            # masked-out strings materialize as None, matching the Python
            # fallback and the nested reassembly (numeric columns use 0 on
            # both paths; '' here would differ from the fallback's None)
            vals[~valid] = None
        return vals, valid

    def _scalar_values(self, ci: int, kind: str, count: int, np_dtype):
        if count == 0:
            return np.empty(0, dtype=np_dtype if kind != "str" else object)
        if kind == "i64":
            vals = np.ctypeslib.as_array(
                self._fn("col_i64")(self._h, ci), shape=(count,)
            )
            if np.dtype(np_dtype).itemsize < 8:
                # narrowing (INT32 columns): saturate like the i64 parse
                # itself does — astype alone would WRAP out-of-range values
                info = np.iinfo(np_dtype)
                vals = np.clip(vals, info.min, info.max)
            return vals.astype(np_dtype, copy=True)
        if kind == "f64":
            # narrowing to f32 overflows out-of-range values to +-inf —
            # the same result the Python fallback's element assignment
            # produces; the RuntimeWarning is expected, not actionable
            with np.errstate(over="ignore"):
                return np.ctypeslib.as_array(
                    self._fn("col_f64")(self._h, ci), shape=(count,)
                ).astype(np_dtype, copy=True)
        if kind == "bool":
            return np.ctypeslib.as_array(
                self._fn("col_bool")(self._h, ci), shape=(count,)
            ).astype(bool)
        # strings
        if (
            getattr(self._libref, f"_{self._prefix}_has_str_dict", False)
            and (n_uniq := int(self._fn("col_str_dict")(self._h, ci))) >= 0
        ):
            # dictionary path (native dedupe, str_dict.hpp): decode each
            # DISTINCT value once, fan out with one vectorized take — the
            # per-row slice+decode loop below was the dominant host cost
            # of the Kafka ingest path.  n_uniq < 0 = high-cardinality
            # bail-out (dict would cost more than the direct loop).
            codes = np.ctypeslib.as_array(
                self._fn("col_str_dict_codes")(self._h, ci), shape=(count,)
            )
            nb = ctypes.c_uint64()
            bptr = self._fn("col_str_dict_bytes")(
                self._h, ci, ctypes.byref(nb)
            )
            raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
            offs = np.ctypeslib.as_array(
                self._fn("col_str_dict_offsets")(self._h, ci),
                shape=(n_uniq + 1,),
            )
            uniq = np.empty(n_uniq, dtype=object)
            for i in range(n_uniq):
                uniq[i] = raw[offs[i] : offs[i + 1]].decode(errors="replace")
            return uniq[codes]
        nb = ctypes.c_uint64()
        bptr = self._fn("col_str_bytes")(self._h, ci, ctypes.byref(nb))
        raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
        offs = np.ctypeslib.as_array(
            self._fn("col_str_offsets")(self._h, ci), shape=(count + 1,)
        )
        arr = np.empty(count, dtype=object)
        for i in range(count):
            arr[i] = raw[offs[i] : offs[i + 1]].decode(errors="replace")
        return arr

    # -- nested (shredded) extraction ------------------------------------

    def _extract_tree(self, tree: list, n: int) -> RecordBatch:
        cols, masks = [], []
        for nd in tree:
            if nd.kind in ("struct", "list"):
                vals, valid = self._node_pyvalues(nd, n)
                arr = np.empty(n, dtype=object)
                arr[:] = vals
                cols.append(arr)
                masks.append(None if valid.all() else valid)
            else:
                arr, valid = self._scalar_arrays(
                    nd.idx, nd.kind, n, nd.field.dtype.to_numpy()
                )
                cols.append(arr)
                masks.append(None if valid.all() else valid)
        return RecordBatch(self.schema, cols, masks)

    def _node_pyvalues(self, nd: "NodeDesc", n: int):
        """Python value list (dicts / lists / scalars, None for null) plus
        row-validity for one node — the reassembly of the shredded leaves.
        Scalar leaves decode once per COLUMN (vectorized ``tolist``), so
        a nested batch costs a few list comprehensions rather than a
        ``json.loads`` per row."""
        if nd.kind == "struct":
            pres = np.ctypeslib.as_array(
                self._fn("col_valid")(self._h, nd.idx), shape=(n,)
            ).astype(bool) if n else np.ones(0, dtype=bool)
            names = [c.field.name for c in nd.children]
            kid_vals = [self._node_pyvalues(c, n)[0] for c in nd.children]
            vals = [
                dict(zip(names, t)) if p else None
                for p, t in zip(pres.tolist(), zip(*kid_vals))
            ] if nd.children else [dict() if p else None for p in pres]
            return vals, pres
        if nd.kind == "list":
            valid = np.ctypeslib.as_array(
                self._fn("col_valid")(self._h, nd.idx), shape=(n,)
            ).astype(bool) if n else np.ones(0, dtype=bool)
            offs = np.ctypeslib.as_array(
                self._fn("col_list_offsets")(self._h, nd.idx), shape=(n + 1,)
            ).tolist()
            ne = int(self._fn("col_list_nelems")(self._h, nd.idx))
            elems = self._scalar_values(
                nd.idx, nd.elem_kind, ne, _NATURAL_DTYPE[nd.elem_kind]
            ).tolist()
            if ne:
                evalid = np.ctypeslib.as_array(
                    self._fn("col_list_evalid")(self._h, nd.idx), shape=(ne,)
                )
                if not evalid.all():
                    for i in np.flatnonzero(evalid == 0):
                        elems[i] = None
            vals = [
                elems[offs[i] : offs[i + 1]] if v else None
                for i, v in enumerate(valid.tolist())
            ]
            return vals, valid
        # python values inside dicts keep the parser's NATURAL width
        # (int64/float64) rather than the declared leaf dtype — json.loads
        # (the fallback) never narrows, and silently wrapping an
        # out-of-range int through int32 would corrupt data
        arr, valid = self._scalar_arrays(
            nd.idx, nd.kind, n, _NATURAL_DTYPE[nd.kind]
        )
        vals = arr.tolist()
        if not valid.all():
            for i in np.flatnonzero(~valid):
                vals[i] = None
        return vals, valid
