"""Runtime lock-order witness — the dynamic companion to dnzlint's
static lock pass.

The static pass (``tools/dnzlint``, DNZ-L001) proves ordering over the
call edges it can resolve; everything it can't — callbacks, loops driven
by queue items, code paths only a chaos plan reaches — is covered here,
the way TSan's deadlock detector or the kernel's lockdep do it: observe
the REAL acquisition order at runtime and assert it stays a consistent
partial order.

Mechanism
---------
:func:`install` replaces ``threading.Lock``/``threading.RLock`` with
factories that wrap locks **created by engine code** (caller filename
under ``denormalized_tpu/``) in a recording proxy; everything else
(stdlib, jax, numpy) gets the real thing and zero overhead.  Like
lockdep, ordering is tracked per lock *class* — the creation site
``file:line`` — so two instances of ``PrefetchWorker._swap_lock`` are
one node and an ABBA between two *instances* of two classes is still
caught.

On every successful acquire, for each lock class already held by the
thread, the witness records the edge ``held -> acquired`` together with
both acquisition stacks.  If the REVERSE edge was ever observed (any
thread, any time earlier in the process), that is a lock-order
violation: two code paths disagree about the global order, which is a
deadlock waiting for the right interleaving.  The violation report
carries both conflicting edges WITH both sides' stacks — the two code
paths a human needs to look at, without having to reproduce the hang.

Intentional non-goals: same-class edges (a lock class nested inside
itself is recursion/reentrancy, judged by dnzlint's self-edge rule, not
order); blocking-vs-try-lock distinction (a ``timeout=`` acquire that
succeeded still participates in ordering); cross-thread hand-off of a
plain ``Lock`` (thread A acquires, thread B releases) — held lists are
thread-local, so a hand-off would strand A's entry and mint false edges.
The engine uses ``Semaphore`` for its hand-offs (prefetch slots), which
the witness deliberately does not wrap; if a Lock hand-off ever appears,
wrap that release in ``witness-exempt`` plumbing rather than teaching
the witness about ownership transfer.

Enabled for the whole tier-1 run by ``tests/conftest.py`` (opt out with
``DENORMALIZED_LOCK_WITNESS=0``); the run fails if any violation was
recorded.  Tests that *construct* inversions on purpose use an isolated
:class:`Witness` via :func:`scoped` so the global record stays clean.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager

# the real factories, captured at import — install() swaps the public
# names, the witness itself must keep allocating raw locks
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_PKG_MARKER = os.sep + "denormalized_tpu" + os.sep
_OWN_FILE = os.path.abspath(__file__)


def _caller_site(depth: int = 2) -> str | None:
    """``file:line`` of the frame that called the lock factory, or None
    when it isn't engine code (those locks stay unwrapped)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover — shallower stack than expected
        return None
    fname = frame.f_code.co_filename
    if os.path.abspath(fname) == _OWN_FILE:
        return None  # the witness's own bookkeeping lock
    if _PKG_MARKER not in fname:
        return None
    short = fname.split(_PKG_MARKER, 1)[-1]
    return f"denormalized_tpu/{short}:{frame.f_lineno}"


def _stack(limit: int = 14) -> list[str]:
    """Compact acquisition stack with the witness's own frames dropped.

    A raw ``sys._getframe`` walk, NOT ``traceback.extract_stack``: the
    latter reads source lines through linecache, and this runs on EVERY
    witnessed acquire for the whole tier-1 session — the witness must
    observe the run, not tax it."""
    out: list[str] = []
    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover
        return out
    while f is not None and len(out) < limit:
        code = f.f_code
        if os.path.abspath(code.co_filename) != _OWN_FILE:
            out.append(f"{code.co_filename}:{f.f_lineno} in {code.co_name}")
        f = f.f_back
    out.reverse()
    return out


class Violation:
    """One observed order inversion: ``first`` saw a->b, ``second`` saw
    b->a.  Each side carries (thread name, stack-of-held, stack-of-new)."""

    def __init__(self, edge_ab, first, edge_ba, second):
        self.edge_first = edge_ab  # (site_a, site_b)
        self.first = first
        self.edge_second = edge_ba
        self.second = second

    def render(self) -> str:
        a, b = self.edge_first
        lines = [
            f"lock-order violation: {a} and {b} acquired in both orders",
            f"  order {a} -> {b} (thread {self.first[0]}):",
            f"    holding {a}, acquired at:",
        ]
        lines += [f"      {ln}" for ln in self.first[1][-6:]]
        lines += [f"    then took {b} at:"]
        lines += [f"      {ln}" for ln in self.first[2][-6:]]
        lines += [
            f"  order {b} -> {a} (thread {self.second[0]}):",
            f"    holding {b}, acquired at:",
        ]
        lines += [f"      {ln}" for ln in self.second[1][-6:]]
        lines += [f"    then took {a} at:"]
        lines += [f"      {ln}" for ln in self.second[2][-6:]]
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"<Violation {self.edge_first} vs {self.edge_second}>"


class Witness:
    """Edge store + violation log.  All mutation happens under a private
    RAW lock, taken only AFTER the target lock was acquired (and during
    release bookkeeping) — the witness can observe deadlocks, never cause
    them."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        #: (site_a, site_b) -> (thread_name, stack_of_a, stack_of_b) —
        #: the FIRST observation of each edge, kept as the evidence base
        self._edges: dict[tuple[str, str], tuple] = {}
        self._violations: list[Violation] = []
        self._tls = threading.local()

    # -- per-thread held list -------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- recording -------------------------------------------------------
    def note_acquire(self, site: str) -> None:
        held = self._held()
        new_stack = _stack()
        tname = threading.current_thread().name
        with self._mu:
            for held_site, held_stack in held:
                if held_site == site:
                    continue  # reentrancy/same-class: not an order fact
                edge = (held_site, site)
                rev = (site, held_site)
                if rev in self._edges:
                    self._violations.append(Violation(
                        rev, self._edges[rev],
                        edge, (tname, held_stack, new_stack),
                    ))
                if edge not in self._edges:
                    self._edges[edge] = (tname, held_stack, new_stack)
        held.append((site, new_stack))

    def note_release(self, site: str) -> None:
        held = self._held()
        # release the most recent matching entry (RLock-style nesting)
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == site:
                del held[i]
                return

    # -- reporting -------------------------------------------------------
    def violations(self) -> list[Violation]:
        with self._mu:
            return list(self._violations)

    def edges(self) -> dict[tuple[str, str], tuple]:
        with self._mu:
            return dict(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()


class WitnessedLock:
    """Recording proxy around a real lock.  Supports the full
    Lock/RLock surface the engine (and stdlib helpers like Condition)
    use: acquire/release, context manager, locked()."""

    __slots__ = ("_inner", "_site", "_witness")

    def __init__(self, inner, site: str, witness: Witness):
        self._inner = inner
        self._site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness.note_acquire(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness.note_release(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # threading.Condition probes _is_owned/_release_save/
        # _acquire_restore via try/except AttributeError to pick the
        # RLock-aware fast path; forward them only when the inner lock
        # really has them (RLock), so a plain Lock keeps Condition's
        # generic fallback.  wait() releasing through _release_save skips
        # witness bookkeeping on purpose: the waiting thread is parked
        # and cannot acquire anything until _acquire_restore returns, so
        # its held entry stays truthful for edge recording.
        if name in ("_is_owned", "_release_save", "_acquire_restore"):
            return getattr(self._inner, name)
        raise AttributeError(name)

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"<WitnessedLock {self._site} {self._inner!r}>"


# -- global install ---------------------------------------------------------

_GLOBAL = Witness()
_installed = False


def witness() -> Witness:
    """The process-global witness (what conftest asserts on)."""
    return _GLOBAL


def _make_factory(real, kind: str):
    def factory():
        site = _caller_site()
        inner = real()
        if site is None:
            return inner
        return WitnessedLock(inner, f"{site} ({kind})", _current())

    factory.__name__ = f"witnessed_{kind.lower()}"
    return factory


# scoped() routing is THREAD-LOCAL: only locks the scoping thread itself
# creates bind the scoped witness.  A background engine thread that
# happens to create a lock while some test is inside a scope must keep
# binding the global witness — otherwise that lock class would report
# into a discarded Witness for the rest of the process and the tier-1
# gate would go blind to it.
_TLS_ACTIVE = threading.local()


def _current() -> Witness:
    return getattr(_TLS_ACTIVE, "w", None) or _GLOBAL


def install() -> None:
    """Patch the ``threading`` lock factories (idempotent).  Only locks
    subsequently CREATED by engine code are witnessed — module-level
    engine locks are covered because conftest installs before the engine
    imports."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _make_factory(_REAL_LOCK, "Lock")
    threading.RLock = _make_factory(_REAL_RLOCK, "RLock")


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK


@contextmanager
def scoped():
    """Route THIS THREAD's lock creations into a fresh, isolated
    :class:`Witness` — for tests that build deliberate inversions
    without dirtying the global record.  Locks created by other threads
    (or before the scope) keep reporting to whichever witness they bound
    at creation; per-witness held lists are disjoint, so records stay
    coherent."""
    prev = getattr(_TLS_ACTIVE, "w", None)
    w = Witness()
    _TLS_ACTIVE.w = w
    try:
        yield w
    finally:
        _TLS_ACTIVE.w = prev
