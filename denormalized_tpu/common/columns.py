"""Arrow-style column representations: strings and nested values stay
columnar from parser buffer to operator, exchange frame, and spill block.

The reference keeps data in Arrow ``RecordBatch``es end to end; until this
module the reproduction was columnar only for flat numeric columns —
strings lived as numpy object arrays of Python ``str`` and nested
STRUCT/LIST values were shredded by the native parsers and then
reassembled into Python dict rows just so operators could carry them.
These classes carry the shredded form directly inside
``RecordBatch.columns`` (alongside plain ndarrays):

- :class:`StringColumn` — Arrow string layout: ``int64`` offsets (n+1)
  into one contiguous UTF-8 byte buffer, plus an optional validity mask.
- :class:`NestedColumn` — a shredded STRUCT/LIST tree: typed child
  columns (``PrimitiveColumn`` leaves at the parser's natural width,
  ``StringColumn`` string leaves, nested ``NestedColumn``s) plus
  Arrow-style list offsets.

Python rows materialize ONLY at user-facing boundaries (sinks, UDFs,
``to_pydict``, pyarrow interop) via the cached :meth:`Column.as_object`
— which every legacy numpy call site reaches automatically through
``__array__``/``tolist``, so operators migrate incrementally.  The
materialization itself reuses the C row assembler
(``native/pyassemble.cpp``) when it builds, and the generated
dict-literal comprehension fallback otherwise — the same machinery the
decode hot path used to run once per INGESTED row now runs once per
EMITTED row.

Ownership/lifetime: a column OWNS its buffers.  Parser-backed columns
are built from one bulk copy of the parser's arena (the parser's buffers
are invalidated by the next ``parse``/``clear``), so a column never
aliases memory it does not control; see docs/columnar.md.
"""

from __future__ import annotations

import ctypes

import numpy as np

from denormalized_tpu.common.errors import SchemaError
from denormalized_tpu.common.schema import DataType, Field


def columnar_strings_enabled() -> bool:
    """Env gate for the columnar string/nested decode path.  Default ON;
    ``DENORMALIZED_COLUMNAR_STRINGS=0`` restores the pre-refactor
    object-column materialization at the parser (kept for one PR as the
    differential oracle, like ``DENORMALIZED_SESSION_REFERENCE``)."""
    import os

    return os.environ.get("DENORMALIZED_COLUMNAR_STRINGS", "1") != "0"


def as_numpy(col) -> np.ndarray:
    """ndarray view of a batch column: plain ndarrays pass through,
    Column instances materialize (cached).  The ONE conversion helper
    every legacy consumer funnels through."""
    if isinstance(col, Column):
        return col.as_object()
    return col


def as_key_column(v):
    """Interner-ready key column: Column instances pass through (the
    offsets+bytes intern lane), everything else normalizes through
    ``np.asarray`` (numeric keys keep their exact-value path)."""
    return v if isinstance(v, Column) else np.asarray(v)


class Column:
    """Base for non-ndarray batch columns.

    Implements enough of the ndarray surface (``shape``, ``dtype``,
    ``__len__``, ``__getitem__``, ``__iter__``, ``tolist``,
    ``__array__``) that legacy operators keep working — numpy call sites
    silently fall back to the cached object-array materialization, while
    migrated consumers (interner, exchange codec, spill codec) test
    ``isinstance(col, Column)`` first and stay on the buffers."""

    __slots__ = ()

    # -- ndarray-compatible surface --------------------------------------
    @property
    def shape(self) -> tuple:
        return (len(self),)

    @property
    def dtype(self) -> np.dtype:
        # object dtype: legacy `col.dtype == object` dispatch routes
        # Column instances down the (correct, slower) object lanes
        return np.dtype(object)

    def __array__(self, dtype=None, copy=None):
        arr = self.as_object()
        if dtype is not None and np.dtype(dtype) != np.dtype(object):
            return arr.astype(dtype)
        return arr

    def __iter__(self):
        return iter(self.as_object())

    def tolist(self) -> list:
        return self.as_object().tolist()

    def __len__(self) -> int:
        raise NotImplementedError

    def as_object(self) -> np.ndarray:
        """Materialize Python values (cached): the ONLY place rows may be
        built from the shredded buffers."""
        raise NotImplementedError

    @property
    def nbytes(self) -> int:
        """Exact buffer bytes (accounting; no materialization)."""
        raise NotImplementedError

    def take(self, indices: np.ndarray) -> "Column":
        raise NotImplementedError

    def __getitem__(self, key):
        if isinstance(key, (int, np.integer)):
            i = int(key)
            n = len(self)
            if i < 0:
                i += n
            if not 0 <= i < n:
                raise IndexError(f"index {key} out of range for {n} rows")
            return self._get_one(i)
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            if step == 1:
                return self.slice(start, stop - start)
            return self.take(np.arange(start, stop, step))
        key = np.asarray(key)
        if key.dtype == bool:
            return self.take(np.flatnonzero(key))
        return self.take(key)

    def slice(self, start: int, length: int) -> "Column":
        return self.take(np.arange(start, start + length))

    def _get_one(self, i: int):
        raise NotImplementedError


class StringColumn(Column):
    """Arrow-layout string column: ``offsets`` (int64, n+1) into ``data``
    (uint8, contiguous UTF-8), optional ``validity`` (bool, n; None =
    all valid).  Invalid slots materialize as ``None`` — the same
    convention as the object-array path."""

    __slots__ = ("offsets", "data", "validity", "_obj")

    def __init__(
        self,
        offsets: np.ndarray,
        data: np.ndarray,
        validity: np.ndarray | None = None,
    ) -> None:
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.data = np.asarray(data, dtype=np.uint8)
        self.validity = validity
        self._obj: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.offsets) - 1

    @property
    def nbytes(self) -> int:
        n = self.offsets.nbytes + self.data.nbytes
        if self.validity is not None:
            n += self.validity.nbytes
        return n

    def _get_one(self, i: int):
        if self.validity is not None and not self.validity[i]:
            return None
        o = self.offsets
        return bytes(self.data[o[i]: o[i + 1]]).decode(errors="replace")

    def as_object(self) -> np.ndarray:
        if self._obj is not None:
            return self._obj
        n = len(self)
        out = np.empty(n, dtype=object)
        raw = self.data.tobytes()
        offs = self.offsets.tolist()
        for i in range(n):
            out[i] = raw[offs[i]: offs[i + 1]].decode(errors="replace")
        if self.validity is not None and not self.validity.all():
            out[~self.validity] = None
        self._obj = out
        return out

    def take(self, indices: np.ndarray) -> "StringColumn":
        idx = np.asarray(indices, dtype=np.int64)
        o = self.offsets
        lens = o[1:] - o[:-1]
        nl = lens[idx]
        noffs = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(nl, out=noffs[1:])
        total = int(noffs[-1])
        if total:
            starts = o[:-1][idx]
            # gather positions: each row's byte range, flattened
            pos = (
                np.repeat(starts - noffs[:-1], nl)
                + np.arange(total, dtype=np.int64)
            )
            data = self.data[pos]
        else:
            data = np.empty(0, dtype=np.uint8)
        v = self.validity[idx] if self.validity is not None else None
        return StringColumn(noffs, data, v)

    def slice(self, start: int, length: int) -> "StringColumn":
        stop = start + length
        o = self.offsets[start: stop + 1]
        data = self.data[int(o[0]): int(o[-1])]
        v = self.validity[start:stop] if self.validity is not None else None
        return StringColumn(o - o[0], data, v)

    @staticmethod
    def concat(cols: list["StringColumn"]) -> "StringColumn":
        datas = [c.data for c in cols]
        data = (
            np.concatenate(datas) if datas else np.empty(0, dtype=np.uint8)
        )
        n_total = sum(len(c) for c in cols)
        offs = np.empty(n_total + 1, dtype=np.int64)
        offs[0] = 0
        pos, base = 1, 0
        for c in cols:  # per-COLUMN sweep (chunk count), vectorized inside
            k = len(c)
            offs[pos: pos + k] = c.offsets[1:] + base
            base += int(c.offsets[-1])
            pos += k
        if any(c.validity is not None for c in cols):
            validity = np.concatenate(
                [
                    c.validity
                    if c.validity is not None
                    else np.ones(len(c), dtype=bool)
                    for c in cols
                ]
            )
        else:
            validity = None
        return StringColumn(offs, data, validity)

    @staticmethod
    def from_objects(arr) -> "StringColumn | None":
        """Build from an object array of str/None, or return None when a
        value is neither (bytes, dicts, mixed) — the caller keeps the
        legacy lane for those."""
        vals = arr.tolist() if isinstance(arr, np.ndarray) else list(arr)
        parts: list[bytes] = []
        validity = np.ones(len(vals), dtype=bool)
        any_null = False
        for i, v in enumerate(vals):
            if v is None:
                validity[i] = False
                any_null = True
                parts.append(b"")
            elif isinstance(v, str):
                parts.append(v.encode())
            else:
                return None
        offs = np.zeros(len(vals) + 1, dtype=np.int64)
        np.cumsum([len(p) for p in parts], out=offs[1:])
        data = np.frombuffer(b"".join(parts), dtype=np.uint8)
        return StringColumn(offs, data, validity if any_null else None)

    def __repr__(self) -> str:
        return f"StringColumn({len(self)} rows, {self.data.nbytes}B)"


#: assembly type codes, matching pyassemble.cpp's node types
_PRIM_CODE = {"i64": 0, "f64": 1, "bool": 2}
_PRIM_DTYPE = {"i64": np.int64, "f64": np.float64, "bool": np.uint8}


class PrimitiveColumn(Column):
    """Typed leaf inside a :class:`NestedColumn`: values at the parser's
    natural width (int64 / float64 / uint8-bool — declared-INT32 leaves
    are already saturated at i32 bounds when the column is built), plus
    per-entry validity.  Only ever a child of a nested column; top-level
    numeric columns stay plain ndarrays."""

    __slots__ = ("kind", "values", "validity", "_obj")

    def __init__(self, kind: str, values: np.ndarray,
                 validity: np.ndarray | None = None) -> None:
        self.kind = kind  # 'i64' | 'f64' | 'bool'
        self.values = np.asarray(values, dtype=_PRIM_DTYPE[kind])
        self.validity = validity
        self._obj: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.values)

    @property
    def nbytes(self) -> int:
        n = self.values.nbytes
        if self.validity is not None:
            n += self.validity.nbytes
        return n

    def _pylist(self) -> list:
        vals = (
            self.values.view(np.bool_).tolist()
            if self.kind == "bool"
            else self.values.tolist()
        )
        if self.validity is not None and not self.validity.all():
            for i in np.flatnonzero(~self.validity):
                vals[i] = None
        return vals

    def _get_one(self, i: int):
        if self.validity is not None and not self.validity[i]:
            return None
        v = self.values[i]
        return bool(v) if self.kind == "bool" else v.item()

    def as_object(self) -> np.ndarray:
        if self._obj is None:
            out = np.empty(len(self), dtype=object)
            out[:] = self._pylist()
            self._obj = out
        return self._obj

    def take(self, indices: np.ndarray) -> "PrimitiveColumn":
        idx = np.asarray(indices, dtype=np.int64)
        return PrimitiveColumn(
            self.kind,
            self.values[idx],
            self.validity[idx] if self.validity is not None else None,
        )

    @staticmethod
    def concat(cols: list["PrimitiveColumn"]) -> "PrimitiveColumn":
        kind = cols[0].kind
        values = np.concatenate([c.values for c in cols])
        if any(c.validity is not None for c in cols):
            validity = np.concatenate(
                [
                    c.validity
                    if c.validity is not None
                    else np.ones(len(c), dtype=bool)
                    for c in cols
                ]
            )
        else:
            validity = None
        return PrimitiveColumn(kind, values, validity)


class NestedColumn(Column):
    """Shredded STRUCT/LIST column.

    ``kind='struct'``: ``children`` holds one column per declared child
    field (order = ``field.children`` order); ``validity`` is struct
    presence.  ``kind='list'``: ``children`` holds the single ELEMENT
    column (len = total elements), ``offsets`` (int64, n+1) gives each
    row's element range, ``validity`` is list presence.  Rows
    materialize as the same dicts / lists / None the pyassemble decode
    path produced — :meth:`as_object` IS that path, run lazily."""

    __slots__ = ("field", "kind", "length", "validity", "children",
                 "offsets", "_obj", "_builders")

    def __init__(
        self,
        field: Field,
        kind: str,
        length: int,
        children: list,
        validity: np.ndarray | None = None,
        offsets: np.ndarray | None = None,
    ) -> None:
        self.field = field
        self.kind = kind  # 'struct' | 'list'
        self.length = int(length)
        self.children = children
        self.validity = validity
        self.offsets = (
            np.asarray(offsets, dtype=np.int64) if offsets is not None
            else None
        )
        self._obj: np.ndarray | None = None
        self._builders: dict | None = None

    def __len__(self) -> int:
        return self.length

    @property
    def nbytes(self) -> int:
        n = sum(c.nbytes for c in self.children)
        if self.validity is not None:
            n += self.validity.nbytes
        if self.offsets is not None:
            n += self.offsets.nbytes
        return n

    def _get_one(self, i: int):
        return self.as_object()[i]

    def as_object(self) -> np.ndarray:
        if self._obj is not None:
            return self._obj
        n = len(self)
        out = np.empty(n, dtype=object)
        if n:
            fn = _pyassemble()
            vals = (
                _assemble_rows_c(self, fn) if fn is not None
                else _assemble_rows_py(self)
            )
            out[:] = vals
        self._obj = out
        return out

    def take(self, indices: np.ndarray) -> "NestedColumn":
        idx = np.asarray(indices, dtype=np.int64)
        v = self.validity[idx] if self.validity is not None else None
        if self.kind == "struct":
            return NestedColumn(
                self.field, "struct", len(idx),
                [c.take(idx) for c in self.children], v,
            )
        o = self.offsets
        lens = o[1:] - o[:-1]
        nl = lens[idx]
        noffs = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(nl, out=noffs[1:])
        total = int(noffs[-1])
        if total:
            pos = (
                np.repeat(o[:-1][idx] - noffs[:-1], nl)
                + np.arange(total, dtype=np.int64)
            )
            elem = self.children[0].take(pos)
        else:
            elem = self.children[0].take(
                np.empty(0, dtype=np.int64)
            )
        return NestedColumn(
            self.field, "list", len(idx), [elem], v, noffs
        )

    @staticmethod
    def concat(cols: list["NestedColumn"]) -> "NestedColumn":
        first = cols[0]
        if any(c.validity is not None for c in cols):
            validity = np.concatenate(
                [
                    c.validity
                    if c.validity is not None
                    else np.ones(len(c), dtype=bool)
                    for c in cols
                ]
            )
        else:
            validity = None
        n = sum(len(c) for c in cols)
        if first.kind == "struct":
            children = [
                concat_columns([c.children[i] for c in cols])
                for i in range(len(first.children))
            ]
            return NestedColumn(first.field, "struct", n, children, validity)
        offs = np.empty(n + 1, dtype=np.int64)
        offs[0] = 0
        pos, base = 1, 0
        for c in cols:
            k = len(c)
            offs[pos: pos + k] = c.offsets[1:] + base
            base += int(c.offsets[-1])
            pos += k
        elem = concat_columns([c.children[0] for c in cols])
        return NestedColumn(first.field, "list", n, [elem], validity, offs)

    def __repr__(self) -> str:
        return (
            f"NestedColumn({self.kind} {self.field.name!r}, "
            f"{self.length} rows)"
        )


def concat_columns(cols: list):
    """Concat a list of same-shape columns (all Column subclass or all
    ndarray).  Mixed representations (a legacy object chunk next to a
    columnar one) materialize — correctness over layout."""
    if all(isinstance(c, StringColumn) for c in cols):
        return StringColumn.concat(cols)
    if all(isinstance(c, PrimitiveColumn) for c in cols):
        return PrimitiveColumn.concat(cols)
    if all(isinstance(c, NestedColumn) for c in cols):
        return NestedColumn.concat(cols)
    return np.concatenate([as_numpy(c) for c in cols])


# -- row assembly (sink/UDF boundary) -------------------------------------

_PA_SENTINEL = object()
_pa_fn = _PA_SENTINEL  # resolved on first use; None = unavailable


def _pyassemble():
    """The C row assembler (native/pyassemble.cpp), or None when it can't
    build here (no compiler / no Python headers — the generated-
    comprehension fallback then does the reassembly).  Loaded via PyDLL:
    the assembler manipulates Python objects and must hold the GIL."""
    global _pa_fn
    if _pa_fn is not _PA_SENTINEL:
        return _pa_fn
    try:
        import sysconfig

        from denormalized_tpu.native.build import load

        inc = sysconfig.get_paths()["include"]
        pylib = load("pyassemble", [f"-I{inc}"], pydll=True)
        fn = pylib.pa_rows
        fn.restype = ctypes.py_object
        fn.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_uint64,
        ]
        _pa_fn = fn
    except Exception as e:  # dnzlint: allow(broad-except) the generated-comprehension reassembly is the designed fallback (no Python headers); logged so the downgrade is visible, gated by test_native_build_gate where headers exist
        from denormalized_tpu.runtime.tracing import logger

        logger.warning(
            "pyassemble (C row assembler) unavailable (%s: %s) — nested "
            "reassembly uses the generated-comprehension path",
            type(e).__name__, e,
        )
        _pa_fn = None
    return _pa_fn


def _valid_ptr(validity: np.ndarray | None):
    """ctypes arg for a validity array: NULL when all-valid so the C
    walker skips the per-value presence load entirely."""
    if validity is None:
        return None
    if validity.all():
        return None
    return ctypes.c_void_p(validity.ctypes.data)


def _assemble_rows_c(col: NestedColumn, fn) -> list:
    """One nested column's Python rows via pa_rows: flatten the column
    tree into the parallel node arrays, handing it the column's OWN
    buffers — string leaves pre-materialize per COLUMN (cached on the
    leaf), everything else is read straight off the typed buffers."""
    types: list[int] = []
    parents: list[int] = []
    names: list[bytes] = []
    datas: list = []
    valids: list = []
    offs: list = []
    keep: list = []  # arrays that must outlive the call

    def add(node, name: str, parent: int) -> None:
        idx = len(types)
        types.append(0)
        parents.append(parent)
        names.append(name.encode())
        datas.append(None)
        valids.append(None)
        offs.append(None)
        if isinstance(node, NestedColumn):
            if node.kind == "struct":
                types[idx] = 4
                valids[idx] = _valid_ptr(node.validity)
                for f, c in zip(node.field.children, node.children):
                    add(c, f.name, idx)
            else:
                types[idx] = 5
                valids[idx] = _valid_ptr(node.validity)
                offsets = node.offsets
                keep.append(offsets)
                offs[idx] = ctypes.c_void_p(offsets.ctypes.data)
                add(node.children[0], "item", idx)
        elif isinstance(node, StringColumn):
            types[idx] = 3
            arr = node.as_object()  # cached; Nones already placed
            keep.append(arr)
            datas[idx] = ctypes.c_void_p(arr.ctypes.data)
        else:  # PrimitiveColumn
            types[idx] = _PRIM_CODE[node.kind]
            datas[idx] = ctypes.c_void_p(node.values.ctypes.data)
            valids[idx] = _valid_ptr(node.validity)

    add(col, col.field.name, -1)
    nn = len(types)
    rows = fn(
        nn,
        (ctypes.c_int * nn)(*types),
        (ctypes.c_int * nn)(*parents),
        (ctypes.c_char_p * nn)(*names),
        (ctypes.c_void_p * nn)(*datas),
        (ctypes.c_void_p * nn)(*valids),
        (ctypes.c_void_p * nn)(*offs),
        len(col),
    )
    del keep
    return rows


def _compile_fused_builder(expr: str, nargs: int):
    """Compile a row builder that assembles one struct column's python
    rows in a SINGLE comprehension: ``expr`` is a nested dict LITERAL
    over loop variables a0..aN (one per leaf/list value list, plus one
    per non-all-present sub-struct presence list), so a whole struct
    subtree materializes in one zip pass with no intermediate per-child
    lists.  Field names are embedded via repr (arbitrary key strings are
    safe); argument names are synthesized."""
    args = ", ".join(f"A{i}" for i in range(nargs))
    unpack = ", ".join(f"a{i}" for i in range(nargs))
    # `for a0 in zip(A0)` would bind the 1-TUPLE, not the element
    loop = (
        f"for {unpack} in zip({args})" if nargs > 1 else "for a0 in A0"
    )
    src = f"def _b({args}):\n    return [{expr} {loop}]\n"
    ns: dict = {}
    exec(src, ns)  # noqa: S102 — schema-derived, keys repr-escaped
    return ns["_b"]


def _assemble_rows_py(col) -> list:
    """Python-fallback assembly (no pyassemble): struct subtrees fuse
    into one generated dict-literal comprehension (builders cached per
    which-sub-structs-were-all-present key), lists reassemble by offset
    slicing — a few list comprehensions per column, never per-row
    ``json.loads``."""
    if isinstance(col, (PrimitiveColumn,)):
        return col._pylist()
    if isinstance(col, StringColumn):
        return col.as_object().tolist()
    if col.kind == "list":
        valid = col.validity
        offs = col.offsets.tolist()
        elems = _assemble_rows_py(col.children[0])
        if valid is None:
            return [
                elems[offs[i]: offs[i + 1]] for i in range(len(col))
            ]
        return [
            elems[offs[i]: offs[i + 1]] if v else None
            for i, v in enumerate(valid.tolist())
        ]
    # struct: fuse the subtree into one comprehension
    n = len(col)
    atoms: list = []
    key: list[bool] = []

    def gen(node: NestedColumn) -> str:
        pres = node.validity
        all_present = pres is None or bool(pres.all())
        parts = []
        for f, c in zip(node.field.children, node.children):
            if isinstance(c, NestedColumn) and c.kind == "struct":
                cexpr = gen(c)
            else:
                ai = len(atoms)
                atoms.append(_assemble_rows_py(c))
                cexpr = f"a{ai}"
            parts.append(f"{f.name!r}: {cexpr}")
        literal = "{" + ", ".join(parts) + "}"
        if all_present:
            key.append(True)
            return literal
        key.append(False)
        pi = len(atoms)
        atoms.append(pres.tolist())
        return f"({literal} if a{pi} else None)"

    if not col.field.children:
        pres = col.validity
        if pres is None:
            return [dict() for _ in range(n)]
        return [dict() if p else None for p in pres.tolist()]
    expr = gen(col)
    if col._builders is None:
        col._builders = {}
    builder = col._builders.get(tuple(key))
    if builder is None:
        builder = _compile_fused_builder(expr, len(atoms))
        col._builders[tuple(key)] = builder
    return builder(*atoms)


# -- spec/buffer codec (exchange frames, spill blocks, snapshots) ---------
#
# One codec for every binary carrier: ``column_spec_and_buffers`` flattens
# a column into a JSON-safe spec plus an ordered list of raw ndarray
# buffers (depth-first), ``column_from_spec`` rebuilds it.  The exchange
# lane ships the buffers as frame sub-buffers; the spill/checkpoint lane
# stores them as named pack_snapshot arrays.  No pickle, no JSON value
# lists — string columns travel as raw offsets+bytes.


def field_to_spec(f: Field) -> dict:
    spec: dict = {"n": f.name, "t": f.dtype.value}
    if f.children:
        spec["c"] = [field_to_spec(c) for c in f.children]
    return spec


def field_from_spec(spec: dict) -> Field:
    return Field(
        spec["n"],
        DataType(spec["t"]),
        children=tuple(field_from_spec(c) for c in spec.get("c", ())),
    )


def column_spec_and_buffers(col) -> tuple[dict, list[np.ndarray]]:
    bufs: list[np.ndarray] = []

    def walk(node) -> dict:
        if isinstance(node, StringColumn):
            spec = {"k": "str", "v": node.validity is not None}
            bufs.append(node.offsets)
            bufs.append(node.data)
            if node.validity is not None:
                bufs.append(np.asarray(node.validity, dtype=bool))
            return spec
        if isinstance(node, PrimitiveColumn):
            spec = {
                "k": "prim", "p": node.kind,
                "v": node.validity is not None,
            }
            bufs.append(node.values)
            if node.validity is not None:
                bufs.append(np.asarray(node.validity, dtype=bool))
            return spec
        if isinstance(node, NestedColumn):
            spec = {
                "k": node.kind,
                "len": len(node),
                "v": node.validity is not None,
                "f": field_to_spec(node.field),
            }
            if node.validity is not None:
                bufs.append(np.asarray(node.validity, dtype=bool))
            if node.kind == "list":
                bufs.append(node.offsets)
            spec["ch"] = [walk(c) for c in node.children]
            return spec
        raise SchemaError(f"not a codec-able column: {type(node).__name__}")

    return walk(col), bufs


def column_from_spec(spec: dict, bufs) -> Column:
    """Rebuild a column from its spec + buffer iterator (the inverse of
    :func:`column_spec_and_buffers`; ``bufs`` yields ndarrays in the
    same depth-first order)."""

    def walk(s: dict):
        k = s["k"]
        if k == "str":
            offsets = next(bufs)
            data = next(bufs)
            validity = (
                np.asarray(next(bufs), dtype=bool) if s["v"] else None
            )
            return StringColumn(offsets, data, validity)
        if k == "prim":
            values = next(bufs)
            validity = (
                np.asarray(next(bufs), dtype=bool) if s["v"] else None
            )
            return PrimitiveColumn(s["p"], values, validity)
        validity = np.asarray(next(bufs), dtype=bool) if s["v"] else None
        offsets = next(bufs) if k == "list" else None
        children = [walk(c) for c in s["ch"]]
        return NestedColumn(
            field_from_spec(s["f"]), k, s["len"], children, validity,
            offsets,
        )

    bufs = iter(bufs)
    return walk(spec)


def column_to_arrays(
    col, prefix: str, arrays: dict[str, np.ndarray]
) -> dict:
    """Named-array carrier (spill blocks / checkpoint snapshots): the
    buffers land in ``arrays`` as ``{prefix}{i}``; returns the JSON-safe
    spec to store in the blob meta."""
    spec, bufs = column_spec_and_buffers(col)
    for i, b in enumerate(bufs):
        arrays[f"{prefix}{i}"] = b
    return {"spec": spec, "nbufs": len(bufs)}

def column_from_arrays(
    entry: dict, prefix: str, arrays: dict[str, np.ndarray]
) -> Column:
    bufs = [arrays[f"{prefix}{i}"] for i in range(int(entry["nbufs"]))]
    return column_from_spec(entry["spec"], iter(bufs))
