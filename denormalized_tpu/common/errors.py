"""Unified error hierarchy.

Mirrors the capability of the reference's ``DenormalizedError`` enum
(crates/common/src/error/mod.rs:16-36), which wraps engine/Arrow/format/Kafka/
state-backend errors into one result type; Python exceptions subsume the
``Result`` plumbing.
"""


class DenormalizedError(Exception):
    """Base error for the framework."""


class SchemaError(DenormalizedError):
    """Schema mismatch / unknown column / bad type."""


class PlanError(DenormalizedError):
    """Invalid logical or physical plan construction."""


class FormatError(DenormalizedError):
    """Decode/encode failure (JSON/Avro)."""


class SourceError(DenormalizedError):
    """Source connector failure (Kafka, replay)."""


class StateError(DenormalizedError):
    """State backend / checkpoint failure."""


class ShutdownError(DenormalizedError):
    """Graceful-shutdown signal, mirroring DenormalizedError::Shutdown."""
