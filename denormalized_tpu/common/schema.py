"""Columnar schema model.

The reference leans on Arrow's schema (DataTypes used across
crates/core/src/utils/arrow_helpers.rs and the decoders).  We keep a small,
TPU-oriented type lattice: every type knows its host (numpy) representation
and whether it can live on device.  Strings are host-only — group keys are
interned to dense int32 ids before touching the device (the TPU analog of
DataFusion's ``GroupValues`` interning table used at
grouped_window_agg_stream.rs:501-537).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np

from denormalized_tpu.common.errors import SchemaError


class DataType(enum.Enum):
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    BOOL = "bool"
    STRING = "string"
    # milliseconds since unix epoch, int64 storage (arrow timestamp-millis
    # equivalent; the reference's canonical_timestamp type,
    # kafka_config.rs:203-208)
    TIMESTAMP_MS = "timestamp_ms"
    # nested struct — host-only, used for nested JSON (rideshare example)
    STRUCT = "struct"
    # variable-length list — host-only (object array of np arrays / lists)
    LIST = "list"

    def to_numpy(self) -> np.dtype:
        return _NUMPY_OF[self]

    @property
    def is_numeric(self) -> bool:
        return self in (
            DataType.INT32,
            DataType.INT64,
            DataType.FLOAT32,
            DataType.FLOAT64,
            DataType.TIMESTAMP_MS,
            DataType.BOOL,
        )

    @property
    def device_ok(self) -> bool:
        """Whether a column of this type can be shipped to TPU directly."""
        return self.is_numeric

    @staticmethod
    def from_numpy(dt: np.dtype) -> "DataType":
        dt = np.dtype(dt)
        if dt == np.int32:
            return DataType.INT32
        if dt in (np.int64, np.dtype("datetime64[ms]")):
            return DataType.INT64
        if dt == np.float32:
            return DataType.FLOAT32
        if dt == np.float64:
            return DataType.FLOAT64
        if dt == np.bool_:
            return DataType.BOOL
        if dt.kind in ("U", "S", "O"):
            return DataType.STRING
        raise SchemaError(f"unsupported numpy dtype {dt!r}")


_NUMPY_OF = {
    DataType.INT32: np.dtype(np.int32),
    DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT32: np.dtype(np.float32),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.BOOL: np.dtype(np.bool_),
    DataType.STRING: np.dtype(object),
    DataType.TIMESTAMP_MS: np.dtype(np.int64),
    DataType.STRUCT: np.dtype(object),
    DataType.LIST: np.dtype(object),
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True
    # for STRUCT fields: child fields
    children: tuple["Field", ...] = ()

    def __post_init__(self):
        # accept the enum's string value ("int64", "string", …) — failing
        # here with the valid names beats an AttributeError deep in an
        # operator long after schema construction
        if isinstance(self.dtype, str):
            try:
                object.__setattr__(self, "dtype", DataType(self.dtype))
            except ValueError:
                raise ValueError(
                    f"unknown dtype {self.dtype!r} for field "
                    f"{self.name!r}; expected one of "
                    f"{[d.value for d in DataType]}"
                ) from None

    def __repr__(self) -> str:
        if self.dtype is DataType.STRUCT:
            return f"Field({self.name}: struct<{', '.join(map(repr, self.children))}>)"
        return f"Field({self.name}: {self.dtype.value})"


@dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __init__(self, fields: Sequence[Field]):
        object.__setattr__(self, "fields", tuple(fields))
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise SchemaError(f"duplicate column names: {dupes}")

    # -- lookups ---------------------------------------------------------
    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise SchemaError(
            f"column {name!r} not found; available: {[f.name for f in self.fields]}"
        )

    def has(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise SchemaError(f"column {name!r} not found")

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def __iter__(self) -> Iterator[Field]:
        return iter(self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    # -- transforms ------------------------------------------------------
    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def drop(self, names: Sequence[str]) -> "Schema":
        gone = set(names)
        return Schema([f for f in self.fields if f.name not in gone])

    def append(self, *fields: Field) -> "Schema":
        return Schema(list(self.fields) + list(fields))

    def rename(self, mapping: dict[str, str]) -> "Schema":
        return Schema(
            [
                Field(mapping.get(f.name, f.name), f.dtype, f.nullable, f.children)
                for f in self.fields
            ]
        )

    def without_internal(self) -> "Schema":
        """User-visible schema: strips internal metadata columns (mirrors
        DataStream::schema, reference datastream.rs:199-210)."""
        from denormalized_tpu.common.constants import (
            CANONICAL_TIMESTAMP_COLUMN,
            INTERNAL_METADATA_COLUMN,
        )

        return Schema(
            [
                f
                for f in self.fields
                if f.name != CANONICAL_TIMESTAMP_COLUMN
                and not f.name.startswith(INTERNAL_METADATA_COLUMN)
            ]
        )

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"
