"""Framework-wide column-name constants.

The reference threads a hidden struct column ``_streaming_internal_metadata``
(fields ``barrier_batch`` + ``canonical_timestamp``) through every plan
(reference: crates/common/src/lib.rs:5, kafka_config.rs:196-211).  Columnar
tensors have no struct columns, so we carry the same information as flat
internal columns that every operator preserves and ``DataStream.schema()``
strips (mirroring datastream.rs:199-210).
"""

# Name of the internal metadata namespace; kept for API parity with the
# reference's INTERNAL_METADATA_COLUMN (crates/common/src/lib.rs:5).
INTERNAL_METADATA_COLUMN = "_streaming_internal_metadata"

# int64 milliseconds-since-epoch event time attached by every source
# (reference: kafka_stream_read.rs:165-296 builds `canonical_timestamp`).
CANONICAL_TIMESTAMP_COLUMN = "_streaming_internal_metadata.canonical_timestamp"

# Barrier tag column equivalent (reference kafka_stream_read.rs:240-243 always
# writes "no_barrier"; barriers are delivered out-of-band).  We keep barriers
# fully out-of-band and do not materialize this column.
BARRIER_BATCH_FIELD = "barrier_batch"

# Window bound columns appended by windowed aggregation
# (reference: streaming_window.rs:534 `add_window_columns_to_schema`).
WINDOW_START_COLUMN = "window_start_time"
WINDOW_END_COLUMN = "window_end_time"
