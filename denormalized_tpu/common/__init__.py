from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    INTERNAL_METADATA_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import (
    DenormalizedError,
    PlanError,
    SchemaError,
    StateError,
)
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.common.record_batch import RecordBatch

__all__ = [
    "CANONICAL_TIMESTAMP_COLUMN",
    "INTERNAL_METADATA_COLUMN",
    "WINDOW_END_COLUMN",
    "WINDOW_START_COLUMN",
    "DenormalizedError",
    "PlanError",
    "SchemaError",
    "StateError",
    "DataType",
    "Field",
    "Schema",
    "RecordBatch",
]
