"""Columnar record batch: a schema plus one host (numpy) array per column.

The host-side unit of flow between physical operators, playing the role of
Arrow ``RecordBatch`` in the reference.  Device transfer happens only inside
the windowed-aggregation operator (the hot path), which ships the numeric
columns it needs as padded tensors — batches themselves never hold device
arrays, keeping every other operator trivially host-side and allocation-light.

Nullability: a column may carry a boolean validity mask; ``None`` mask means
all-valid (Arrow's convention).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from denormalized_tpu.common.columns import (
    Column,
    as_numpy,
    concat_columns,
)
from denormalized_tpu.common.errors import SchemaError
from denormalized_tpu.common.schema import DataType, Field, Schema


@dataclass
class RecordBatch:
    schema: Schema
    # plain host ndarrays, or columnar Column instances (StringColumn /
    # NestedColumn — see common/columns.py) for string & nested fields
    columns: list[np.ndarray]
    # validity masks, parallel to columns; None = all valid
    masks: list[np.ndarray | None]
    num_rows: int

    def __init__(
        self,
        schema: Schema,
        columns: Sequence[np.ndarray],
        masks: Sequence[np.ndarray | None] | None = None,
    ):
        if len(columns) != len(schema):
            raise SchemaError(
                f"{len(columns)} columns for schema of {len(schema)} fields"
            )
        self.schema = schema
        self.columns = [
            c if isinstance(c, Column) else np.asarray(c) for c in columns
        ]
        n = self.columns[0].shape[0] if self.columns else 0
        for f, c in zip(schema, self.columns):
            if c.shape[0] != n:
                raise SchemaError(
                    f"column {f.name!r} has {c.shape[0]} rows, expected {n}"
                )
        self.masks = list(masks) if masks is not None else [None] * len(self.columns)
        if len(self.masks) != len(self.columns):
            raise SchemaError("masks length != columns length")
        self.num_rows = n

    # -- constructors ----------------------------------------------------
    @staticmethod
    def from_pydict(
        data: Mapping[str, Sequence], schema: Schema | None = None
    ) -> "RecordBatch":
        if schema is None:
            fields, cols = [], []
            for name, vals in data.items():
                arr = _coerce_column(vals)
                fields.append(Field(name, DataType.from_numpy(arr.dtype)))
                cols.append(arr)
            return RecordBatch(Schema(fields), cols)
        cols = []
        for f in schema:
            if f.name not in data:
                raise SchemaError(f"missing column {f.name!r}")
            cols.append(np.asarray(data[f.name], dtype=f.dtype.to_numpy()))
        return RecordBatch(schema, cols)

    @staticmethod
    def empty(schema: Schema) -> "RecordBatch":
        return RecordBatch(
            schema, [np.empty(0, dtype=f.dtype.to_numpy()) for f in schema]
        )

    # -- access ----------------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        return self.columns[self.schema.index_of(name)]

    def mask(self, name: str) -> np.ndarray | None:
        return self.masks[self.schema.index_of(name)]

    def to_pydict(self) -> dict[str, list]:
        """Python value lists per column, with validity APPLIED: a null
        entry surfaces as ``None`` (matching ``to_pyarrow().to_pylist()``),
        never as the storage fill value (0/False/'')."""
        out: dict[str, list] = {}
        for f, c, m in zip(self.schema, self.columns, self.masks):
            vals = c.tolist()
            if m is not None and not (valid := np.asarray(m, dtype=bool)).all():
                vals = [
                    v if ok else None for v, ok in zip(vals, valid.tolist())
                ]
            out[f.name] = vals
        return out

    def materialized(self) -> "RecordBatch":
        """A batch whose columnar string/nested columns are replaced by
        their object-array materialization — the user-facing boundary
        (CallbackSink, UDF inputs).  A batch with no Column instances
        returns itself."""
        if not any(isinstance(c, Column) for c in self.columns):
            return self
        return RecordBatch(
            self.schema, [as_numpy(c) for c in self.columns], self.masks
        )

    # -- Arrow interop ---------------------------------------------------
    # The reference's Python callback path hands pyarrow batches to user
    # code (py-denormalized/src/datastream.rs:244-252), and its vendored
    # layer leans on pyarrow throughout — a user switching over gets the
    # same shapes via these converters.  pyarrow is an optional
    # convenience (lazy import), never an engine dependency.

    def to_pyarrow(self):
        """Convert to a ``pyarrow.RecordBatch`` (nulls preserved)."""
        import pyarrow as pa

        arrays, fields = [], []
        for f, col, mask in zip(self.schema, self.columns, self.masks):
            nulls = None if mask is None else ~np.asarray(mask, dtype=bool)
            pa_type = _pa_type_of_field(pa, f)
            if pa_type is not None and col.dtype != object and not (
                pa.types.is_struct(pa_type) or pa.types.is_list(pa_type)
            ):
                arr = pa.array(np.ascontiguousarray(col), type=pa_type,
                               mask=nulls)
            else:
                # STRING object arrays and host-only STRUCT/LIST columns go
                # through python values; nulls become None.  The declared
                # type (when derivable from Field children) keeps the
                # arrow schema identical between empty and non-empty
                # batches — inference on [] would yield a null-typed field.
                vals = col.tolist()
                if nulls is not None:
                    vals = [None if d else v for v, d in zip(vals, nulls)]
                arr = (pa.array(vals, type=pa_type)
                       if pa_type is not None else pa.array(vals))
            arrays.append(arr)
            fields.append(pa.field(f.name, arr.type, nullable=f.nullable))
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    def to_pandas(self):
        """Convert to a ``pandas.DataFrame`` (via pyarrow)."""
        return self.to_pyarrow().to_pandas()

    @staticmethod
    def from_pyarrow(rb) -> "RecordBatch":
        """Build from a ``pyarrow.RecordBatch`` / ``pyarrow.Table`` slice."""
        import pyarrow as pa

        fields, cols, masks = [], [], []
        for pf in rb.schema:
            col = rb.column(pf.name)
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            dtype = _dtype_from_arrow(pa, pf.type)
            valid = None
            if col.null_count:
                valid = np.asarray(pa.compute.is_valid(col).to_numpy(
                    zero_copy_only=False), dtype=bool)
            if dtype in (DataType.STRING, DataType.STRUCT, DataType.LIST):
                arr = np.empty(len(col), dtype=object)
                arr[:] = col.to_pylist()
            else:
                if pa.types.is_timestamp(pf.type):
                    # normalize us/ns (e.g. pandas-origin) to millisecond
                    # values BEFORE the integer reinterpretation
                    col = col.cast(pa.timestamp("ms")).cast(pa.int64())
                if col.null_count:
                    fill = False if pa.types.is_boolean(col.type) else 0
                    col = col.fill_null(fill)
                arr = np.asarray(
                    col.to_numpy(zero_copy_only=False),
                    dtype=dtype.to_numpy(),
                )
            fields.append(Field(pf.name, dtype, nullable=pf.nullable))
            cols.append(arr)
            masks.append(valid)
        return RecordBatch(Schema(fields), cols, masks)

    # -- transforms ------------------------------------------------------
    def select(self, names: Sequence[str]) -> "RecordBatch":
        idx = [self.schema.index_of(n) for n in names]
        return RecordBatch(
            self.schema.select(names),
            [self.columns[i] for i in idx],
            [self.masks[i] for i in idx],
        )

    def drop(self, names: Sequence[str]) -> "RecordBatch":
        keep = [f.name for f in self.schema if f.name not in set(names)]
        return self.select(keep)

    def with_column(
        self, field: Field, col: np.ndarray, mask: np.ndarray | None = None
    ) -> "RecordBatch":
        """Append or replace a column."""
        if self.schema.has(field.name):
            i = self.schema.index_of(field.name)
            fields = list(self.schema.fields)
            fields[i] = field
            cols = list(self.columns)
            cols[i] = col if isinstance(col, Column) else np.asarray(col)
            masks = list(self.masks)
            masks[i] = mask
            return RecordBatch(Schema(fields), cols, masks)
        return RecordBatch(
            self.schema.append(field),
            list(self.columns)
            + [col if isinstance(col, Column) else np.asarray(col)],
            list(self.masks) + [mask],
        )

    def filter(self, keep: np.ndarray) -> "RecordBatch":
        keep = np.asarray(keep, dtype=bool)
        return RecordBatch(
            self.schema,
            [c[keep] for c in self.columns],
            [m[keep] if m is not None else None for m in self.masks],
        )

    def take(self, indices: np.ndarray) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            [c[indices] for c in self.columns],
            [m[indices] if m is not None else None for m in self.masks],
        )

    def slice(self, start: int, length: int) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            [c[start : start + length] for c in self.columns],
            [m[start : start + length] if m is not None else None for m in self.masks],
        )

    @staticmethod
    def concat(
        batches: Sequence["RecordBatch"], schema: Schema | None = None
    ) -> "RecordBatch":
        batches = list(batches)
        if not batches:
            # an empty sequence has no schema to concat under — either the
            # caller supplies one (→ a well-formed 0-row batch) or this is
            # a clear error instead of an opaque IndexError
            if schema is None:
                raise SchemaError(
                    "RecordBatch.concat of an empty sequence needs an "
                    "explicit schema= argument"
                )
            return RecordBatch.empty(schema)
        batches = [b for b in batches if b.num_rows > 0] or batches[:1]
        first = batches[0]
        cols = [
            concat_columns([b.columns[i] for b in batches])
            for i in range(len(first.schema))
        ]
        masks = []
        for i in range(len(first.schema)):
            if any(b.masks[i] is not None for b in batches):
                masks.append(
                    np.concatenate(
                        [
                            b.masks[i]
                            if b.masks[i] is not None
                            else np.ones(b.num_rows, dtype=bool)
                            for b in batches
                        ]
                    )
                )
            else:
                masks.append(None)
        return RecordBatch(first.schema, cols, masks)

    def __repr__(self) -> str:
        return f"RecordBatch({self.num_rows} rows, {self.schema!r})"


# engine dtype → pyarrow type factory (callables taking the pa module, so
# pyarrow stays a lazy import); STRUCT/LIST fall through to inference
_PA_OF = {
    DataType.INT32: lambda pa: pa.int32(),
    DataType.INT64: lambda pa: pa.int64(),
    DataType.FLOAT32: lambda pa: pa.float32(),
    DataType.FLOAT64: lambda pa: pa.float64(),
    DataType.BOOL: lambda pa: pa.bool_(),
    DataType.STRING: lambda pa: pa.string(),
    DataType.TIMESTAMP_MS: lambda pa: pa.timestamp("ms"),
}


def _pa_type_of_field(pa, f):
    """Arrow type for an engine Field, or None when not derivable (a LIST
    with no declared child falls back to value inference)."""
    base = _PA_OF.get(f.dtype)
    if base is not None:
        return base(pa)
    if f.dtype is DataType.STRUCT:
        return pa.struct(
            [
                pa.field(c.name, _pa_type_of_field(pa, c) or pa.null(),
                         nullable=c.nullable)
                for c in f.children
            ]
        )
    if f.dtype is DataType.LIST and len(f.children) == 1:
        child = _pa_type_of_field(pa, f.children[0])
        if child is not None:
            return pa.list_(child)
    return None


def _dtype_from_arrow(pa, t) -> DataType:
    if pa.types.is_timestamp(t):
        return DataType.TIMESTAMP_MS
    if pa.types.is_int32(t):
        return DataType.INT32
    if pa.types.is_uint64(t):
        # values above 2**63-1 would wrap negative in the int64 engine
        # representation — refuse loudly rather than corrupt silently
        raise SchemaError("uint64 arrow columns are not representable")
    if pa.types.is_integer(t):
        return DataType.INT64
    if pa.types.is_float32(t):
        return DataType.FLOAT32
    if pa.types.is_floating(t):
        return DataType.FLOAT64
    if pa.types.is_boolean(t):
        return DataType.BOOL
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return DataType.STRING
    if pa.types.is_struct(t):
        return DataType.STRUCT
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return DataType.LIST
    raise SchemaError(f"unsupported arrow type {t!r}")


def _coerce_column(vals: Sequence) -> np.ndarray:
    arr = np.asarray(vals)
    if arr.dtype.kind == "U":
        arr = arr.astype(object)
    if arr.dtype.kind == "O" and arr.shape[0] and isinstance(arr[0], bool):
        arr = arr.astype(bool)
    return arr
