"""Columnar record batch: a schema plus one host (numpy) array per column.

The host-side unit of flow between physical operators, playing the role of
Arrow ``RecordBatch`` in the reference.  Device transfer happens only inside
the windowed-aggregation operator (the hot path), which ships the numeric
columns it needs as padded tensors — batches themselves never hold device
arrays, keeping every other operator trivially host-side and allocation-light.

Nullability: a column may carry a boolean validity mask; ``None`` mask means
all-valid (Arrow's convention).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from denormalized_tpu.common.errors import SchemaError
from denormalized_tpu.common.schema import DataType, Field, Schema


@dataclass
class RecordBatch:
    schema: Schema
    columns: list[np.ndarray]
    # validity masks, parallel to columns; None = all valid
    masks: list[np.ndarray | None]
    num_rows: int

    def __init__(
        self,
        schema: Schema,
        columns: Sequence[np.ndarray],
        masks: Sequence[np.ndarray | None] | None = None,
    ):
        if len(columns) != len(schema):
            raise SchemaError(
                f"{len(columns)} columns for schema of {len(schema)} fields"
            )
        self.schema = schema
        self.columns = [np.asarray(c) for c in columns]
        n = self.columns[0].shape[0] if self.columns else 0
        for f, c in zip(schema, self.columns):
            if c.shape[0] != n:
                raise SchemaError(
                    f"column {f.name!r} has {c.shape[0]} rows, expected {n}"
                )
        self.masks = list(masks) if masks is not None else [None] * len(self.columns)
        if len(self.masks) != len(self.columns):
            raise SchemaError("masks length != columns length")
        self.num_rows = n

    # -- constructors ----------------------------------------------------
    @staticmethod
    def from_pydict(
        data: Mapping[str, Sequence], schema: Schema | None = None
    ) -> "RecordBatch":
        if schema is None:
            fields, cols = [], []
            for name, vals in data.items():
                arr = _coerce_column(vals)
                fields.append(Field(name, DataType.from_numpy(arr.dtype)))
                cols.append(arr)
            return RecordBatch(Schema(fields), cols)
        cols = []
        for f in schema:
            if f.name not in data:
                raise SchemaError(f"missing column {f.name!r}")
            cols.append(np.asarray(data[f.name], dtype=f.dtype.to_numpy()))
        return RecordBatch(schema, cols)

    @staticmethod
    def empty(schema: Schema) -> "RecordBatch":
        return RecordBatch(
            schema, [np.empty(0, dtype=f.dtype.to_numpy()) for f in schema]
        )

    # -- access ----------------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        return self.columns[self.schema.index_of(name)]

    def mask(self, name: str) -> np.ndarray | None:
        return self.masks[self.schema.index_of(name)]

    def to_pydict(self) -> dict[str, list]:
        return {
            f.name: c.tolist() for f, c in zip(self.schema, self.columns)
        }

    # -- transforms ------------------------------------------------------
    def select(self, names: Sequence[str]) -> "RecordBatch":
        idx = [self.schema.index_of(n) for n in names]
        return RecordBatch(
            self.schema.select(names),
            [self.columns[i] for i in idx],
            [self.masks[i] for i in idx],
        )

    def drop(self, names: Sequence[str]) -> "RecordBatch":
        keep = [f.name for f in self.schema if f.name not in set(names)]
        return self.select(keep)

    def with_column(
        self, field: Field, col: np.ndarray, mask: np.ndarray | None = None
    ) -> "RecordBatch":
        """Append or replace a column."""
        if self.schema.has(field.name):
            i = self.schema.index_of(field.name)
            fields = list(self.schema.fields)
            fields[i] = field
            cols = list(self.columns)
            cols[i] = np.asarray(col)
            masks = list(self.masks)
            masks[i] = mask
            return RecordBatch(Schema(fields), cols, masks)
        return RecordBatch(
            self.schema.append(field),
            list(self.columns) + [np.asarray(col)],
            list(self.masks) + [mask],
        )

    def filter(self, keep: np.ndarray) -> "RecordBatch":
        keep = np.asarray(keep, dtype=bool)
        return RecordBatch(
            self.schema,
            [c[keep] for c in self.columns],
            [m[keep] if m is not None else None for m in self.masks],
        )

    def take(self, indices: np.ndarray) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            [c[indices] for c in self.columns],
            [m[indices] if m is not None else None for m in self.masks],
        )

    def slice(self, start: int, length: int) -> "RecordBatch":
        return RecordBatch(
            self.schema,
            [c[start : start + length] for c in self.columns],
            [m[start : start + length] if m is not None else None for m in self.masks],
        )

    @staticmethod
    def concat(batches: Sequence["RecordBatch"]) -> "RecordBatch":
        batches = [b for b in batches if b.num_rows > 0] or list(batches[:1])
        first = batches[0]
        cols = [
            np.concatenate([b.columns[i] for b in batches])
            for i in range(len(first.schema))
        ]
        masks = []
        for i in range(len(first.schema)):
            if any(b.masks[i] is not None for b in batches):
                masks.append(
                    np.concatenate(
                        [
                            b.masks[i]
                            if b.masks[i] is not None
                            else np.ones(b.num_rows, dtype=bool)
                            for b in batches
                        ]
                    )
                )
            else:
                masks.append(None)
        return RecordBatch(first.schema, cols, masks)

    def __repr__(self) -> str:
        return f"RecordBatch({self.num_rows} rows, {self.schema!r})"


def _coerce_column(vals: Sequence) -> np.ndarray:
    arr = np.asarray(vals)
    if arr.dtype.kind == "U":
        arr = arr.astype(object)
    if arr.dtype.kind == "O" and arr.shape[0] and isinstance(arr[0], bool):
        arr = arr.astype(bool)
    return arr
