"""Packaged cluster job factories for bench.py (cluster_scale) and
tools/soak.py (--pipeline cluster).

Worker processes import this by name ("denormalized_tpu.cluster.
benchjob:<factory>"), so the factories must rebuild the identical
deterministic source from job_args alone — the same contract as the
test jobs (tests/cluster_jobs.py), packaged so the committed artifacts
(CLUSTER_SCALE.json, SOAK_CLUSTER.json) never depend on the test tree.

The bench job uses int64 keys (vectorized hash lane, no per-row
Python); the soak job uses string keys (the crc32 lane) and
integer-valued readings so every aggregate is exact in f32
accumulators regardless of exchange arrival order — the property the
exactly-once comparison needs (docs/cluster.md#determinism).
"""

from __future__ import annotations

import time

import numpy as np

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.base import (
    PartitionReader,
    Source,
    attach_canonical_timestamp,
    canonicalize_schema,
)

T0 = 1_700_000_000_000

BENCH_SCHEMA = Schema([
    Field("k", DataType.INT64, nullable=False),
    Field("v", DataType.FLOAT64, nullable=False),
    Field("ts", DataType.TIMESTAMP_MS, nullable=False),
])

SOAK_SCHEMA = Schema([
    Field("k", DataType.STRING, nullable=False),
    Field("v", DataType.FLOAT64, nullable=False),
    Field("ts", DataType.TIMESTAMP_MS, nullable=False),
])


class _SynthReader(PartitionReader):
    """Deterministic batch generator: in-order timestamps, keys spread
    over the key space, integer readings.  Seekable (pos-based) so
    checkpoint restore replays exactly."""

    def __init__(self, part: int, args: dict, string_keys: bool) -> None:
        self.part = part
        self.args = args
        self.string_keys = string_keys
        self._pos = 0
        self._n = int(args.get("batches", 50))
        self._pace_s = float(args.get("pace_s", 0.0))

    def _batch(self, b: int) -> RecordBatch:
        a = self.args
        rows = int(a.get("rows", 8192))
        keys = int(a.get("keys", 1024))
        span = int(a.get("batch_span_ms", 250))
        base = T0 + b * span
        i = np.arange(rows, dtype=np.int64)
        ts = base + (i * span) // rows
        kid = (i * 7 + self.part * 3 + b) % keys
        v = ((i + self.part + b) % 16).astype(np.float64)
        if self.string_keys:
            k = np.array([f"s{x:05d}" for x in kid], dtype=object)
        else:
            k = kid
        schema = SOAK_SCHEMA if self.string_keys else BENCH_SCHEMA
        return RecordBatch(schema, [k, v, ts])

    def read(self, timeout_s=None):
        if self._pos >= self._n:
            return None
        if self._pace_s:
            time.sleep(self._pace_s)
        b = self._batch(self._pos)
        self._pos += 1
        return attach_canonical_timestamp(b, "ts", fallback_ms=0)

    def offset_snapshot(self) -> dict:
        return {"pos": self._pos}

    def offset_restore(self, snap: dict) -> None:
        self._pos = int(snap.get("pos", 0))


class SynthSource(Source):
    def __init__(self, args: dict, string_keys: bool) -> None:
        self._args = dict(args)
        self._string_keys = string_keys
        self.name = "cluster_bench" if not string_keys else "cluster_soak"
        self._schema = canonicalize_schema(
            SOAK_SCHEMA if string_keys else BENCH_SCHEMA
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def unbounded(self) -> bool:
        return False

    def partitions(self) -> list[PartitionReader]:
        return [
            _SynthReader(p, self._args, self._string_keys)
            for p in range(int(self._args.get("partitions", 4)))
        ]


def _pipeline(ds, args: dict):
    from denormalized_tpu import col
    from denormalized_tpu.api import functions as F

    return ds.window(
        [col("k")],
        [
            F.count(col("v")).alias("count"),
            F.sum(col("v")).alias("total"),
            F.min(col("v")).alias("lo"),
            F.max(col("v")).alias("hi"),
        ],
        int(args.get("window_ms", 1000)),
    )


def bench_job(args: dict) -> dict:
    return {
        "source": SynthSource(args, string_keys=False),
        "pipeline": lambda ds: _pipeline(ds, args),
        "engine": args.get("engine") or {},
    }


def soak_job(args: dict) -> dict:
    return {
        "source": SynthSource(args, string_keys=True),
        "pipeline": lambda ds: _pipeline(ds, args),
        "engine": args.get("engine") or {},
    }


def oracle_rows(args: dict, string_keys: bool) -> list[tuple]:
    """Uninterrupted single-process oracle → canonical sorted tuples."""
    from denormalized_tpu.api.context import Context, EngineConfig
    from denormalized_tpu.common.constants import (
        WINDOW_END_COLUMN,
        WINDOW_START_COLUMN,
    )

    config = EngineConfig()
    config.partition_watermarks = True
    ctx = Context(config)
    src = SynthSource(args, string_keys=string_keys)
    got = _pipeline(ctx.from_source(src), args).collect()
    out = []
    for i in range(got.num_rows):
        out.append((
            int(got.column(WINDOW_START_COLUMN)[i]),
            int(got.column(WINDOW_END_COLUMN)[i]),
            str(got.column("k")[i]),
            int(got.column("count")[i]),
            float(got.column("total")[i]),
            float(got.column("lo")[i]),
            float(got.column("hi")[i]),
        ))
    return sorted(out)


def canonical_row(rec: dict) -> tuple:
    from denormalized_tpu.common.constants import (
        WINDOW_END_COLUMN,
        WINDOW_START_COLUMN,
    )

    return (
        int(rec[WINDOW_START_COLUMN]),
        int(rec[WINDOW_END_COLUMN]),
        str(rec["k"]),
        int(rec["count"]),
        float(rec["total"]),
        float(rec["lo"]),
        float(rec["hi"]),
    )
