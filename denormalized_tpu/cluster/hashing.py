"""Stable cross-process key hashing + partition assignment.

The ONE hash map of the cluster: exchange routing (which worker owns a
row's group key) and rescale-on-restore (which new worker inherits a
checkpointed group's accumulators) must agree bit-for-bit, across
processes and across engine versions — Python's builtin ``hash`` is
per-process salted and therefore banned here (dnzlint DNZ-H002 keeps it
out of the pinned kernels too).

``hash_rows`` is vectorized for numeric key columns (a splitmix64-style
finalizer over the canonical uint64 reinterpretation); object (string)
columns fall back to a per-row crc32 loop in a separate, deliberately
unpinned helper.
"""

from __future__ import annotations

import zlib

import numpy as np

# splitmix64 finalizer constants (Stafford mix13)
_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)
_S = np.uint64(33)
_COMBINE = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio increment


def _mix64(x: np.ndarray) -> np.ndarray:
    """Stafford variant-13 finalizer, elementwise over uint64 (wrapping
    multiply is numpy's unsigned semantics — exactly what we want)."""
    x = x ^ (x >> _S)
    x = x * _M1
    x = x ^ (x >> _S)
    x = x * _M2
    x = x ^ (x >> _S)
    return x


def _object_column_u64(col: np.ndarray) -> np.ndarray:
    """Per-row canonical hash of an object (string) column — the slow
    lane, kept OUT of the pinned kernels on purpose: strings have no
    vectorized canonical form, and a crc32 loop at intern-scale rates is
    the honest cost of string group keys over the exchange."""
    out = np.empty(len(col), dtype=np.uint64)
    for i, v in enumerate(col):
        if isinstance(v, bytes):
            b = v
        else:
            b = str(v).encode("utf-8", "surrogatepass")
        out[i] = zlib.crc32(b)
    return out


def _string_column_u64(col) -> np.ndarray:
    """crc32 lane for columnar strings: hashes each row's UTF-8 bytes
    STRAIGHT off the offsets+bytes buffers — per-row crc32 like the
    object lane (and bit-identical to it for the same logical values,
    so rescale across lanes re-buckets identically: a valid UTF-8 str's
    encoded bytes ARE its column bytes, and a null hashes b'None' just
    like the object lane str()s None) — but with no Python str ever
    materialized."""
    out = np.empty(len(col), dtype=np.uint64)
    mv = memoryview(np.ascontiguousarray(col.data))
    offs = col.offsets.tolist()
    valid = col.validity.tolist() if col.validity is not None else None
    for i in range(len(col)):
        if valid is not None and not valid[i]:
            out[i] = zlib.crc32(b"None")
        else:
            out[i] = zlib.crc32(mv[offs[i]: offs[i + 1]])
    return out


def column_u64(col: np.ndarray) -> np.ndarray:
    """Canonical uint64 reinterpretation of one key column.

    ints/bools/timestamps go through int64 (sign-preserving two's
    complement view); floats through float64 bit patterns with -0.0
    normalized to +0.0 so the two equal keys hash identically; object
    columns through the crc32 lane."""
    from denormalized_tpu.common.columns import Column, StringColumn

    if isinstance(col, StringColumn):
        return _string_column_u64(col)
    if isinstance(col, Column):
        # nested key columns: materialize (grouping by a whole struct is
        # a legacy corner, not a hot path)
        col = col.as_object()
    a = np.asarray(col)
    if a.dtype == object:
        return _object_column_u64(a)
    if a.dtype.kind == "f":
        f = a.astype(np.float64, copy=False)
        f = f + 0.0  # -0.0 -> +0.0; NaNs keep their payload bits
        return f.view(np.uint64)
    if a.dtype.kind == "b":
        return a.astype(np.uint64)
    return a.astype(np.int64, copy=False).view(np.uint64)


def hash_rows(key_columns: list) -> np.ndarray:
    """Row-wise stable hash over one or more key columns → uint64.

    The exchange router and the rescale re-bucketer both call this; the
    column list must be the operator's group-key columns in group-expr
    order (order matters — it is part of the hash)."""
    h = np.zeros(len(key_columns[0]), dtype=np.uint64)
    for col in key_columns:  # dnzlint: allow(hot-loop) bounded per-KEY-COLUMN sweep (group-expr arity, typically 1-3), each iteration fully vectorized over rows
        h = _mix64(h + _COMBINE + column_u64(col))
    return h


def bucket_rows(key_columns: list, n_buckets: int) -> np.ndarray:
    """``hash(key) % n_buckets`` per row, as int64 worker indices."""
    return (hash_rows(key_columns) % np.uint64(n_buckets)).astype(np.int64)


def partitions_for(worker: int, n_workers: int, n_partitions: int) -> list[int]:
    """Engine-owned static partition assignment: worker w owns global
    partitions ``{w, w+N, w+2N, ...}`` — the one rule sources, offset
    rescale, and docs all share (docs/cluster.md#partition-assignment)."""
    if not (0 <= worker < n_workers):
        raise ValueError(f"worker {worker} out of range for N={n_workers}")
    return list(range(worker, n_partitions, n_workers))
