"""Rescale-on-restore: re-bucket a cluster checkpoint across a changed
worker count.

Given the cluster-committed epoch E and the old layout (N_old workers,
one LSM store each), this module streams every worker's state blobs and
rewrites them for N_new workers under the SAME epoch:

- **source offsets** remap exactly: reader ``i`` of old worker ``w`` is
  global partition ``w + i*N_old`` (cluster/hashing.partitions_for), so
  the per-partition cursors regroup losslessly under the new
  assignment;
- **windowed-aggregation state** re-buckets per GROUP: each group's
  accumulator planes move whole (hash partitioning means a key's
  accumulators live on exactly one worker, before and after), keyed by
  ``hash_rows(group key) % N_new`` — the same function the exchange
  router applies to live rows, evaluated over the checkpointed
  interner's key tuples coerced back to their original column dtypes;
- **spilled window planes** (PR-9 tier blocks referenced by the epoch)
  merge back into the resident ring first — ``first_open`` lowers to
  cover them, exactly like the budget-removed restore path — and the
  restored worker's tier re-evicts under its own budget, rebuilding the
  tier map under the new hash map.

Bit-exactness: accumulators are never re-aggregated, only permuted, so
a rescaled restore emits byte-identical windows to an uninterrupted
run (pinned by tests/test_cluster_rescale.py).  Variance aggregates
carry a per-operator shift pivot that is NOT mergeable across workers
when pivots diverge — that case fails loudly rather than emit subtly
wrong variances.

Non-window keyed state (session/UDAF/join) restores at the same worker
count only; rescaling it is future work (docs/cluster.md#limitations).
"""

from __future__ import annotations

import os

import numpy as np

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.cluster.hashing import bucket_rows, partitions_for


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _interner_key_tuples(snap: dict) -> list[tuple]:
    """GroupInterner snapshot → per-gid key-value tuples."""
    columns = snap["columns"]
    rows = snap["rows"]
    return [
        tuple(columns[c][vid] for c, vid in enumerate(row))
        for row in rows
    ]


def _typed_key_columns(
    key_tuples: list[tuple], key_dtypes: list[str]
) -> list[np.ndarray]:
    """Key tuples → columns coerced back to the dtypes the exchange
    router hashed, so ``hash_rows`` agrees bit-for-bit with routing."""
    cols = []
    for c, dt in enumerate(key_dtypes):
        vals = [k[c] for k in key_tuples]
        if dt == "obj":
            a = np.empty(len(vals), dtype=object)
            a[:] = vals
        else:
            a = np.array(vals, dtype=np.dtype(dt))
        cols.append(a)
    return cols


def _interner_snapshot_from_tuples(key_tuples: list[tuple]) -> dict:
    """Fresh GroupInterner snapshot with gids in list order (first-seen
    per-column value interning, matching GroupInterner semantics)."""
    if not key_tuples:
        return {"columns": [], "rows": []}
    n_cols = len(key_tuples[0])
    col_values: list[list] = [[] for _ in range(n_cols)]
    col_ids: list[dict] = [{} for _ in range(n_cols)]
    rows = []
    for kt in key_tuples:
        row = []
        for c, v in enumerate(kt):
            vid = col_ids[c].get(v)
            if vid is None:
                vid = len(col_values[c])
                col_ids[c][v] = vid
                col_values[c].append(v)
            row.append(vid)
        rows.append(tuple(row))
    return {"columns": col_values, "rows": rows}


class _WindowContribution:
    """One old worker's window state, rebased to absolute window index
    (spilled planes merged resident)."""

    def __init__(self, meta: dict, arrays: dict, spill_planes: dict):
        self.meta = meta
        self.key_tuples = _interner_key_tuples(meta["interner"])
        w = int(meta["window_slots"])
        first = meta["first_open"]
        last = meta["max_win_seen"]
        spill_js = sorted(int(j) for j in spill_planes)
        if spill_js:
            first = min([first] + spill_js) if first is not None \
                else spill_js[0]
        self.first_open = first
        self.max_win_seen = last
        self.watermark_ms = meta.get("watermark_ms")
        # absolute window index -> {label: [G] row vector}
        self.planes: dict[int, dict[str, np.ndarray]] = {}
        if first is not None and last is not None:
            for j in range(first, last + 1):
                self.planes[j] = {
                    label: arr[j % w] for label, arr in arrays.items()
                }
        for j in spill_js:
            self.planes[j] = spill_planes[j]

    @property
    def n_groups(self) -> int:
        return len(self.key_tuples)


def _load_contribution(coord, window_key: str) -> _WindowContribution | None:
    from denormalized_tpu.state.serialization import unpack_snapshot

    blob = coord.get_snapshot(window_key)
    if blob is None:
        return None  # this worker had no keyed snapshot at the epoch
    meta, arrays = unpack_snapshot(blob)
    if meta.get("interner") is None:
        raise StateError(
            "rescale: window snapshot has no group interner (global "
            "aggregate) — nothing to re-bucket; run at the same worker "
            "count"
        )
    spill_planes: dict[int, dict] = {}
    refs = meta.get("spill_windows") or {}
    for j_str, block_id in refs.items():
        raw = coord.get_snapshot(f"{window_key}:spill:{block_id}")
        if raw is None:
            raise StateError(
                f"rescale: epoch references spilled window {j_str} "
                "but its block snapshot is missing"
            )
        _bmeta, block_arrays = unpack_snapshot(raw)
        spill_planes[int(j_str)] = dict(block_arrays)
    return _WindowContribution(meta, arrays, spill_planes)


def _merge_var_shift(contribs: list[_WindowContribution]) -> dict:
    merged: dict = {}
    for c in contribs:
        for k, v in (c.meta.get("var_shift") or {}).items():
            if k in merged and merged[k] != v:
                raise StateError(
                    "rescale: variance shift pivots diverge across "
                    f"workers for aggregate {k!r} — variance state is "
                    "not mergeable under rescale (docs/cluster.md)"
                )
            merged[k] = v
    return merged


def _build_target_snapshot(
    parts: list[tuple[_WindowContribution, np.ndarray]], epoch: int
) -> tuple[dict, dict] | None:
    """Assemble one NEW worker's window snapshot from (contribution,
    kept-gid-indices) pairs.  Returns (meta, arrays) or None when no
    groups land here."""
    total = sum(len(sel) for _c, sel in parts)
    live = [(c, sel) for c, sel in parts if len(sel)]
    if total == 0 or not live:
        return None
    firsts = [c.first_open for c, _s in live if c.first_open is not None]
    lasts = [
        c.max_win_seen for c, _s in live if c.max_win_seen is not None
    ]
    wms = [c.watermark_ms for c, _s in live if c.watermark_ms is not None]
    if not firsts or not lasts:
        # groups interned but every window already emitted at the cut
        # (watermark closed them all): a valid, plane-less snapshot —
        # restore starts pre-first-batch with the interner intact
        first = last = None
        w_new = 16
    else:
        first = min(firsts)
        last = max(lasts)
        span = last - first + 1
        w_new = max(_next_pow2(span + 1), 16)
    g_cap = max(_next_pow2(total), 128)
    labels = {
        label
        for c, _s in live
        for planes in c.planes.values()
        for label in planes
    }
    arrays: dict[str, np.ndarray] = {}
    key_tuples: list[tuple] = []
    offset = 0
    for c, sel in live:
        key_tuples.extend(c.key_tuples[i] for i in sel)
        for j, planes in c.planes.items():
            if first is None or not (first <= j <= last):
                continue
            slot = j % w_new
            # sorted: label order here IS the arrays-dict insertion
            # order, which pack_snapshot serializes — set order would
            # make the rebuilt snapshot bytes hash-seed-dependent
            for label in sorted(labels):
                row = planes.get(label)
                if row is None:
                    continue
                dst = arrays.get(label)
                if dst is None:
                    dst = np.zeros((w_new, g_cap), dtype=row.dtype)
                    arrays[label] = dst
                if len(sel) and int(sel.max()) >= row.shape[0]:
                    # a plane captured before these groups existed (e.g.
                    # a spilled block) is implicitly zero for them — pad
                    # so gid positions stay aligned with the selection
                    padded = np.zeros(int(sel.max()) + 1, dtype=row.dtype)
                    padded[:row.shape[0]] = row
                    row = padded
                dst[slot, offset:offset + len(sel)] = row[sel]
        offset += len(sel)
    meta = {
        "epoch": epoch,
        "first_open": int(first) if first is not None else None,
        "max_win_seen": int(last) if last is not None else -1,
        "watermark_ms": int(min(wms)) if wms else None,
        "window_slots": int(w_new),
        "group_capacity": int(g_cap),
        "interner": _interner_snapshot_from_tuples(key_tuples),
        "var_shift": _merge_var_shift([c for c, _s in live]),
        "any_nulls_seen": any(
            c.meta.get("any_nulls_seen", True) for c, _s in live
        ),
    }
    return meta, arrays


def rescale_cluster(
    coordinator, manifest: dict, epoch: int, new_n: int, new_version: int
) -> None:
    """Re-bucket the committed cluster cut at ``epoch`` from
    ``manifest['n_workers']`` workers into ``new_n`` fresh stores under
    ``state/v<new_version>/`` — each written as a committed, manifested
    checkpoint at the SAME epoch, so the new workers restore through the
    exact same pinned path an unchanged restart uses."""
    from denormalized_tpu.cluster.worker import PinnedCheckpointCoordinator
    from denormalized_tpu.state.checkpoint import get_json, put_json
    from denormalized_tpu.state.lsm import LsmStore
    from denormalized_tpu.state.serialization import pack_snapshot

    old_n = int(manifest["n_workers"])
    old_version = int(manifest["store_version"])
    n_partitions = int(manifest["n_partitions"])
    state_keys = manifest.get("state_keys") or {}
    offsets_key = state_keys.get("offsets")
    keyed_key = state_keys.get("keyed")
    key_dtypes = manifest.get("key_dtypes") or []
    if keyed_key is not None and not keyed_key.startswith("window_"):
        raise StateError(
            f"rescale: keyed state {keyed_key!r} is not windowed-"
            "aggregation state — session/UDAF/join rescale is not "
            "implemented; restore at the original worker count "
            f"(N={old_n}) instead"
        )

    # -- read the old cut --------------------------------------------------
    global_offsets: dict[int, dict] = {}
    contribs: list[_WindowContribution | None] = []
    stores: list[LsmStore] = []
    try:
        for w in range(old_n):
            store = LsmStore(coordinator.store_dir(old_version, w))
            stores.append(store)
            coord = PinnedCheckpointCoordinator(store, epoch)
            if offsets_key:
                snap = get_json(coord, offsets_key)
                if snap is None:
                    raise StateError(
                        f"rescale: worker {w} has no offsets snapshot "
                        f"at epoch {epoch}"
                    )
                pids = partitions_for(w, old_n, n_partitions)
                parts = snap.get("partitions", [])
                if len(parts) != len(pids):
                    raise StateError(
                        f"rescale: worker {w} offsets cover "
                        f"{len(parts)} partitions, assignment expects "
                        f"{len(pids)}"
                    )
                for pid, s in zip(pids, parts):
                    global_offsets[pid] = s
            contribs.append(
                _load_contribution(coord, keyed_key)
                if keyed_key else None
            )

        # -- bucket groups under the new hash map -------------------------
        assignments: list[list[np.ndarray]] = []  # [old_w][new_t] -> gids
        for c in contribs:
            if c is None or c.n_groups == 0:
                assignments.append(
                    [np.empty(0, dtype=np.int64) for _ in range(new_n)]
                )
                continue
            cols = _typed_key_columns(c.key_tuples, key_dtypes)
            buckets = bucket_rows(cols, new_n)
            assignments.append([
                np.nonzero(buckets == t)[0].astype(np.int64)
                for t in range(new_n)
            ])

        # -- write the new stores -----------------------------------------
        for t in range(new_n):
            store_path = coordinator.store_dir(new_version, t)
            os.makedirs(store_path, exist_ok=True)
            new_store = LsmStore(store_path)
            try:
                new_coord = PinnedCheckpointCoordinator(new_store, None)
                if offsets_key:
                    pids = partitions_for(t, new_n, n_partitions)
                    missing = [p for p in pids if p not in global_offsets]
                    if missing:
                        raise StateError(
                            f"rescale: no offsets for partitions "
                            f"{missing} in the old cut"
                        )
                    put_json(
                        new_coord, offsets_key, epoch,
                        {
                            "epoch": epoch,
                            "partitions": [
                                global_offsets[p] for p in pids
                            ],
                        },
                    )
                if keyed_key:
                    parts = [
                        (c, assignments[w][t])
                        for w, c in enumerate(contribs)
                        if c is not None
                    ]
                    built = _build_target_snapshot(parts, epoch)
                    if built is not None:
                        meta, arrays = built
                        new_coord.put_snapshot(
                            keyed_key, epoch,
                            pack_snapshot(meta, arrays),
                        )
                new_coord.commit(epoch)
            finally:
                new_store.close()
    finally:
        for s in stores:
            s.close()
