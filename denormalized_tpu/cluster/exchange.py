"""Exchange plumbing: unix-domain sockets between worker processes.

Topology: every worker hosts one **server** socket and dials one
**client** connection to every other worker — worker w's keyed operator
therefore has N inbound *edges*: N-1 sockets plus a zero-copy loopback
from its own ingest half.  Frames (cluster/framing.py) flow sender →
receiver only; there is no request/response.

The receive side runs one thread per inbound connection, decoding frames
into a bounded per-edge queue — the queue bound (plus the kernel socket
buffer) IS the exchange's backpressure, exactly like the prefetch
pump's per-partition double buffer.  The :class:`EdgeMerger` is the
single consumer: it merges data across edges, merges **watermarks** as
the min over per-edge watermarks (an edge's watermark advances via
piggybacked data-frame watermarks and explicit wm frames), aligns
**barriers** (an edge that delivered barrier E is not consumed again
until every live edge delivered E — the aligned Chandy-Lamport cut,
same invariant the join operator enforces per-epoch), and collapses to
EOS when every edge reports it.

Failure model: **integrity** violations stay fail-stop (a torn or
corrupt frame kills the worker that observed it — under partial
recovery the coordinator then respawns only that worker), but
**connectivity** failures are survivable when the spec enables
``partial_recovery``: a send on a dead edge buffers-or-backpressures
behind a bounded-exponential-backoff reconnect, a receiver whose peer
vanished marks the edge *down* (``dnz_exchange_edges_down``) and keeps
merging the other edges while the dead peer's watermark holds the min.
Every client keeps a bounded **replay buffer** of frames since the
last cluster-committed barrier (pruned on commit notifications); the
rejoin handshake (hello → resume, cluster/framing.py) picks one of
three replay modes — same-generation tear-heal (resend frames the
receiver never processed), reborn-sender dedup (receiver reports rows
per partition already delivered since the pinned epoch; the router
skips exactly that prefix), or reborn-receiver full replay (resend
everything since the last committed barrier).  Anything the handshake
cannot prove exact — ledger gap, evicted buffer, unstamped batches —
raises a ``SourceError`` tagged ``cluster_fallback`` and the
coordinator falls back to the documented full-cluster restart: graceful
degradation, never a new wedge class (docs/cluster.md#rejoin).

Fault sites ``exchange.connect`` / ``exchange.send`` /
``exchange.recv`` / ``exchange.reconnect`` / ``cluster.replay``
(runtime/faults.py) make every one of those paths reproducible on
demand; ``exchange.send`` and ``cluster.replay`` support ``torn``
rules — the truncated frame is genuinely written before the connection
drops, so the RECEIVER exercises its tear detection, not just the
sender its error path.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.runtime import faults
from denormalized_tpu.cluster import framing

#: per-edge inbound queue bound (items, mostly data frames): with the
#: socket buffer this bounds memory while a barrier-blocked edge waits
EDGE_QUEUE_ITEMS = 16

_CONNECT_TIMEOUT_S = 30.0

#: bounded exponential backoff for edge reconnects (seconds)
_RECONNECT_BACKOFF_S = (0.05, 1.6)


def cluster_fallback_error(msg: str) -> SourceError:
    """A failure partial recovery cannot absorb exactly — the worker
    reports it with ``fallback="cluster"`` and the coordinator takes
    the documented full-cluster restart instead of a partial respawn."""
    e = SourceError(f"{msg} [cluster-restart-fallback]")
    e.cluster_fallback = True
    return e


class ExchangeClient:
    """One outbound edge: this worker's ingest half → peer ``dst``.

    With ``partial=True`` the edge is *reconnectable*: every frame is
    appended to a bounded replay buffer before it is written (pruned
    when the coordinator announces a cluster commit), a failed write
    triggers bounded-exponential-backoff redial, and the peer's resume
    frame decides what to resend — see the module docstring for the
    three replay modes."""

    def __init__(
        self,
        src: int,
        dst: int,
        sock_path: str,
        gen: int = 0,
        restore_epoch: int = 0,
        partial: bool = False,
        replay_buffer_bytes: int = 64 << 20,
        reconnect_deadline_s: float = 60.0,
    ) -> None:
        from denormalized_tpu import obs

        self.src = src
        self.dst = dst
        self.sock_path = sock_path
        self.gen = int(gen)
        self.restore_epoch = int(restore_epoch)
        self.partial = bool(partial)
        self.reconnect_deadline_s = reconnect_deadline_s
        self.edge = f"{src}->{dst}"
        self._sock: socket.socket | None = None
        # replay buffer: (idx, kind, epoch, frame_bytes) since the last
        # cluster-committed barrier; idx is the frame's position in this
        # sender generation's stream (implicit sequence number)
        self._buf: list[tuple[int, str, int | None, bytes]] = []
        self._buf_bytes = 0
        self._buf_cap = int(replay_buffer_bytes)
        self._buf_lock = threading.Lock()
        self._replay_ok = True
        self._sent_idx = 0
        # rows per global partition the receiver already holds since my
        # restore epoch (reborn-sender dedup ledger, set from resume)
        self._skip: dict[int, int] = {}
        self._obs_frames = obs.counter(
            "dnz_exchange_frames_total", dir="send", edge=self.edge
        )
        self._obs_bytes = obs.counter(
            "dnz_exchange_bytes_total", dir="send", edge=self.edge
        )
        self._obs_send_ms = obs.histogram(
            "dnz_exchange_send_ms", edge=self.edge
        )
        self._obs_reconnects = obs.counter(
            "dnz_exchange_reconnects_total", edge=self.edge
        )
        self._obs_replayed = obs.counter(
            "dnz_exchange_replayed_frames_total", edge=self.edge
        )

    def connect(self, deadline_s: float = _CONNECT_TIMEOUT_S) -> None:
        """Dial the peer's server socket (which may not be listening yet
        — workers start concurrently), identify this edge with a hello
        frame, then read the peer's resume frame and resend whatever it
        proves undelivered.  Retries cover startup races only; an
        injected fault or the deadline fails the worker outright."""
        faults.inject("exchange.connect", key=self.edge)
        self._dial_and_resume(deadline_s, reconnect=False)

    def _dial_and_resume(self, deadline_s: float, reconnect: bool) -> None:
        """Dial + hello + read resume, retrying handshake failures
        (peer not listening yet, peer mid-restart, injected
        ``exchange.reconnect`` faults) with bounded exponential backoff
        until ``deadline_s``.  Replay-phase errors are NOT retried —
        a tagged fallback or a torn replay frame propagates."""
        deadline = time.monotonic() + deadline_s
        backoff = _RECONNECT_BACKOFF_S[0]
        last: Exception | None = None
        while time.monotonic() < deadline:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                if reconnect:
                    faults.inject("exchange.reconnect", key=self.edge)
                s.connect(self.sock_path)
                s.settimeout(10.0)
                s.sendall(framing.encode_hello(
                    self.src, self.gen, self.restore_epoch
                ))
                payload = framing.read_frame(s)
                if payload is None:
                    raise SourceError(
                        f"exchange peer on {self.edge} closed before resume"
                    )
                resume = framing.decode_frame(payload, None)
                if resume[0] != "resume":
                    raise SourceError(
                        f"exchange peer on {self.edge} answered hello "
                        f"with {resume[0]!r}"
                    )
                s.settimeout(None)
            except (OSError, socket.timeout, SourceError) as e:
                s.close()
                last = e
                time.sleep(backoff)
                backoff = min(backoff * 2, _RECONNECT_BACKOFF_S[1])
                continue
            self._sock = s
            self._apply_resume(resume)
            return
        raise SourceError(
            f"exchange connect {self.edge} failed after {deadline_s}s: {last}"
        )

    def _apply_resume(self, resume: tuple) -> None:
        """Resolve the receiver's resume frame into a replay plan and
        execute it — see the module docstring for the three modes."""
        _, gen_seen, frames_seen, _epoch, counts, counts_ok = resume
        if gen_seen == self.gen:
            # same-generation tear-heal: resend exactly the frames the
            # receiver never fully processed
            with self._buf_lock:
                needed = [e for e in self._buf if e[0] >= frames_seen]
                replay_ok = self._replay_ok
            if needed and (
                not replay_ok or needed[0][0] != frames_seen
            ):
                raise cluster_fallback_error(
                    f"exchange edge {self.edge} cannot tear-heal: replay "
                    f"buffer no longer covers frame {frames_seen}"
                )
            self._replay(needed)
            return
        if gen_seen >= 0:
            # I am a reborn sender talking to a receiver that survived:
            # it reports rows per partition already delivered since my
            # pinned epoch; the router skips exactly that prefix
            if not counts_ok:
                raise cluster_fallback_error(
                    f"exchange edge {self.edge} rejoin: receiver cannot "
                    "attribute delivered rows to partitions"
                )
            self._skip = {int(k): int(v) for k, v in counts.items()}
            return
        # fresh receiver (reborn, or first contact): resend everything
        # since the last cluster-committed barrier — which is exactly
        # what the pruned buffer holds
        with self._buf_lock:
            needed = list(self._buf)
            replay_ok = self._replay_ok
        if needed and not replay_ok:
            raise cluster_fallback_error(
                f"exchange edge {self.edge} cannot replay to reborn "
                "receiver: buffer was evicted past the committed barrier"
            )
        self._replay(needed)

    def _replay(self, entries: list[tuple]) -> None:
        """Resend buffered frames verbatim on the fresh connection.
        ``cluster.replay`` is torn-capable: a truncated replay frame is
        genuinely written, then this worker fails — the same
        fail-stop-per-worker contract as a torn first send."""
        for _idx, _kind, _epoch, frame in entries:
            payload = faults.inject(
                "cluster.replay", key=self.edge, payload=frame
            )
            self._sock.sendall(payload)
            if len(payload) != len(frame):
                self.close()
                raise SourceError(
                    f"exchange replay frame torn by fault injection on "
                    f"{self.edge} ({len(payload)}/{len(frame)} bytes)"
                )
            self._obs_replayed.add(1)

    def take_skip(self, part: int, n_rows: int) -> int:
        """Rows the router must drop from the front of this partition's
        next batch bound for ``dst`` (reborn-sender dedup)."""
        have = self._skip.get(part, 0)
        if not have:
            return 0
        s = min(have, n_rows)
        self._skip[part] = have - s
        return s

    def skip_residual(self) -> dict[int, int]:
        """Undrained dedup skip per partition — piggybacked on barrier
        frames so the receiver's per-epoch ledger snapshot accounts for
        the replay position lagging the delivered frontier."""
        return {p: n for p, n in self._skip.items() if n > 0}

    def note_commit(self, epoch: int) -> None:
        """Coordinator announced cluster commit ``epoch``: every
        receiver provably processed this edge's barrier-``epoch`` frame
        (or drained it to EOS), so everything up to that frame can never
        be needed for replay again."""
        with self._buf_lock:
            cut = None
            saw_eos = None
            for i, (_idx, kind, ep, _f) in enumerate(self._buf):
                if kind == "barrier" and ep == epoch:
                    cut = i
                if kind == "eos":
                    saw_eos = i
            if cut is not None:
                dropped = self._buf[: cut + 1]
            elif saw_eos is not None:
                # sender hit EOS before this barrier was issued: every
                # acking receiver drained the edge, so only the EOS
                # frame itself must remain reachable for reborn peers
                dropped = self._buf[:saw_eos]
            else:
                return
            self._buf = self._buf[len(dropped):]
            self._buf_bytes -= sum(len(f) for _, _, _, f in dropped)

    def _buffer(self, kind: str, epoch: int | None, frame: bytes) -> None:
        with self._buf_lock:
            self._buf.append((self._sent_idx, kind, epoch, frame))
            self._buf_bytes += len(frame)
            while self._buf_bytes > self._buf_cap and len(self._buf) > 1:
                idx, k, ep, f = self._buf.pop(0)
                self._buf_bytes -= len(f)
                if k != "eos":
                    # evicted un-committed frames: replay is no longer
                    # exact, escalate to full restart if ever needed
                    self._replay_ok = False

    def send(
        self, frame: bytes, kind: str = "data", epoch: int | None = None
    ) -> None:
        """Write one frame (buffering it first when reconnectable).  A
        ``torn`` fault rule truncates the bytes actually written and
        then drops the connection, so the tear is observed where real
        tears are: at the receiver.  A plain connection failure under
        ``partial`` redials with bounded exponential backoff and lets
        the resume handshake resend the tail — the blocked ingest
        thread IS the backpressure against a down edge."""
        if self._sock is None:
            raise SourceError(f"exchange edge {self.edge} not connected")
        if self.partial:
            self._buffer(kind, epoch, frame)
        t0 = time.perf_counter()
        payload = faults.inject("exchange.send", key=self.edge, payload=frame)
        try:
            self._sock.sendall(payload)
        except OSError as e:
            if not self.partial:
                raise SourceError(
                    f"exchange send on {self.edge} failed: {e}"
                ) from e
            self._reconnect(e)
        if len(payload) != len(frame):
            # the torn prefix is on the wire; kill the connection so the
            # receiver sees a mid-frame EOF/CRC failure, then fail this
            # worker — exactly what a mid-send process death looks like
            self.close()
            raise SourceError(
                f"exchange frame torn by fault injection on {self.edge} "
                f"({len(payload)}/{len(frame)} bytes written)"
            )
        self._sent_idx += 1
        self._obs_frames.add(1)
        self._obs_bytes.add(len(frame))
        self._obs_send_ms.observe((time.perf_counter() - t0) * 1e3)

    def _reconnect(self, cause: Exception) -> None:
        """Redial a down edge until ``reconnect_deadline_s``; the resume
        handshake replays the buffered tail (including the frame whose
        write just failed — it was buffered before the attempt).  Past
        the deadline the worker escalates to the full-cluster fallback
        rather than stall forever."""
        self.close()
        self._obs_reconnects.add(1)
        try:
            self._dial_and_resume(
                self.reconnect_deadline_s, reconnect=True
            )
        except SourceError as e:
            if getattr(e, "cluster_fallback", False):
                raise
            raise cluster_fallback_error(
                f"exchange edge {self.edge} down past "
                f"{self.reconnect_deadline_s}s reconnect budget "
                f"(send failed: {cause}; last: {e})"
            ) from e

    def close(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass


class EdgeState:
    """Receiver-side state of one inbound edge.

    Beyond the merge state (queue / watermark / alignment / EOS), an
    edge carries the **rejoin ledgers**: the sender generation last
    heard from, how many post-hello frames of that generation were
    fully processed (the implicit sequence number), cumulative rows
    delivered per global source partition, and a snapshot of those
    counts at every barrier — ``counts - barrier_marks[C]`` is exactly
    what a sender reborn at epoch C must skip.  The counts survive
    sender generations (they ledger *deliveries*, not connections)."""

    __slots__ = (
        "edge_id", "queue", "wm", "aligned", "eos", "depth_gauge",
        "gen", "frames_seen", "part_counts", "barrier_marks",
        "counts_ok", "down", "conn", "settled",
    )

    def __init__(self, edge_id: int, depth_gauge) -> None:
        self.edge_id = edge_id
        self.queue: queue.Queue = queue.Queue(maxsize=EDGE_QUEUE_ITEMS)
        self.wm: int | None = None
        self.aligned = False  # delivered the in-flight barrier epoch
        self.eos = False
        self.depth_gauge = depth_gauge
        self.gen = -1  # sender generation last seen (-1 = never)
        self.frames_seen = 0  # frames fully processed from that gen
        self.part_counts: dict[int, int] = {}
        self.barrier_marks: dict[int, dict[int, int]] = {}
        self.counts_ok = True  # False once an unstamped batch arrives
        self.down = False
        self.conn = None
        self.settled = threading.Event()
        self.settled.set()


class ExchangeServer:
    """This worker's inbound half: accepts N-1 peer connections, runs
    one decode thread per connection, and exposes the per-edge queues to
    the :class:`EdgeMerger`."""

    def __init__(
        self,
        worker_id: int,
        n_workers: int,
        sock_path: str,
        schema,
        partial: bool = False,
        last_commit: int = 0,
    ) -> None:
        from denormalized_tpu import obs

        self.worker_id = worker_id
        self.n_workers = n_workers
        self.schema = schema
        self.sock_path = sock_path
        self.partial = bool(partial)
        self.last_commit = int(last_commit)
        self.edges: dict[int, EdgeState] = {
            w: EdgeState(
                w,
                obs.gauge(
                    "dnz_exchange_edge_depth", edge=f"{w}->{worker_id}"
                ),
            )
            for w in range(n_workers)
        }
        self._obs_frames = obs.counter(
            "dnz_exchange_frames_total", dir="recv",
            edge=f"*->{worker_id}",
        )
        self._obs_bytes = obs.counter(
            "dnz_exchange_bytes_total", dir="recv",
            edge=f"*->{worker_id}",
        )
        self._obs_down = obs.gauge(
            "dnz_exchange_edges_down", worker=str(worker_id)
        )
        self.wake = threading.Event()
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(sock_path)
        self._listener.listen(n_workers)
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"exch-accept-{worker_id}",
            daemon=True,
        )
        self._accept_thread.start()

    def note_commit(self, epoch: int) -> None:
        """Coordinator announced cluster commit ``epoch``: barrier
        snapshots older than it can never anchor a rejoin again."""
        self.last_commit = max(self.last_commit, int(epoch))
        for e in self.edges.values():
            for k in [k for k in e.barrier_marks if k < epoch]:
                del e.barrier_marks[k]

    def _set_down_gauge(self) -> None:
        self._obs_down.set(
            sum(1 for e in self.edges.values() if e.down)
        )

    # -- loopback (ingest half of THIS worker) ---------------------------
    def local_put(self, item: tuple) -> None:
        """Zero-copy enqueue from this worker's own ingest half — no
        socket, no framing, no fault site (the in-process edge is not an
        I/O boundary)."""
        edge = self.edges[self.worker_id]
        edge.queue.put(item)
        edge.depth_gauge.set(edge.queue.qsize())
        self.wake.set()

    # -- socket side ------------------------------------------------------
    def _accept_loop(self) -> None:
        """Accept until stopped — NOT just n_workers-1 connections: a
        reconnecting or reborn sender dials the same listener, and its
        hello re-binds the existing edge (ledgers intact)."""
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed during shutdown
            t = threading.Thread(
                target=self._recv_loop, args=(conn,),
                name=f"exch-recv-{self.worker_id}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _bind_conn(self, conn: socket.socket, wid: int, gen: int,
                   restore: int) -> EdgeState:
        """Re-bind an edge to a fresh connection and answer the hello
        with a resume frame.  If an older connection is still attached
        (the sender redialed before our read observed the break), close
        it and wait for its loop to settle FIRST — two loops feeding
        one queue would interleave frames and corrupt the ledgers."""
        edge = self.edges[wid]
        old = edge.conn
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
            edge.settled.wait(timeout=10.0)
        if gen != edge.gen and edge.gen >= 0:
            # reborn sender: report rows already delivered per
            # partition since its pinned epoch so it skips exactly
            # that prefix on replay
            base = {} if restore == 0 else edge.barrier_marks.get(restore)
            if base is None or not edge.counts_ok:
                counts, counts_ok = {}, False
            else:
                counts = {
                    p: edge.part_counts.get(p, 0) - base.get(p, 0)
                    for p in set(edge.part_counts) | set(base)
                }
                counts_ok = True
        else:
            counts, counts_ok = {}, True
        conn.sendall(framing.encode_resume(
            edge.gen, edge.frames_seen, self.last_commit, counts, counts_ok
        ))
        if gen != edge.gen:
            edge.gen = gen
            edge.frames_seen = 0
        edge.conn = conn
        edge.settled.clear()
        if edge.down:
            edge.down = False
            self._set_down_gauge()
        return edge

    def _recv_loop(self, conn: socket.socket) -> None:
        """Decode frames from one peer into its edge queue, maintaining
        the rejoin ledgers.  On an integrity/connectivity failure:
        under ``partial`` the edge is marked *down* and the loop exits
        — the merger keeps consuming the other edges and the queued
        prefix of this one until the sender redials; in fail-stop mode
        the failure is delivered IN-BAND as an ("err", exc) item and
        the merger re-raises on the consumer thread."""
        edge: EdgeState | None = None
        try:
            payload = framing.read_frame(conn)
            if payload is None:
                return  # peer connected and vanished before hello
            kind = framing.decode_frame(payload, self.schema)
            if kind[0] != "hello":
                raise SourceError(
                    f"exchange peer spoke {kind[0]!r} before hello"
                )
            edge = self._bind_conn(conn, kind[1], kind[2], kind[3])
            while not self._stop.is_set():
                faults.inject(
                    "exchange.recv",
                    key=f"{edge.edge_id}->{self.worker_id}",
                )
                payload = framing.read_frame(conn)
                if payload is None:
                    # clean EOF without an eos frame: the peer died —
                    # surface, never silently treat as end-of-partition
                    raise SourceError(
                        f"exchange edge {edge.edge_id}->{self.worker_id} "
                        "closed without EOS"
                    )
                if edge.conn is not conn:
                    return  # replaced by a newer connection
                item = framing.decode_frame(payload, self.schema)
                self._obs_frames.add(1)
                self._obs_bytes.add(len(payload))
                t = item[0]
                if t == "data":
                    _, batch, wm, part = item
                    if part is None:
                        edge.counts_ok = False
                    else:
                        edge.part_counts[part] = (
                            edge.part_counts.get(part, 0) + batch.num_rows
                        )
                    item = ("data", batch, wm)
                elif t == "barrier":
                    _, ep, skips = item
                    marks = dict(edge.part_counts)
                    for p, n in skips.items():
                        # the sender was mid-replay: n of this
                        # partition's delivered rows actually sit AT OR
                        # AFTER the barrier's stream position, so they
                        # don't belong in the epoch's baseline
                        marks[p] = marks.get(p, 0) - n
                    edge.barrier_marks[ep] = marks
                    item = ("barrier", ep)
                edge.frames_seen += 1
                if not edge.eos:
                    edge.queue.put(item)
                    edge.depth_gauge.set(edge.queue.qsize())
                    self.wake.set()
                # else: the edge already drained to EOS — a reborn
                # sender re-walking its stream can only produce frames
                # the skip ledger emptied (wm/barrier/eos), all of
                # which an EOS edge satisfies implicitly
                if t == "eos":
                    return
        except (SourceError, OSError) as e:
            if edge is not None:
                if self.partial:
                    edge.down = True
                    self._set_down_gauge()
                else:
                    edge.queue.put(("err", e))
                    self.wake.set()
            # hello never arrived: no edge to mark — the merger will
            # starve and the coordinator's liveness timeout recovers
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if edge is not None and edge.conn is conn:
                edge.conn = None
                edge.settled.set()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class EdgeMerger:
    """Single consumer over all inbound edges: data interleaves freely,
    watermarks merge as the min over live edges, barriers align, EOS
    collapses when unanimous.  Yields engine stream items — see
    :class:`~denormalized_tpu.cluster.runtime.ExchangeSourceExec` for
    where they enter the keyed pipeline."""

    def __init__(self, server: ExchangeServer) -> None:
        self.server = server
        self._merged_wm: int | None = None
        #: epochs ≤ this were aborted by the coordinator (a worker died
        #: with the barrier in flight) or already committed before this
        #: worker was (re)born — their markers must neither align nor
        #: overlap-check, whether they arrive late or via replay
        self.abort_floor = 0

    def abort_to(self, epoch: int) -> None:
        """Coordinator aborted the in-flight barrier ``epoch`` (it will
        never commit; the next barrier uses a FRESH number — epoch
        reuse is unsound because a peer may already hold a snapshot cut
        at the aborted number).  Any partial alignment unwinds: edges
        that already delivered the aborted marker resume consumption,
        and their post-marker rows simply belong to the next epoch's
        window."""
        self.abort_floor = max(self.abort_floor, int(epoch))
        self.server.wake.set()

    def _merged_watermark(self) -> int | None:
        """Min over non-EOS edges; an exhausted edge leaves the min
        (same rule as finished partitions in _PartitionWatermarks)."""
        live = [
            e.wm for e in self.server.edges.values() if not e.eos
        ]
        if not live or any(w is None for w in live):
            return None
        return min(live)

    def __iter__(self):
        """→ ("data", batch) | ("wm", ts) | ("barrier", epoch) | EOS (by
        StopIteration).  Runs on the keyed half's thread."""
        edges = list(self.server.edges.values())
        barrier_epoch: int | None = None
        while True:
            if barrier_epoch is not None and barrier_epoch <= self.abort_floor:
                # the in-flight barrier was aborted mid-alignment:
                # unwind the cut, resume consuming the aligned edges
                for x in edges:
                    x.aligned = False
                barrier_epoch = None
            progressed = False
            for e in edges:
                if e.eos or e.aligned:
                    continue
                try:
                    item = e.queue.get_nowait()
                except queue.Empty:
                    continue
                e.depth_gauge.set(e.queue.qsize())
                progressed = True
                t = item[0]
                if t == "err":
                    raise item[1]
                if t == "data":
                    _, batch, wm = item
                    if wm is not None and (e.wm is None or wm > e.wm):
                        e.wm = wm
                    yield ("data", batch)
                    merged = self._merged_watermark()
                    if merged is not None and (
                        self._merged_wm is None or merged > self._merged_wm
                    ):
                        self._merged_wm = merged
                        yield ("wm", merged)
                elif t == "wm":
                    if e.wm is None or item[1] > e.wm:
                        e.wm = item[1]
                    merged = self._merged_watermark()
                    if merged is not None and (
                        self._merged_wm is None or merged > self._merged_wm
                    ):
                        self._merged_wm = merged
                        yield ("wm", merged)
                elif t == "barrier":
                    if item[1] <= self.abort_floor:
                        continue  # aborted or stale-replayed marker
                    if barrier_epoch is not None and item[1] != barrier_epoch:
                        raise SourceError(
                            f"exchange barrier overlap: epoch {item[1]} "
                            f"arrived while {barrier_epoch} is aligning "
                            "(the coordinator issues barriers serially)"
                        )
                    barrier_epoch = item[1]
                    e.aligned = True
                elif t == "eos":
                    e.eos = True
                else:
                    raise SourceError(f"unknown exchange item {t!r}")
                # an EOS edge satisfies any in-flight barrier (its
                # sender persisted final offsets coordinator-side)
                if barrier_epoch is not None and all(
                    x.aligned or x.eos for x in edges
                ):
                    for x in edges:
                        x.aligned = False
                    ep, barrier_epoch = barrier_epoch, None
                    yield ("barrier", ep)
                if all(x.eos for x in edges):
                    return
            if not progressed:
                self.server.wake.wait(timeout=0.002)
                self.server.wake.clear()
